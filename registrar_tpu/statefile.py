"""On-disk session handoff state (ISSUE 5 tentpole).

The reference's only restart story is ``process.exit(1)`` + an SMF
restart — and every restart kills the ZooKeeper session, so the host's
ephemeral znodes vanish and Binder serves NXDOMAIN until the successor
re-registers: a self-inflicted DNS outage on every deploy.  ZooKeeper
itself never required that: a session is addressed by ``(session_id,
passwd)`` and survives any number of TCP connections, including
connections from *different processes*.  PR 3 taught the client to
reattach a live session in-process; this module carries the same trick
across a process boundary.

A handoff-mode daemon (config ``restart: {stateFile, mode: "handoff"}``)
keeps this file current — written on session establish, reattach,
rebirth, and every registration refresh, then once more with a fresh
stamp at SIGTERM — and the successor process reads it, seeds its
:class:`~registrar_tpu.zk.client.ZKClient` with the saved credentials,
reattaches the *same* session, and verifies (rather than re-creates) the
registration.  The ephemerals never flicker: a watching resolver sees
zero NO_NODE across the restart.

The file is the SESSION SECRET: anyone who reads it can adopt the
session and delete or replace the host's DNS records.  It is therefore
written ``0600`` via an fsynced atomic rename, must live on a path with
the same trust domain as the ZooKeeper ACL credentials (a root-owned
/var/run subdirectory, not /tmp), and a file owned by a different uid is
refused as foreign.

Every degraded shape falls back to today's fresh-session registration —
never to a crash:

  * unreadable / non-JSON / wrong-format ("foreign") file;
  * malformed fields, including a passwd that is not 16 bytes;
  * stale stamp: older than the negotiated session timeout, so the
    server has certainly expired the session already (the SIGKILL-crash
    shape — the predecessor could not refresh the stamp on its way out);
  * config-hash mismatch: the registration this state describes is not
    the registration this config would write;
  * a reattach the server refuses (``SESSION_EXPIRED``) — handled by the
    client's seeded-resume path, not here.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import itertools
import json
import logging
import os
import time
from dataclasses import dataclass
from typing import List, Optional

log = logging.getLogger("registrar_tpu.statefile")

#: format marker; anything else in the ``format`` field is a foreign file
FORMAT = "registrar-statefile-v1"

#: check_resumable() rejection reasons (stable strings: logged, tested,
#: and printed by ``zkcli state``)
R_STALE_STAMP = "staleStamp"
R_CONFIG_HASH = "configHash"

#: temp-file uniquifier (save() may run concurrently in worker threads)
_TMP_SEQ = itertools.count()


class StateFileError(Exception):
    """The state file cannot be used; ``reason`` is a stable slug."""

    def __init__(self, message: str, reason: str):
        super().__init__(message)
        self.reason = reason


class StateFileMissing(StateFileError):
    """No state file at the path (a normal cold start, not an error)."""

    def __init__(self, path: str):
        super().__init__(f"no state file at {path}", "missing")


class StateFileUnreadable(StateFileError):
    """The file exists but could not be read (permissions, I/O)."""

    def __init__(self, path: str, err: Exception):
        super().__init__(f"cannot read state file {path}: {err}", "unreadable")


class StateFileInvalid(StateFileError):
    """Foreign or corrupt content; ``reason`` names the first defect."""


@dataclass
class SessionState:
    """One handoff-able ZooKeeper session, as persisted.

    ``stamp`` is WALL-CLOCK (time.time()): it must be comparable across
    two different processes, which monotonic clocks are not.
    """

    session_id: int
    passwd: bytes
    negotiated_timeout_ms: int
    last_zxid: int
    chroot: str
    config_hash: str
    znodes: List[str]
    pid: int
    stamp: float


def config_fingerprint(
    registration, admin_ip: Optional[str], chroot: Optional[str]
) -> str:
    """Hash of everything that shapes the desired znode records.

    Two configs with the same fingerprint write byte-identical records at
    identical paths, so a verified resume under one is valid under the
    other.  Keys that do NOT shape the records (timeouts, healthCheck,
    metrics, the server list — a moved ensemble refuses the reattach on
    its own) are deliberately excluded: changing them must not force a
    re-registration blip across a restart.
    """
    digest = hashlib.sha256(
        json.dumps(
            {
                "registration": registration,
                "adminIp": admin_ip,
                "chroot": chroot or "",
            },
            sort_keys=True,
            separators=(",", ":"),
        ).encode()
    )
    return digest.hexdigest()


def save(path: str, state: SessionState) -> None:
    """Atomically persist ``state`` at ``path``, 0600, fsynced.

    Atomic (write-temp + rename) so a crash mid-write can never leave a
    truncated file the successor would half-parse; fsynced (file AND
    directory) so the rename survives a machine crash — a state file that
    points at a session is only useful if it is durably the *latest*
    one.  Raises OSError on failure (the caller logs and carries on: a
    broken statefile degrades the next restart to a fresh registration,
    it must never take down the running daemon).
    """
    # Imported here, not at module top: statefile is also consumed by
    # zkcli (a cold-start CLI path where pulling the tracing layer in
    # for a file inspection would be pure import weight).
    from registrar_tpu import trace

    with trace.get_tracer().span("statefile.save", path=path):
        _save_atomic(path, state)


def _save_atomic(path: str, state: SessionState) -> None:
    payload = json.dumps(
        {
            "format": FORMAT,
            "sessionId": f"0x{state.session_id:x}",
            "passwd": base64.b64encode(state.passwd).decode("ascii"),
            "negotiatedTimeoutMs": state.negotiated_timeout_ms,
            "lastZxid": state.last_zxid,
            "chroot": state.chroot,
            "configHash": state.config_hash,
            "znodes": list(state.znodes),
            "pid": state.pid,
            "stamp": state.stamp,
        },
        indent=2,
        sort_keys=True,
    ).encode()
    # pid + sequence: saves may run concurrently from worker threads of
    # one process (the daemon's background writes), and two writers
    # sharing a temp name would interleave into a corrupt file before
    # the rename.
    tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_SEQ)}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        try:
            os.write(fd, payload)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def _fsync_dir(dirname: str) -> None:
    try:
        dfd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # e.g. a platform/filesystem that refuses O_RDONLY on dirs
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


def clear(path: str) -> None:
    """Invalidate the state file (terminal expiry, clean drain).

    A session that is *known dead or closed* must not be offered to a
    successor: the reattach would be refused anyway, but fencing the file
    keeps a half-informed operator (or ``zkcli state``) from trusting it.
    """
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass


def load(path: str) -> SessionState:
    """Read and structurally validate a state file.

    Raises :class:`StateFileMissing` / :class:`StateFileUnreadable` /
    :class:`StateFileInvalid`; liveness (stamp age) and config matching
    are :func:`check_resumable`'s job — load answers only "is this a
    well-formed statefile of ours".
    """
    try:
        st = os.stat(path)
    except FileNotFoundError:
        raise StateFileMissing(path) from None
    except OSError as e:
        raise StateFileUnreadable(path, e) from e
    if hasattr(os, "getuid") and st.st_uid != os.getuid():
        # Not ours: a file another user planted at our configured path
        # could seed us with an attacker-chosen session.
        raise StateFileInvalid(
            f"state file {path} is owned by uid {st.st_uid}, not ours "
            f"({os.getuid()})", "foreign",
        )
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise StateFileUnreadable(path, e) from e
    try:
        raw = json.loads(text)
    except ValueError:
        raise StateFileInvalid(
            f"state file {path} is not JSON", "foreign"
        ) from None
    if not isinstance(raw, dict) or raw.get("format") != FORMAT:
        raise StateFileInvalid(
            f"state file {path} is not a {FORMAT} file", "foreign"
        )

    def field(name, types):
        value = raw.get(name)
        if not isinstance(value, types) or isinstance(value, bool):
            raise StateFileInvalid(
                f"state file {path}: bad field {name!r}", "malformed"
            )
        return value

    sid_text = field("sessionId", str)
    try:
        session_id = int(sid_text, 16)
    except ValueError:
        raise StateFileInvalid(
            f"state file {path}: bad field 'sessionId'", "malformed"
        ) from None
    try:
        passwd = base64.b64decode(field("passwd", str), validate=True)
    except (binascii.Error, ValueError):
        raise StateFileInvalid(
            f"state file {path}: passwd is not base64", "passwd"
        ) from None
    if len(passwd) != 16:
        # The wire protocol's session passwd is exactly 16 bytes; any
        # other length is a truncated/tampered file, and offering it to
        # the server would just burn a refused reattach.
        raise StateFileInvalid(
            f"state file {path}: passwd is {len(passwd)} bytes, not 16",
            "passwd",
        )
    znodes = field("znodes", list)
    if not all(isinstance(n, str) for n in znodes):
        raise StateFileInvalid(
            f"state file {path}: bad field 'znodes'", "malformed"
        )
    return SessionState(
        session_id=session_id,
        passwd=passwd,
        negotiated_timeout_ms=field("negotiatedTimeoutMs", int),
        last_zxid=field("lastZxid", int),
        chroot=field("chroot", str),
        config_hash=field("configHash", str),
        znodes=list(znodes),
        pid=field("pid", int),
        stamp=float(field("stamp", (int, float))),
    )


def check_resumable(
    state: SessionState,
    config_hash: str,
    now: Optional[float] = None,
) -> Optional[str]:
    """Is this state worth offering to the server?  None = yes, else the
    rejection reason (:data:`R_STALE_STAMP` / :data:`R_CONFIG_HASH`).

    The stamp check is a cheap local pre-filter, not the authority (the
    server's reattach verdict is): a stamp older than the negotiated
    session timeout means the session has certainly expired — the
    predecessor stopped refreshing it at least a full timeout ago — so
    skipping the doomed reattach saves the successor a round trip and a
    confusing refusal log.  A *fresh* stamp proves nothing (the server
    may have expired the session early); the refused-reattach fallback
    covers that.
    """
    if state.config_hash != config_hash:
        return R_CONFIG_HASH
    age = (time.time() if now is None else now) - state.stamp
    if age > state.negotiated_timeout_ms / 1000.0:
        return R_STALE_STAMP
    if age < 0 and abs(age) > state.negotiated_timeout_ms / 1000.0:
        # A stamp far in the future is a broken clock or a tampered
        # file; treat like staleness rather than trusting it forever.
        return R_STALE_STAMP
    return None
