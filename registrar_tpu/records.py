"""Service-discovery record construction — the ZooKeeper data contract.

This module is the single source of truth for the JSON payloads registrar
writes into ZooKeeper and the path-mapping scheme, i.e. the contract between
registrar and Binder (the DNS server that reads these records).

Contract sources in the reference (do not change without consulting both):
  * reference lib/register.js:34-39  (domainToPath)
  * reference lib/register.js:132-171 (host record construction)
  * reference lib/register.js:45-75  (service record construction)
  * reference README.md, section "ZooKeeper data format" (README.md:443-757)

Everything here is a pure function; serialization is deliberately pinned to
the reference's observable output: Node's ``JSON.stringify`` with no
whitespace, object keys in insertion order, ``undefined`` members omitted.
``payload_bytes`` reproduces that byte-for-byte (golden tests in
tests/test_records.py assert against the README examples).
"""

from __future__ import annotations

import json
import socket
from typing import Any, Iterable, Mapping, Optional

#: Host-record subtypes understood by Binder, with their semantics
#: (direct-query vs usable-under-a-service), per reference README.md:274-282.
#: The vestigial "database" type (historically written by Manatee) is
#: intentionally not listed; it is neither produced nor consumed any more.
HOST_RECORD_TYPES = {
    #  type           (queried directly?, usable for service?)
    "db_host": (True, False),
    "host": (True, False),
    "load_balancer": (True, True),
    "moray_host": (True, True),
    "ops_host": (False, True),
    "redis_host": (True, True),
    "rr_host": (False, True),
}

#: Default TTL (seconds) injected into the inner service object when the
#: configuration does not specify one (reference lib/register.js:197).
DEFAULT_SERVICE_TTL = 60


def domain_to_path(domain: str) -> str:
    """Map a DNS domain to its ZooKeeper path.

    The domain's labels are reversed, lowercased, and joined with "/":
    ``1.moray.us-east.joyent.com`` -> ``/com/joyent/us-east/moray/1``
    (reference lib/register.js:34-39, README.md:462-469).
    """
    if not isinstance(domain, str) or not domain:
        raise ValueError("domain must be a non-empty string")
    return "/" + "/".join(reversed(domain.lower().split(".")))


def path_to_domain(path: str) -> str:
    """Inverse of :func:`domain_to_path` (rebuild addition, used by tooling)."""
    parts = [p for p in path.split("/") if p]
    return ".".join(reversed(parts))


def default_address() -> str:
    """Pick this host's first non-loopback IPv4 address.

    Fallback used only when the configuration provides no ``adminIp``
    (reference lib/register.js:22-31); the reference README explicitly
    recommends always configuring ``adminIp`` instead (README.md:180-186).
    """
    # Ask the routing table which source address would be used for an
    # outbound packet; no traffic is actually sent for SOCK_DGRAM connect.
    # (Any routable destination works; RFC 5737 TEST-NET-3 address used.)
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("203.0.113.1", 9))
            addr = s.getsockname()[0]
            if addr and not addr.startswith("127."):
                return addr
    except OSError:
        pass
    # Last resort: resolve our own hostname.
    try:
        addr = socket.gethostbyname(socket.gethostname())
        if addr and not addr.startswith("127."):
            return addr
    except OSError:
        pass
    # Refuse to register a loopback address into DNS — remote clients would
    # connect to themselves.  (The reference crashes on this path too, via
    # addrs[0] of an empty array, lib/register.js:22-31.)
    raise RuntimeError(
        "no non-loopback IPv4 address found; configure adminIp explicitly"
    )


def host_record(
    rtype: str,
    address: str,
    ttl: Optional[int] = None,
    ports: Optional[Iterable[int]] = None,
) -> dict:
    """Build a host record (the payload of an ephemeral per-instance znode).

    Shape (reference lib/register.js:139-155, README.md:585-636)::

        {
          "type": <rtype>,
          "address": <ip>,          # top-level address: unused by Binder,
                                    # kept for wire compatibility
          "ttl": <int>,             # omitted when not configured
          <rtype>: {
            "address": <ip>,
            "ports": [<int>, ...]   # omitted when not configured
          }
        }

    Key order matters for byte-exact parity and matches the reference's
    object-literal insertion order.
    """
    if not isinstance(rtype, str) or not rtype:
        raise ValueError("record type must be a non-empty string")
    if rtype == "service":
        raise ValueError('"service" is not a host-record type')
    rec: dict = {"type": rtype, "address": address}
    if ttl is not None:
        rec["ttl"] = ttl
    inner: dict = {"address": address}
    if ports is not None:
        inner["ports"] = list(ports)
    rec[rtype] = inner
    return rec


def service_record(service: Mapping[str, Any]) -> dict:
    """Build a service record (the payload of the persistent domain znode).

    ``service`` is the validated ``registration.service`` object from the
    configuration; shape of the result (reference lib/register.js:58-61,
    README.md:638-678)::

        {
          "type": "service",
          "service": {
            "type": "service",
            "service": {"srvce": ..., "proto": ..., "port": ..., "ttl": ...},
            ...any additional configured members (e.g. an outer "ttl")...
          }
        }

    The inner ``service.service.ttl`` is defaulted to 60 when absent, exactly
    as the reference does during validation (lib/register.js:197) — the
    default is *appended* to the inner object so key order matches a config
    that did not specify it.
    """
    svc = _validate_service(service)
    return {"type": "service", "service": svc}


def _validate_service(service: Mapping[str, Any]) -> dict:
    """Validate + normalize a ``registration.service`` config object.

    Mirrors the reference's assert-plus schema (lib/register.js:188-200):
    ``type`` must be the string "service"; ``service.srvce`` and
    ``service.proto`` are required strings; ``service.port`` a required
    number; ``service.ttl`` an optional number defaulted to 60.  Returns a
    deep copy; never mutates the caller's config (the reference mutates it
    in place — a wart, not contract).
    """
    if not isinstance(service, Mapping):
        raise ValueError("registration.service must be an object")
    if service.get("type") != "service":
        raise ValueError('registration.service.type must be "service"')
    inner = service.get("service")
    if not isinstance(inner, Mapping):
        raise ValueError("registration.service.service must be an object")
    if not isinstance(inner.get("srvce"), str):
        raise ValueError("registration.service.service.srvce must be a string")
    if not isinstance(inner.get("proto"), str):
        raise ValueError("registration.service.service.proto must be a string")
    if not isinstance(inner.get("port"), (int, float)) or isinstance(
        inner.get("port"), bool
    ):
        raise ValueError("registration.service.service.port must be a number")
    # Explicit null is rejected, matching the reference's assert-plus
    # optionalNumber (which only tolerates an *absent* member).
    if "ttl" in inner and (
        not isinstance(inner["ttl"], (int, float)) or isinstance(inner["ttl"], bool)
    ):
        raise ValueError("registration.service.service.ttl must be a number")

    svc = {k: (dict(v) if isinstance(v, Mapping) else v) for k, v in service.items()}
    if "ttl" not in svc["service"]:
        svc["service"]["ttl"] = DEFAULT_SERVICE_TTL
    return svc


def payload_bytes(record: Mapping[str, Any]) -> bytes:
    """Serialize a record exactly as the reference stack does.

    zkplus writes ``JSON.stringify(obj)``: UTF-8, no whitespace, insertion
    key order.  ``json.dumps`` with compact separators over Python's
    order-preserving dicts reproduces this byte-for-byte.
    """
    return json.dumps(record, separators=(",", ":"), ensure_ascii=False).encode("utf-8")


def parse_payload(data: bytes) -> Any:
    """Parse a znode payload written by registrar (or by the reference)."""
    return json.loads(data.decode("utf-8"))
