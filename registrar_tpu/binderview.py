"""Binder's view of the records: resolve DNS names from ZooKeeper state.

Binder (the DNS server, a separate repository) is the sole consumer of the
records registrar writes; its behavior is specified in the reference's
README ("ZooKeeper data format", README.md:443-757, and the host-record
type table at README.md:274-282).  This module implements that documented
resolution logic over our ZK client.  It is not a DNS server — it exists
so tests and operators can validate, end to end, that what registrar wrote
resolves to exactly the answers Binder would serve:

  * host-record lookups (``$zonename.$domain``) — A answers for the
    directly-queryable types only (``ops_host``/``rr_host`` resolve as if
    absent, README.md:284-287);
  * service lookups (``$domain``) — the children of the service node,
    filtered to the usable-under-service types (``db_host``/``host``
    excluded, README.md:289-293);
  * SRV lookups (``_svc._proto.$domain``) — one SRV per port per instance
    with A additionals, exactly the dig output shown at README.md:421-424;
  * the TTL precedence chains from "About TTLs" (README.md:680-757).

Used by the ``resolve`` subcommand of the zkcli operator tool and by
tests/test_binderview.py (which pins the README's worked dig examples).

Read source: every function takes any object exposing the two-call read
surface ``read_node(path)`` / ``get_many(paths)`` — a
:class:`~registrar_tpu.zk.client.ZKClient` (live reads; the record get
and children listing ride ONE pipelined flush, so an uncached resolve
costs two round trips, not three) or a
:class:`~registrar_tpu.zkcache.ZKCache` (watch-coherent memory; a warm
resolve touches the server zero times — the ``zkcli resolve --cached``
/ ``serve-view`` hot path, ISSUE 4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache
from typing import Any, Dict, List, Optional

from registrar_tpu import trace
from registrar_tpu.records import (
    HOST_RECORD_TYPES,
    domain_to_path,
    parse_payload,
)

#: Binder's fallback TTL when no record supplies one (typical deploys use
#: 30 s answers, reference README.md:87-89).
DEFAULT_TTL = 30

#: SRV priority/weight are fixed — "DNS SRV records also support weights,
#: but these are not supported by Registrar or Binder" (README.md:678).
SRV_PRIORITY = 0
SRV_WEIGHT = 10


@dataclass
class Answer:
    """One DNS answer (shape mirrors dig output lines)."""

    name: str
    rtype: str  # "A" | "SRV" | "TXT"
    ttl: int
    #: A: the IPv4 address.  SRV: "<prio> <weight> <port> <target>".
    data: str

    def __str__(self) -> str:
        return f"{self.name}. {self.ttl} IN {self.rtype} {self.data}"


@dataclass
class Resolution:
    answers: List[Answer] = field(default_factory=list)
    additionals: List[Answer] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.answers

    def to_wire_records(self):
        """Render to DNS wire form: ``(answers, additionals)`` as
        ``(name, type_code, ttl, rdata_bytes)`` tuples.  THE one RR
        renderer — the dnsfront encode cache and ``zkcli dig`` both
        come through here (the ``registration_payloads`` precedent:
        one stable hook instead of two drifting copies)."""
        from registrar_tpu import dnsfront

        return dnsfront.wire_records(self)


def _host_ttl(record: Dict[str, Any]) -> int:
    """A-record TTL for a host record: inner ttl, then top-level ttl
    (README.md:692-697)."""
    inner = record.get(record.get("type"), {})
    if isinstance(inner, dict) and isinstance(inner.get("ttl"), int):
        return inner["ttl"]
    if isinstance(record.get("ttl"), int):
        return record["ttl"]
    return DEFAULT_TTL


def _service_ttl(record: Dict[str, Any]) -> int:
    """SRV TTL for a service record: service.service.ttl, then service.ttl,
    then top-level ttl (README.md:744-750)."""
    svc = record.get("service")
    if isinstance(svc, dict):
        inner = svc.get("service")
        if isinstance(inner, dict) and isinstance(inner.get("ttl"), int):
            return inner["ttl"]
        if isinstance(svc.get("ttl"), int):
            return svc["ttl"]
    if isinstance(record.get("ttl"), int):
        return record["ttl"]
    return DEFAULT_TTL


@lru_cache(maxsize=8192)
def _record_from_bytes(data: bytes) -> Optional[Dict[str, Any]]:
    """Parse a znode payload into a record dict (None when unusable).

    Memoized on the payload bytes: the watch-coherent cache serves the
    same payload object for every warm resolve, and re-running
    ``json.loads`` over 50 instance records per DNS answer would
    dominate the in-memory hot path.  Consumers treat the returned dict
    as immutable (every reader here only ``.get``s); a changed record
    arrives as fresh bytes and misses the memo.
    """
    if not data:
        return None
    try:
        record = parse_payload(data)
    except ValueError:
        return None
    return record if isinstance(record, dict) else None


def _queryable_directly(rtype: str) -> bool:
    entry = HOST_RECORD_TYPES.get(rtype)
    return bool(entry and entry[0])


def _usable_for_service(rtype: str) -> bool:
    entry = HOST_RECORD_TYPES.get(rtype)
    return bool(entry and entry[1])


def _host_address(record: Dict[str, Any]) -> Optional[str]:
    inner = record.get(record.get("type"), {})
    if isinstance(inner, dict) and isinstance(inner.get("address"), str):
        return inner["address"]
    return None


async def _service_instances(src, path: str, children: List[str]):
    """Fetch the usable child host records of a service node (one
    pipelined getData burst — one write and one reply sweep, not N
    task-scheduled round-trips; zero round trips from a warm cache)."""
    replies = await src.get_many(f"{path}/{child}" for child in children)
    records = [
        None if reply is None else _record_from_bytes(reply[0])
        for reply in replies
    ]
    instances = []
    for child, rec in zip(children, records):
        if rec is None or rec.get("type") == "service":
            continue
        if not _usable_for_service(rec.get("type", "")):
            continue
        addr = _host_address(rec)
        if addr is None:
            continue
        instances.append((child, rec, addr))
    return instances


async def resolve_a(src, name: str) -> Resolution:
    """Answer an A query for ``name`` the way Binder would."""
    name = name.rstrip(".").lower()
    path = domain_to_path(name)
    node = await src.read_node(path)
    res = Resolution()
    if node is None:
        return res
    data, _stat, children = node
    record = _record_from_bytes(data)
    if record is None:
        return res

    rtype = record.get("type")
    if rtype != "service":
        # Direct host-record lookup (README.md:547-552).
        if not _queryable_directly(rtype or ""):
            return res  # behaves as though it weren't there (README:284-287)
        addr = _host_address(record)
        if addr is not None:
            res.answers.append(Answer(name, "A", _host_ttl(record), addr))
        return res

    # Service lookup: one A per usable instance (README.md:522-534); the
    # A TTL is min(service-chain TTL, host-record TTL) (README.md:752-757).
    svc_ttl = _service_ttl(record)
    for _child, rec, addr in await _service_instances(src, path, children):
        res.answers.append(Answer(name, "A", min(svc_ttl, _host_ttl(rec)), addr))
    return res


async def resolve_srv(src, name: str) -> Resolution:
    """Answer an SRV query (``_service._proto.domain``) the way Binder would.

    Produces one SRV per port per instance plus A additionals for the
    instance names (README.md:406-424).
    """
    name = name.rstrip(".").lower()
    labels = name.split(".")
    res = Resolution()
    if len(labels) < 3 or not (
        labels[0].startswith("_") and labels[1].startswith("_")
    ):
        return res
    srvce, proto = labels[0], labels[1]
    domain = ".".join(labels[2:])
    path = domain_to_path(domain)
    node = await src.read_node(path)
    if node is None:
        return res
    data, _stat, children = node
    record = _record_from_bytes(data)
    if record is None or record.get("type") != "service":
        return res
    svc = record.get("service", {})
    inner = svc.get("service", {}) if isinstance(svc, dict) else {}
    if not isinstance(inner, dict):
        return res  # malformed record: resolve as absent, don't crash
    if inner.get("srvce") != srvce or inner.get("proto") != proto:
        return res

    svc_ttl = _service_ttl(record)
    default_port = inner.get("port")
    for child, rec, addr in await _service_instances(src, path, children):
        target = f"{child}.{domain}"
        rec_inner = rec.get(rec.get("type"), {})
        ports = rec_inner.get("ports") if isinstance(rec_inner, dict) else None
        if not isinstance(ports, list) or not ports:
            # "port to use for SRV answers when a child host record does
            # not contain its own array of ports" (README.md:370-372)
            ports = [default_port] if default_port is not None else []
        if not ports:
            continue  # no SRV answers for this instance -> no orphan A
        for port in ports:
            res.answers.append(
                Answer(
                    name, "SRV", svc_ttl,
                    f"{SRV_PRIORITY} {SRV_WEIGHT} {port} {target}.",
                )
            )
        res.additionals.append(Answer(target, "A", _host_ttl(rec), addr))
    return res


async def resolve_txt(src, name: str) -> Resolution:
    """Answer a TXT query for ``name``.

    Rebuild extension (the reference Binder serves TXT from the same
    records; our subset): a node that exists and parses answers one TXT
    string ``registrar-type=<type>`` — the operator-facing "what kind
    of record is actually behind this name" probe `zkcli dig -t TXT`
    uses.  TTL follows the host chain (top-level ttl, else default).
    """
    name = name.rstrip(".").lower()
    node = await src.read_node(domain_to_path(name))
    res = Resolution()
    if node is None:
        return res
    record = _record_from_bytes(node[0])
    if record is None or not isinstance(record.get("type"), str):
        return res
    ttl = record["ttl"] if isinstance(record.get("ttl"), int) else DEFAULT_TTL
    res.answers.append(
        Answer(name, "TXT", ttl, f"registrar-type={record['type']}")
    )
    return res


async def resolve(src, name: str, qtype: str = "A") -> Resolution:
    """Resolve ``name`` for query type ``qtype`` ("A", "SRV" or "TXT").

    ``src`` is the read source: a connected
    :class:`~registrar_tpu.zk.client.ZKClient` for live answers, or a
    :class:`~registrar_tpu.zkcache.ZKCache` for the in-memory hot path.
    """
    qtype = qtype.upper()
    if qtype not in ("A", "SRV", "TXT"):
        raise ValueError(f"unsupported query type: {qtype}")
    # source: "cached" only while a ZKCache is actually serving from
    # memory (a degraded cache falls through to live reads and is
    # honestly labeled "live"); a plain ZKClient has no `authoritative`
    # and always reads live.
    with trace.tracer_for(src).span(
        "resolve.query",
        qtype=qtype,
        source=(
            "cached" if getattr(src, "authoritative", False) else "live"
        ),
    ):
        if qtype == "A":
            return await resolve_a(src, name)
        if qtype == "TXT":
            return await resolve_txt(src, name)
        return await resolve_srv(src, name)
