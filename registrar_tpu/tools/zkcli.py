"""Operator CLI for inspecting service-discovery state in ZooKeeper.

The reference's debugging docs tell operators to poke at znodes with
ZooKeeper's ``zkCli.sh`` (README.md "Debugging Notes"); this ships the
equivalent, plus a ``resolve`` command that answers exactly as Binder
would (see :mod:`registrar_tpu.binderview`), so "what will DNS say?" is
one command instead of manual tree-walking::

    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 ls /us/joyent
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 get /us/joyent/emy-10/authcache
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 stat /us/joyent/emy-10/authcache
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 tree /us
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 rm /us/joyent/emy-10/stale
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 resolve authcache.emy-10.joyent.us
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 resolve -t SRV _http._tcp.example.joyent.us
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 admin ruok
    python -m registrar_tpu.tools.zkcli verify -f /opt/registrar/etc/config.json
    python -m registrar_tpu.tools.zkcli state /var/run/registrar/state.json
    python -m registrar_tpu.tools.zkcli drain -f /opt/registrar/etc/config.json
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 getacl /us/joyent
    python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181 --auth digest:ops:pw \
        setacl /us/joyent/locked digest:ops:HASH:cdrwa world:anyone:r

With no command, zkcli enters an interactive prompt running the same
commands over ONE ZooKeeper session — the ``zkCli.sh -server`` workflow
the reference's debugging notes teach (reference README.md:785-807)::

    $ python -m registrar_tpu.tools.zkcli -s 127.0.0.1:2181
    zkcli> ls /us/joyent/emy-10
    zkcli> get /us/joyent/emy-10/authcache
    zkcli> addauth digest:ops:pw
    zkcli> quit

Extra prompt-only commands: ``addauth SCHEME:CRED`` (authenticate the
live session), ``help``, ``quit``/``exit``; ``#`` starts a comment.
Because the session persists between commands, ``create -e`` ephemerals
live until the prompt exits — handy for rehearsing registrar failover.

Exit status: 0 on success, 1 on ZK errors (e.g. no such node), 2 on usage.
``verify`` refines this into its audit contract: 0 in-sync, 1 drift
detected, 2 unreachable — cron- and runbook-friendly (ISSUE 3 satellite).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from typing import List, Optional, Tuple

from registrar_tpu import binderview
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.quota import (
    LIMITS_LEAF,
    QUOTA_ROOT,
    STATS_LEAF,
    format_quota,
    parse_quota,
)
from registrar_tpu.zk.protocol import (
    ACL,
    CreateFlag,
    Err,
    EventType,
    Perms,
    Stat,
    ZKError,
)

#: perm letter <-> bit, in zkCli.sh's display order (cdrwa)
_PERM_LETTERS = [
    ("c", Perms.CREATE),
    ("d", Perms.DELETE),
    ("r", Perms.READ),
    ("w", Perms.WRITE),
    ("a", Perms.ADMIN),
]


def _fmt_perms(perms: int) -> str:
    return "".join(ch for ch, bit in _PERM_LETTERS if perms & bit)


def _parse_acl(spec: str) -> ACL:
    """Parse ``scheme:id:perms`` (id may itself contain colons, e.g. a
    digest ``user:hash`` — the *last* segment is always the perm letters)."""
    scheme, _, rest = spec.partition(":")
    ident, _, perm_str = rest.rpartition(":")
    if not scheme or not perm_str:
        raise argparse.ArgumentTypeError(
            f"expected scheme:id:perms (e.g. world:anyone:cdrwa), got {spec!r}"
        )
    perms = 0
    for ch in perm_str:
        for letter, bit in _PERM_LETTERS:
            if ch == letter:
                perms |= bit
                break
        else:
            raise argparse.ArgumentTypeError(
                f"bad perm letter {ch!r} in {spec!r} (use [cdrwa])"
            )
    return ACL(perms=perms, scheme=scheme, id=ident)


def _parse_auth(value: str) -> Tuple[str, bytes]:
    scheme, sep, cred = value.partition(":")
    if not scheme or not sep:
        raise argparse.ArgumentTypeError(
            f"expected scheme:credential (e.g. digest:user:pass), got {value!r}"
        )
    return (scheme, cred.encode())


def _parse_servers(value: str) -> List[Tuple[str, int]]:
    servers = []
    for part in value.split(","):
        host, _, port = part.strip().rpartition(":")
        if not host:
            raise argparse.ArgumentTypeError(
                f"expected host:port[,host:port...], got {value!r}"
            )
        try:
            servers.append((host, int(port)))
        except ValueError:
            raise argparse.ArgumentTypeError(f"bad port in {part!r}")
    return servers


def _fmt_stat(stat: Stat) -> str:
    lines = [
        f"czxid = 0x{stat.czxid:x}",
        f"mzxid = 0x{stat.mzxid:x}",
        f"ctime = {stat.ctime}",
        f"mtime = {stat.mtime}",
        f"version = {stat.version}",
        f"cversion = {stat.cversion}",
        f"ephemeralOwner = 0x{stat.ephemeral_owner:x}",
        f"dataLength = {stat.data_length}",
        f"numChildren = {stat.num_children}",
        f"pzxid = 0x{stat.pzxid:x}",
    ]
    return "\n".join(lines)


async def _cmd_ls(zk: ZKClient, args) -> int:
    for child in await zk.get_children(args.path):
        print(child)
    return 0


async def _cmd_get(zk: ZKClient, args) -> int:
    data, _ = await zk.get(args.path)
    if not data:
        return 0
    try:
        print(json.dumps(json.loads(data), indent=2 if args.pretty else None,
                         separators=None if args.pretty else (",", ":")))
    except ValueError:
        sys.stdout.buffer.write(data + b"\n")
    return 0


async def _cmd_stat(zk: ZKClient, args) -> int:
    print(_fmt_stat(await zk.stat(args.path)))
    return 0


async def _cmd_tree(zk: ZKClient, args) -> int:
    async def walk(path: str, depth: int) -> None:
        name = path.rsplit("/", 1)[-1] or "/"
        data, stat = await zk.get(path)
        suffix = ""
        if stat.ephemeral_owner:
            suffix += f"  [ephemeral 0x{stat.ephemeral_owner:x}]"
        if data:
            body = data.decode("utf-8", errors="replace")
            if len(body) > 60:
                body = body[:57] + "..."
            suffix += f"  {body}"
        print("  " * depth + name + suffix)
        for child in await zk.get_children(path):
            base = path.rstrip("/")
            await walk(f"{base}/{child}", depth + 1)

    await walk(args.path, 0)
    return 0


async def _cmd_rm(zk: ZKClient, args) -> int:
    await zk.unlink(args.path, version=args.version)
    return 0


async def _cmd_watch(zk: ZKClient, args) -> int:
    """Stream change events for a path (data + children) until interrupted."""
    names = {
        EventType.NODE_CREATED: "created",
        EventType.NODE_DELETED: "deleted",
        EventType.NODE_DATA_CHANGED: "dataChanged",
        EventType.NODE_CHILDREN_CHANGED: "childrenChanged",
    }
    queue: asyncio.Queue = asyncio.Queue()
    zk.watch(args.path, queue.put_nowait)

    async def arm() -> None:
        # NO_NODE is fine (the exist-watch fires on creation); anything
        # else — above all CONNECTION_LOSS, since this client does not
        # reconnect — must surface rather than leave a silent dead watch.
        try:
            await zk.stat(args.path, watch=True)
        except ZKError as err:
            if err.code != Err.NO_NODE:
                raise
        try:
            await zk.get_children(args.path, watch=True)
        except ZKError as err:
            if err.code != Err.NO_NODE:
                raise

    await arm()
    print(f"watching {args.path} (ctrl-C to stop)", file=sys.stderr)
    deadline = asyncio.get_running_loop().time() + args.duration
    while True:
        remaining = deadline - asyncio.get_running_loop().time()
        if args.duration and remaining <= 0:
            return 0
        try:
            ev = await asyncio.wait_for(
                queue.get(), timeout=remaining if args.duration else None
            )
        except asyncio.TimeoutError:
            return 0
        print(f"{names.get(ev.type, ev.type)} {ev.path}", flush=True)
        await arm()  # watches are one-shot; re-arm


async def _cmd_create(zk: ZKClient, args) -> int:
    flags = CreateFlag.PERSISTENT
    if args.ephemeral and args.sequential:
        flags = CreateFlag.EPHEMERAL_SEQUENTIAL
    elif args.ephemeral:
        flags = CreateFlag.EPHEMERAL
    elif args.sequential:
        flags = CreateFlag.PERSISTENT_SEQUENTIAL
    path = await zk.create(
        args.path, args.data.encode(), flags,
        acls=args.acl if args.acl else None,
    )
    print(path)
    if args.ephemeral and not getattr(args, "repl", False):
        # In one-shot mode an ephemeral dies with this CLI's session the
        # moment we exit — only useful for watching the effect from
        # another session.  At the interactive prompt the session (and
        # so the node) lives until 'quit', so no warning there.
        print(
            "zkcli: note: ephemeral node is deleted when this command's "
            "session closes (now)",
            file=sys.stderr,
        )
    return 0


async def _cmd_set(zk: ZKClient, args) -> int:
    if args.version != -1:
        # Conditional set is a plain setData (no create-if-missing
        # fallback: an expected version implies the node exists).
        stat = await zk.set_data(
            args.path, args.data.encode(), version=args.version
        )
    else:
        stat = await zk.put(args.path, args.data.encode())
    print(f"version = {stat.version}")
    return 0


async def _cmd_mkdirp(zk: ZKClient, args) -> int:
    await zk.mkdirp(args.path)
    print(args.path)
    return 0


async def _cmd_rmr(zk: ZKClient, args) -> int:
    """Recursive delete, children first (zkCli.sh ``rmr``/``deleteall``).

    Not atomic: concurrent writers can race the walk.  A node that gained
    a child between listing and delete is re-walked (bounded retries), and
    nodes that vanished underneath us (ephemeral expiry) are fine.
    """
    deleted = 0

    async def walk(path: str, retries: int = 5) -> None:
        nonlocal deleted
        try:
            children = await zk.get_children(path)
        except ZKError as e:
            if e.code == Err.NO_NODE:
                return
            raise
        for child in children:
            await walk(f"{path}/{child}" if path != "/" else f"/{child}")
        try:
            await zk.unlink(path)
            deleted += 1
        except ZKError as e:
            if e.code == Err.NO_NODE:  # raced with an ephemeral expiry: fine
                return
            if e.code == Err.NOT_EMPTY and retries > 0:
                # A writer added a child after we listed; re-walk.
                await walk(path, retries - 1)
                return
            raise

    if args.path == "/":
        print("zkcli: refusing to delete /", file=sys.stderr)
        return 1
    await walk(args.path)
    print(f"deleted {deleted} node(s)")
    return 0


async def _quota_conflict(zk: ZKClient, path: str) -> "str | None":
    """A quota may not nest inside another (zkCli.sh refuses both
    directions).  Returns the conflicting target path, if any."""
    # Ancestor (or self) already quota'd?
    comps = path.strip("/").split("/")
    prefix = ""
    for comp in comps:
        prefix += "/" + comp
        if await zk.exists(f"{QUOTA_ROOT}{prefix}/{LIMITS_LEAF}"):
            return prefix

    # Descendant already quota'd?
    async def walk(qpath: str, target: str) -> "str | None":
        try:
            children = await zk.get_children(qpath)
        except ZKError as e:
            if e.code == Err.NO_NODE:
                return None
            raise
        for child in children:
            if child == LIMITS_LEAF and qpath != f"{QUOTA_ROOT}{path}":
                return target
            if child in (LIMITS_LEAF, STATS_LEAF):
                continue
            found = await walk(f"{qpath}/{child}", f"{target}/{child}")
            if found:
                return found
        return None

    return await walk(f"{QUOTA_ROOT}{path}", path)


async def _cmd_setquota(zk: ZKClient, args) -> int:
    """zkCli.sh ``setquota -n N | -b B path`` (soft limits: the server
    logs violations, it never rejects writes)."""
    if args.count is None and args.bytes is None:
        print("zkcli: setquota needs -n COUNT and/or -b BYTES", file=sys.stderr)
        return 2
    conflict = await _quota_conflict(zk, args.path)
    if conflict and conflict != args.path:
        print(
            f"zkcli: {conflict} already has a quota; nested quotas are not "
            "allowed", file=sys.stderr,
        )
        return 1
    limits_path = f"{QUOTA_ROOT}{args.path}/{LIMITS_LEAF}"
    stats_path = f"{QUOTA_ROOT}{args.path}/{STATS_LEAF}"
    existing = await zk.exists(limits_path)
    quota = {"count": -1, "bytes": -1}
    if existing:
        data, _ = await zk.get(limits_path)
        quota = parse_quota(data)
    if args.count is not None:
        quota["count"] = args.count
    if args.bytes is not None:
        quota["bytes"] = args.bytes
    await zk.mkdirp(f"{QUOTA_ROOT}{args.path}")
    await zk.put(limits_path, format_quota(quota["count"], quota["bytes"]))
    if not await zk.exists(stats_path):
        await zk.put(stats_path, format_quota(0, 0))
    print(f"quota for {args.path}: count={quota['count']},bytes={quota['bytes']}")
    return 0


async def _cmd_listquota(zk: ZKClient, args) -> int:
    """zkCli.sh ``listquota path``: the limit and the live usage."""
    limits_path = f"{QUOTA_ROOT}{args.path}/{LIMITS_LEAF}"
    try:
        data, _ = await zk.get(limits_path)
    except ZKError as e:
        if e.code == Err.NO_NODE:
            print(f"quota for {args.path} does not exist")
            return 1
        raise
    print(f"absolute path is {limits_path}")
    quota = parse_quota(data)
    print(f"Output quota for {args.path} "
          f"count={quota['count']},bytes={quota['bytes']}")
    stats, _ = await zk.get(f"{QUOTA_ROOT}{args.path}/{STATS_LEAF}")
    usage = parse_quota(stats)
    print(f"Output stat for {args.path} "
          f"count={usage['count']},bytes={usage['bytes']}")
    return 0


async def _cmd_delquota(zk: ZKClient, args) -> int:
    """zkCli.sh ``delquota [-n|-b] path``: clear one limit dimension, or
    the whole quota when no flag is given."""
    limits_path = f"{QUOTA_ROOT}{args.path}/{LIMITS_LEAF}"
    if args.count or args.bytes:
        try:
            data, _ = await zk.get(limits_path)
        except ZKError as e:
            if e.code == Err.NO_NODE:
                print(f"quota for {args.path} does not exist", file=sys.stderr)
                return 1
            raise
        quota = parse_quota(data)
        if args.count:
            quota["count"] = -1
        if args.bytes:
            quota["bytes"] = -1
        await zk.put(limits_path, format_quota(quota["count"], quota["bytes"]))
        print(f"quota for {args.path}: "
              f"count={quota['count']},bytes={quota['bytes']}")
        return 0
    for leaf in (LIMITS_LEAF, STATS_LEAF):
        try:
            await zk.unlink(f"{QUOTA_ROOT}{args.path}/{leaf}")
        except ZKError as e:
            if e.code != Err.NO_NODE:
                raise
    try:
        await zk.unlink(f"{QUOTA_ROOT}{args.path}")
    except ZKError as e:
        if e.code not in (Err.NO_NODE, Err.NOT_EMPTY):
            raise
    print(f"quota for {args.path} deleted")
    return 0


async def _cmd_admin(args) -> int:
    """Send a 4-letter-word admin command to every server, raw TCP.

    These are connection-less health probes (no ZK session), answered by
    real ZooKeeper and by the in-process test server alike — `ruok` is the
    standard "is this ensemble member alive" check in operator runbooks.
    """
    failures = 0
    for host, port in args.servers:
        if len(args.servers) > 1:
            print(f";; {host}:{port}")
        writer = None
        text = None
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), timeout=5
            )
            writer.write(args.word.encode("ascii"))
            await writer.drain()
            # The server closes the connection after answering; read to EOF
            # (a single read() can return one TCP segment of a longer
            # mntr/dump response).
            out = await asyncio.wait_for(reader.read(), timeout=5)
            text = out.decode(errors="replace").rstrip("\n")
        except (OSError, asyncio.TimeoutError) as e:
            # Includes server-socket EPIPE/reset: a failed probe, counted.
            print(f"zkcli: {host}:{port}: {e!r}", file=sys.stderr)
            failures += 1
        finally:
            if writer is not None:
                writer.close()
                try:
                    await writer.wait_closed()
                except (OSError, asyncio.TimeoutError):
                    pass
        if text is not None:
            # Outside the network try: a BrokenPipeError here is *stdout*
            # going away (piped into head/grep that exited), which main()
            # treats as a clean exit — not a probe failure.
            print(text)
    return 1 if failures else 0


async def _cmd_sync(zk: ZKClient, args) -> int:
    """Read barrier: flush the server's commit pipeline for a path."""
    print(await zk.sync(args.path))
    return 0


async def _cmd_getacl(zk: ZKClient, args) -> int:
    """Print a node's ACL list in zkCli.sh's getAcl format."""
    acls, stat = await zk.get_acl(args.path)
    for acl in acls:
        print(f"'{acl.scheme},'{acl.id}")
        print(f": {_fmt_perms(acl.perms)}")
    print(f"aversion = {stat.aversion}")
    return 0


async def _cmd_setacl(zk: ZKClient, args) -> int:
    stat = await zk.set_acl(args.path, args.acl, version=args.version)
    print(f"aversion = {stat.aversion}")
    return 0


async def _config_session(args, what: str):
    """Load ``-f CONFIG`` and open one bounded, non-reconnecting session
    per its own ``zookeeper`` block — the shared scaffolding of every
    config-driven command (``verify``, ``drain``), so the connect/timeout
    envelope can never drift between them.

    Returns ``(cfg, zk)`` with the session connected, or ``None`` after
    printing the error (the caller exits 2: the command could not run).
    The per-operation deadline honors the config's own
    ``zookeeper.requestTimeout``, else derives one from ``--timeout`` —
    a server that accepts the handshake and then stalls replies must
    make the command exit 2, never hang a cron job forever.
    """
    from registrar_tpu.config import ConfigError, load_config

    try:
        cfg = load_config(args.file)
    except ConfigError as e:
        print(f"zkcli: {what}: {e}", file=sys.stderr)
        return None
    zk = ZKClient(
        cfg.zookeeper.servers,
        timeout_ms=cfg.zookeeper.timeout_ms,
        connect_timeout_ms=cfg.zookeeper.connect_timeout_ms,
        chroot=cfg.zookeeper.chroot,
        reconnect=False,
        request_timeout_ms=(
            cfg.zookeeper.request_timeout_ms
            if cfg.zookeeper.request_timeout_ms is not None
            else max(int(args.timeout * 1000), 1)
        ),
        # Honor the config's read-only opt-in (ISSUE 10): an audit
        # (`verify`) must still answer during quorum loss — reads work
        # on a read-only member; a drain's deletes fail truthfully.
        can_be_read_only=cfg.zookeeper.can_be_read_only,
    )
    try:
        await asyncio.wait_for(zk.connect(), timeout=args.timeout)
    except Exception as e:  # noqa: BLE001 - probe failure, not a bug
        await zk.close()
        print(
            f"zkcli: {what}: cannot connect to "
            f"{cfg.zookeeper.servers}: {e!r}", file=sys.stderr,
        )
        return None
    return cfg, zk


async def _cmd_verify(args) -> int:
    """Read-only drift audit: diff live ZooKeeper state against a
    registrar config's desired records (the reconciler's sweep,
    :func:`registrar_tpu.reconcile.audit`).

    Exit status is the cron/runbook contract: 0 in-sync, 1 drift
    detected, 2 unreachable (ensemble down, or the config itself
    unreadable/invalid — either way the audit could not run).  Connects
    with the config's own ``zookeeper`` block (servers, chroot), not the
    ``-s`` flag, so the audit sees exactly what the daemon would.
    """
    from registrar_tpu import reconcile

    session = await _config_session(args, "verify")
    if session is None:
        return 2
    cfg, zk = session
    try:
        try:
            drifts = await reconcile.audit(
                zk, cfg.registration,
                admin_ip=cfg.admin_ip, hostname=args.hostname,
            )
        except (ZKError, ConnectionError, OSError, ValueError) as e:
            print(f"zkcli: verify: audit failed: {e}", file=sys.stderr)
            return 2
    finally:
        await zk.close()
    if not drifts:
        print(f"in sync: {args.file} matches the live ensemble")
        return 0
    for d in drifts:
        detail = f"  ({d.detail})" if d.detail else ""
        print(f"drift: {d.reason}  {d.path}{detail}")
    rollup = ", ".join(
        f"{reason}={count}"
        for reason, count in sorted(reconcile.summarize(drifts).items())
    )
    print(f"{len(drifts)} drift(s): {rollup}", file=sys.stderr)
    return 1


async def _cmd_state(args) -> int:
    """Inspect a registrar handoff state file (``restart.stateFile``).

    Prints every persisted field plus a resumability verdict: would a
    successor starting NOW (optionally with ``--config``'s fingerprint)
    attempt the session resume, or fall back to a fresh registration —
    and why.  Exit 0 = resumable, 1 = not resumable, 2 = unreadable.
    Local file inspection only; no ZooKeeper connection is made (the
    server's reattach verdict is the final authority either way).
    """
    import time as time_mod

    from registrar_tpu import statefile

    try:
        state = statefile.load(args.file)
    except statefile.StateFileError as e:
        print(f"zkcli: state: {e} (reason: {e.reason})", file=sys.stderr)
        return 2
    age = time_mod.time() - state.stamp
    print(f"format = {statefile.FORMAT}")
    print(f"sessionId = 0x{state.session_id:x}")
    print(f"negotiatedTimeoutMs = {state.negotiated_timeout_ms}")
    print(f"lastZxid = 0x{state.last_zxid:x}")
    print(f"chroot = {state.chroot or '(none)'}")
    print(f"configHash = {state.config_hash}")
    print(f"pid = {state.pid}")
    print(f"stampAgeSeconds = {age:.1f}")
    print(f"znodes = {' '.join(state.znodes) or '(none)'}")
    config_hash = state.config_hash
    if args.config:
        from registrar_tpu.config import ConfigError, load_config

        try:
            cfg = load_config(args.config)
        except ConfigError as e:
            print(f"zkcli: state: {e}", file=sys.stderr)
            return 2
        config_hash = statefile.config_fingerprint(
            cfg.registration, cfg.admin_ip, cfg.zookeeper.chroot
        )
    reason = statefile.check_resumable(state, config_hash)
    if reason is None:
        print("resumable = yes (a successor would attempt the reattach)")
        return 0
    print(f"resumable = no ({reason})")
    return 1


async def _cmd_drain(args) -> int:
    """Deregister a host's records from OUTSIDE the daemon.

    The external analog of the daemon's ``restart.mode: "drain"``
    shutdown — for pulling a crashed, wedged, or SIGKILLed instance out
    of DNS without waiting for its session timeout.  Connects per the
    config's own ``zookeeper`` block (like ``verify``) and deletes the
    config's desired znodes; a shared service node still holding sibling
    hosts' ephemerals is left in place, exactly as the daemon's own
    deregistration would.  Exit 0 = drained (deleted nodes printed),
    2 = unreachable or config invalid.
    """
    from registrar_tpu import reconcile

    session = await _config_session(args, "drain")
    if session is None:
        return 2
    cfg, zk = session
    try:
        paths = [
            d.path
            for d in reconcile.desired_records(
                cfg.registration, cfg.admin_ip, args.hostname
            )
        ]
        from registrar_tpu.registration import unlink_tolerant

        outcomes = []
        try:
            for p in paths:
                # Already absent, or a shared service node with sibling
                # hosts still under it: both are fine for an external
                # drain — the goal is THIS host out of DNS.
                outcomes.append((p, await unlink_tolerant(zk, p)))
        except (ZKError, ConnectionError, OSError) as e:
            print(f"zkcli: drain: {e}", file=sys.stderr)
            return 2
    finally:
        await zk.close()
    for node, outcome in outcomes:
        if outcome == "deleted":
            print(f"deleted {node}")
        else:
            why = "already absent" if outcome == "absent" else "shared (kept)"
            print(f"skipped {node} ({why})")
    return 0


async def _metrics_get_json(host: str, port: int, path: str, timeout: float):
    """GET a JSON payload off the daemon's metrics listener (stdlib
    asyncio only, matching the listener's HTTP/1.0 one-shot shape).
    Returns the decoded object; raises OSError/ValueError on failure."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout=timeout
    )
    try:
        writer.write(
            f"GET {path} HTTP/1.0\r\nHost: {host}\r\n\r\n".encode()
        )
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout=timeout)
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    parts = head.split()
    if len(parts) < 2 or parts[1] != b"200":
        raise OSError(
            f"GET {path}: HTTP "
            f"{head.splitlines()[0].decode('latin-1', 'replace') if head else 'no response'}"
        )
    return json.loads(body)


def _metrics_endpoint(args, what: str):
    """Resolve ``-f CONFIG``'s metrics listener address, or None (after
    printing why) when the config is unreadable or has no ``metrics``
    block — the shared scaffolding of ``status`` and ``trace``."""
    from registrar_tpu.config import ConfigError, load_config

    try:
        cfg = load_config(args.file)
    except ConfigError as e:
        print(f"zkcli: {what}: {e}", file=sys.stderr)
        return None
    if cfg.metrics is None:
        print(
            f"zkcli: {what}: {args.file} has no `metrics` block — the "
            "daemon serves /status and /debug/trace on the metrics "
            "listener", file=sys.stderr,
        )
        return None
    return cfg.metrics.host, cfg.metrics.port


async def _member_role(server: str, timeout: float) -> Optional[str]:
    """The connected ensemble member's replication role, read off its
    ``srvr`` admin word (ISSUE 10): leader / follower / read-only /
    standalone.  None when the probe fails — role reporting must never
    break ``status`` against a member that dropped since the snapshot.
    """
    from registrar_tpu.zk.client import four_letter_word

    host, _, port_s = server.rpartition(":")
    try:
        raw = await four_letter_word(host, int(port_s), b"srvr", timeout)
    except (OSError, ValueError, asyncio.TimeoutError):
        return None
    for line in raw.decode("latin-1", "replace").splitlines():
        if line.startswith("Mode: "):
            return line[len("Mode: "):].strip()
    return None


async def _cmd_status(args) -> int:
    """One-shot daemon introspection: ``GET /status`` off the metrics
    listener, pretty-printed (ISSUE 8 — the runbook's first stop).

    Exit status follows the ``verify`` contract: 0 = healthy (session
    connected, registered, not health-down), 1 = degraded (any of those
    false, read-only attach, or reconciler drift standing), 2 =
    unreachable (no metrics block, daemon not answering, or the config
    unreadable).
    """
    endpoint = _metrics_endpoint(args, "status")
    if endpoint is None:
        return 2
    host, port = endpoint
    try:
        snapshot = await _metrics_get_json(
            host, port, "/status", args.timeout
        )
    except (OSError, ValueError, asyncio.TimeoutError) as e:
        print(f"zkcli: status: {host}:{port}: {e}", file=sys.stderr)
        return 2
    print(json.dumps(snapshot, indent=2, default=str))
    # uptime + last-transition stamps (ISSUE 9 satellite): the MTTR
    # arithmetic at a glance — how long the daemon has been up and how
    # long ago each slow-moving state last changed.
    uptime = snapshot.get("uptime_s")
    transitions = snapshot.get("last_transition") or {}
    if uptime is not None or transitions:
        import time as time_mod

        bits = []
        if uptime is not None:
            bits.append(f"up {uptime}s")
        for kind in ("session", "registration", "health", "serve"):
            entry = transitions.get(kind)
            if entry and entry.get("at") is not None:
                age = max(0.0, round(time_mod.time() - entry["at"], 1))
                bits.append(f"{kind} -> {entry.get('state')} {age}s ago")
        print(f"zkcli: status: {'; '.join(bits)}", file=sys.stderr)
    if "shards" in snapshot:
        # The sharded serve tier's router (ISSUE 12): the snapshot is a
        # per-shard rollup, not a single daemon's session — degraded is
        # any shard down (its slice is failing until the respawn lands).
        shards = snapshot.get("shards") or {}
        for sid, info in sorted(shards.items(), key=lambda kv: int(kv[0])):
            sess = info.get("session") or {}
            state = "up" if info.get("up") else "DOWN"
            ro = " ro" if sess.get("readOnly") else ""
            print(
                f"zkcli: status: shard {sid} {state} "
                f"session={sess.get('id')}@{sess.get('server')}{ro} "
                f"entries={info.get('entries')} "
                f"resolves={info.get('resolves_total')} "
                f"lagMs={info.get('coherence_lag_ms_last')} "
                f"respawns={info.get('respawns')}",
                file=sys.stderr,
            )
            # Overload armor at a glance (ISSUE 17): the live dispatch
            # backlog plus deliberate rejects by reason — the runbook's
            # shed-reason taxonomy, one line per shard.
            sheds = {
                reason: count
                for reason, count in (info.get("sheds") or {}).items()
                if count
            }
            shed_bits = (
                " ".join(f"{r}={n}" for r, n in sorted(sheds.items()))
                if sheds
                else "none"
            )
            print(
                f"zkcli: status: shard {sid} "
                f"queueDepth={info.get('queue_depth', 0)} "
                f"sheds: {shed_bits}",
                file=sys.stderr,
            )
            # DNS frontend at a glance (ISSUE 19): query volume, the
            # encode cache's hit ratio (the line-rate path's health),
            # and DNS-side sheds — one line per shard.
            dns = info.get("dns")
            if dns:
                queries = dns.get("queries") or {}
                cache = dns.get("encode_cache") or {}
                hits = cache.get("hits", 0)
                lookups = hits + cache.get("misses", 0)
                ratio = f"{hits / lookups:.2f}" if lookups else "-"
                dns_sheds = sum((dns.get("sheds") or {}).values())
                print(
                    f"zkcli: status: shard {sid} "
                    f"dns port={dns.get('port')} "
                    f"queries={sum(queries.values())} "
                    f"encodeCacheHit={ratio} "
                    f"entries={cache.get('entries', 0)} "
                    f"sheds={dns_sheds}",
                    file=sys.stderr,
                )
        problems = []
        for sid in snapshot.get("shards_down") or []:
            problems.append(f"shard {sid} down")
        for sid, info in sorted(shards.items(), key=lambda kv: int(kv[0])):
            if info.get("up") and not info.get("authoritative"):
                problems.append(f"shard {sid} degraded (live reads)")
        if problems:
            print(f"zkcli: status: DEGRADED: {'; '.join(problems)}",
                  file=sys.stderr)
            return 1
        print("zkcli: status: healthy", file=sys.stderr)
        return 0
    session = snapshot.get("session") or {}
    registration = snapshot.get("registration") or {}
    health = snapshot.get("health") or {}
    reconcile_info = snapshot.get("reconcile") or {}
    # The connected ensemble member's real role, probed off its srvr
    # admin word (ISSUE 10): election outcomes at a glance.
    if session.get("server"):
        role = await _member_role(session["server"], args.timeout)
        ro = " (read-only session)" if session.get("readOnly") else ""
        print(
            f"zkcli: status: zk member {session['server']} "
            f"role={role or 'unknown'}{ro}",
            file=sys.stderr,
        )
    # Connect-race outcome (ISSUE 20): which member won the last raced
    # connect, how many candidates were in flight, and how long the last
    # failover took — the raced-connect levers at a glance.
    race = session.get("connectRace") or {}
    if race.get("wins"):
        failover = session.get("lastFailoverS")
        failover_bit = (
            f" lastFailover={failover}s" if failover is not None else ""
        )
        print(
            f"zkcli: status: connect race won by {race.get('lastWinner')} "
            f"(candidates={race.get('lastCandidates')} "
            f"aborted={race.get('lastAborted')} "
            f"wins={race.get('wins')}){failover_bit}",
            file=sys.stderr,
        )
    problems = []
    if not session.get("connected"):
        problems.append(f"session {session.get('state', 'unknown')}")
    elif session.get("readOnly"):
        # Attached, but to a read-only minority member: resolves answer,
        # writes refuse — the OPERATIONS.md read-only-mode alert.
        problems.append("read-only member (writes refused)")
    if not registration.get("registered"):
        problems.append("not registered")
    if health.get("down"):
        problems.append("health-down")
    last_sweep = reconcile_info.get("lastSweep") or {}
    if last_sweep.get("drift"):
        problems.append(f"drift={last_sweep['drift']} at last sweep")
    if problems:
        print(f"zkcli: status: DEGRADED: {'; '.join(problems)}",
              file=sys.stderr)
        return 1
    print("zkcli: status: healthy", file=sys.stderr)
    return 0


def _span_line(entry) -> str:
    """One flight-recorder entry as one grep-friendly line."""
    import datetime

    stamp = datetime.datetime.fromtimestamp(
        entry.get("time", 0), tz=datetime.timezone.utc
    ).strftime("%H:%M:%S.%f")[:-3]
    attrs = " ".join(
        f"{k}={v}" for k, v in sorted((entry.get("attrs") or {}).items())
    )
    if entry.get("kind") == "event":
        return f"{stamp}  event  {entry.get('name')}  {attrs}".rstrip()
    dur = entry.get("duration_ms")
    marks = entry.get("marks") or {}
    split = ""
    if "flushed" in marks and dur is not None:
        queue = marks["flushed"]
        split = f" queue={queue}ms wire={round(dur - queue, 3)}ms"
    ids = f"{entry.get('trace_id')}/{entry.get('span_id')}"
    status = entry.get("status", "?")
    return (
        f"{stamp}  {dur if dur is not None else '?':>9}ms  "
        f"{entry.get('name')}  [{status}]  {ids}  {attrs}{split}"
    ).rstrip()


async def _cmd_trace(args) -> int:
    """Dump the daemon's flight recorder: ``GET /debug/trace?n=`` off
    the metrics listener, one line per span/event (ISSUE 8) — or, with
    ``--id TRACE_ID``, fetch ONE assembled trace tree (ISSUE 13: across
    every shard worker when the listener fronts the sharded tier) and
    pretty-print it as an indented duration tree.

    Exit 0 = entries printed, 1 = tracing disabled (no `observability`
    block) or the recorder is empty, 2 = unreachable.  ``--json`` prints
    the raw payload instead of the line rendering.
    """
    endpoint = _metrics_endpoint(args, "trace")
    if endpoint is None:
        return 2
    host, port = endpoint
    if args.id:
        from registrar_tpu import traceview

        try:
            tree = await _metrics_get_json(
                host, port, f"/debug/trace?id={args.id}", args.timeout
            )
        except (OSError, ValueError, asyncio.TimeoutError) as e:
            print(f"zkcli: trace: {host}:{port}: {e}", file=sys.stderr)
            return 2
        if tree.get("error"):
            print(f"zkcli: trace: {tree['error']}", file=sys.stderr)
            return 2
        if "roots" not in tree:
            # Not an assembled tree (a daemon with custom wiring handed
            # something else back): a clean exit, never a KeyError.
            print(
                "zkcli: trace: the listener did not answer an assembled "
                "tree for --id (unexpected payload shape)",
                file=sys.stderr,
            )
            return 2
        if args.json:
            print(json.dumps(tree, indent=2, default=str))
            return 0 if tree.get("spans") else 1
        print(traceview.render_text(tree))
        for source in tree.get("sources") or ():
            if source.get("error"):
                print(
                    f"zkcli: trace: {source['proc']}: {source['error']} "
                    "(its spans, if any, are orphaned above)",
                    file=sys.stderr,
                )
        if not tree.get("spans"):
            print(
                f"zkcli: trace: no spans recorded for {args.id} (wrong "
                "id, evicted from the ring, or tracing disabled)",
                file=sys.stderr,
            )
            return 1
        return 0
    try:
        payload = await _metrics_get_json(
            host, port, f"/debug/trace?n={args.n}", args.timeout
        )
    except (OSError, ValueError, asyncio.TimeoutError) as e:
        print(f"zkcli: trace: {host}:{port}: {e}", file=sys.stderr)
        return 2
    if not payload.get("enabled"):
        print(
            "zkcli: trace: tracing is disabled (no `observability` "
            "config block on the daemon)", file=sys.stderr,
        )
        return 1
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0 if payload.get("entries") else 1
    entries = payload.get("entries") or []
    for entry in entries:
        print(_span_line(entry))
    print(
        f"zkcli: trace: {len(entries)} entries "
        f"({payload.get('spans_recorded', 0)} spans, "
        f"{payload.get('events_recorded', 0)} events recorded; "
        f"sampleRate={payload.get('sample_rate')})", file=sys.stderr,
    )
    return 0 if entries else 1


def _resolution_lines(res) -> List[str]:
    """Render a Resolution the way `resolve` prints it (shared with the
    serve-view loop so the two command outputs can never drift)."""
    lines = [str(ans) for ans in res.answers]
    if res.additionals:
        lines.append(";; ADDITIONAL:")
        lines.extend(str(ans) for ans in res.additionals)
    return lines


async def _cmd_resolve(zk: ZKClient, args) -> int:
    src = zk
    cache = None
    try:
        if getattr(args, "cached", False):
            # The watch-coherent memory path (ISSUE 4): first resolve
            # fills the cache (live reads + one-shot watches), the
            # printed answer is then served entirely from memory — the
            # same plumbing the long-running `serve-view` loop keeps
            # hot.
            from registrar_tpu.zkcache import ZKCache

            cache = ZKCache(zk)
            await binderview.resolve(cache, args.name, args.qtype)
            src = cache
        res = await binderview.resolve(src, args.name, args.qtype)
    finally:
        # close() even when the warm-up resolve raised: at the REPL the
        # session (and the cache's listeners on it) outlives the failed
        # command, and a leaked listener set per retry accumulates.
        if cache is not None:
            cache.close()
    if res.empty:
        print(f"no answers for {args.name} ({args.qtype})", file=sys.stderr)
        return 1
    for line in _resolution_lines(res):
        print(line)
    return 0


def _infer_qtype(name: str) -> str:
    labels = name.split(".")
    if (
        len(labels) >= 3
        and labels[0].startswith("_")
        and labels[1].startswith("_")
    ):
        return "SRV"
    return "A"


async def _dig_endpoint(args) -> Optional[Tuple[str, int]]:
    """Resolve where `dig` should send packets: --server wins, else the
    config's serve.dns block; a configured port of 0 (allocate at tier
    start) is read off the running tier's ``GET /status`` serve block,
    which carries the concrete SO_REUSEPORT port."""
    if args.server:
        host, _, port_s = args.server.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            port = 0
        if not host or not (0 < port < 65536):
            print(f"zkcli: dig: bad --server {args.server!r} "
                  "(want HOST:PORT)", file=sys.stderr)
            return None
        return host, port
    if not args.file:
        print("zkcli: dig: need --server HOST:PORT or -f CONFIG",
              file=sys.stderr)
        return None
    from registrar_tpu.config import ConfigError, load_config

    try:
        cfg = load_config(args.file)
    except ConfigError as e:
        print(f"zkcli: dig: {e}", file=sys.stderr)
        return None
    dns_cfg = cfg.serve.dns if cfg.serve is not None else None
    if dns_cfg is None:
        print(f"zkcli: dig: {args.file} has no serve.dns block",
              file=sys.stderr)
        return None
    if dns_cfg.port:
        return dns_cfg.host, dns_cfg.port
    if cfg.metrics is None:
        print(
            "zkcli: dig: serve.dns.port is 0 (allocated at tier start) "
            "and the config has no metrics block to ask the running "
            "tier — pin a port or pass --server", file=sys.stderr,
        )
        return None
    try:
        snapshot = await _metrics_get_json(
            cfg.metrics.host, cfg.metrics.port, "/status", args.timeout
        )
    except (OSError, ValueError, asyncio.TimeoutError) as e:
        print(
            f"zkcli: dig: {cfg.metrics.host}:{cfg.metrics.port}: {e} "
            "(serve.dns.port is 0; the running tier's /status has the "
            "allocated port)", file=sys.stderr,
        )
        return None
    port = ((snapshot.get("serve") or {}).get("dns") or {}).get("port")
    if not port:
        print("zkcli: dig: the running tier reports no DNS frontend",
              file=sys.stderr)
        return None
    return dns_cfg.host, int(port)


async def _cmd_dig(args) -> int:
    """Query the DNS frontend with real packets (ISSUE 19): the wire-
    level sibling of `resolve` — same answers, but through the tier's
    SO_REUSEPORT UDP socket (TCP on truncation), so it proves the whole
    serve path an actual resolver would traverse.

    Exit codes follow the probe contract: 0 = NOERROR with answers,
    1 = a well-formed negative or refusal (NXDOMAIN, NODATA, REFUSED,
    SERVFAIL), 2 = unreachable (nowhere to send, timeout, or a reply
    the codec rejects).
    """
    import random
    import time as time_mod

    from registrar_tpu import dnsfront

    endpoint = await _dig_endpoint(args)
    if endpoint is None:
        return 2
    host, port = endpoint
    qtype = args.qtype or _infer_qtype(args.name)
    packet = dnsfront.build_query(
        random.randrange(1 << 16), args.name, dnsfront.TYPE_CODES[qtype],
        edns_size=dnsfront.DEFAULT_UDP_PAYLOAD_MAX,
    )
    proto = "TCP" if args.tcp else "UDP"
    t0 = time_mod.perf_counter()
    try:
        if args.tcp:
            raw = await dnsfront.query_tcp(
                host, port, packet, timeout=args.timeout)
        else:
            raw = await dnsfront.query_udp(
                host, port, packet, timeout=args.timeout)
            if dnsfront.decode_response(raw).tc:
                # The TC bit: the answer outgrew the UDP budget — retry
                # the same query over the tier's TCP listener, like any
                # real resolver would.
                print(";; truncated: retrying over TCP", file=sys.stderr)
                proto = "UDP->TCP"
                raw = await dnsfront.query_tcp(
                    host, port, packet, timeout=args.timeout)
    except (asyncio.TimeoutError, ConnectionError, OSError) as e:
        print(f"zkcli: dig: {host}:{port}: {e!r}", file=sys.stderr)
        return 2
    elapsed_ms = (time_mod.perf_counter() - t0) * 1000.0
    try:
        resp = dnsfront.decode_response(raw)
    except dnsfront.DnsError as e:
        print(f"zkcli: dig: malformed reply from {host}:{port}: {e}",
              file=sys.stderr)
        return 2
    status = dnsfront.RCODE_NAMES.get(resp.rcode, str(resp.rcode))
    flag_bits = " ".join(
        label for label, mask in (
            ("qr", dnsfront.FLAG_QR), ("aa", dnsfront.FLAG_AA),
            ("tc", dnsfront.FLAG_TC), ("rd", dnsfront.FLAG_RD),
            ("ra", dnsfront.FLAG_RA),
        ) if resp.flags & mask
    )
    print(f";; ->>HEADER<<- opcode: QUERY, status: {status}, "
          f"id: {resp.qid}")
    print(f";; flags: {flag_bits}; ANSWER: {len(resp.answers)}, "
          f"AUTHORITY: {len(resp.authorities)}, "
          f"ADDITIONAL: {len(resp.additionals)}")
    print(";; QUESTION SECTION:")
    qtname = dnsfront.QTYPE_NAMES.get(resp.qtype, str(resp.qtype))
    print(f";{resp.qname}.\t\tIN\t{qtname}")
    for title, section in (("ANSWER", resp.answers),
                           ("AUTHORITY", resp.authorities),
                           ("ADDITIONAL", resp.additionals)):
        if section:
            print(f";; {title} SECTION:")
            for name, tname, ttl, text in section:
                print(f"{name}.\t{ttl}\tIN\t{tname}\t{text}")
    print(f";; Query time: {elapsed_ms:.1f} msec")
    print(f";; SERVER: {host}#{port} ({proto})")
    return 0 if resp.rcode == dnsfront.RCODE_NOERROR and resp.answers \
        else 1


async def _cmd_serve_view(args) -> int:
    """Long-running Binder's-eye watch loop over the resolve cache.

    Warms a :class:`registrar_tpu.zkcache.ZKCache` for the given names,
    prints each answer set, then re-resolves and re-prints whenever a
    watch invalidation lands — the cache stays hot and coherent exactly
    the way Binder's own zkplus cache does.  A periodic bunyan status
    line on stderr (the daemon's jlog shape) carries hit rate, entry
    count, and authority, so an operator can see a cold or degraded
    cache at a glance.  ``--duration`` bounds the run (0 = until ^C).

    Connects per ``-f CONFIG``'s own zookeeper/cache blocks when given
    (like ``verify``), else per ``-s``.
    """
    import logging

    from registrar_tpu import jlog
    from registrar_tpu.retry import RetryPolicy
    from registrar_tpu.zkcache import DEFAULT_MAX_ENTRIES, ZKCache

    # getattr: at the interactive prompt only `servers` is copied onto
    # the parsed command; the chroot flag is a one-shot-invocation knob.
    servers = args.servers
    chroot = getattr(args, "chroot", None)
    request_timeout_ms = None
    max_entries = args.max_entries
    if args.file:
        from registrar_tpu.config import ConfigError, load_config

        try:
            cfg = load_config(args.file)
        except ConfigError as e:
            print(f"zkcli: serve-view: {e}", file=sys.stderr)
            return 2
        servers = cfg.zookeeper.servers
        chroot = cfg.zookeeper.chroot
        request_timeout_ms = cfg.zookeeper.request_timeout_ms
        if max_entries is None and cfg.cache is not None:
            max_entries = cfg.cache.max_entries
    if max_entries is None:
        max_entries = DEFAULT_MAX_ENTRIES

    zk = ZKClient(
        servers,
        chroot=chroot,
        request_timeout_ms=request_timeout_ms,
        # Long-running: ride out blips like the daemon does; the cache
        # degrades to live reads while down and resumes cold after.
        reconnect_policy=RetryPolicy(
            max_attempts=float("inf"), initial_delay=0.5, max_delay=15
        ),
        # A pure reader: keep serving through a read-only minority
        # member during quorum loss (ISSUE 10).
        can_be_read_only=True,
    )
    try:
        await asyncio.wait_for(zk.connect(), timeout=10)
    except Exception as e:  # noqa: BLE001 - startup probe failure
        print(f"zkcli: cannot connect to {servers}: {e}", file=sys.stderr)
        return 1

    status_log = logging.getLogger("registrar_tpu.zkcli.serve_view")
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(jlog.BunyanFormatter("zkcli"))
    status_log.handlers[:] = [handler]
    status_log.propagate = False
    status_log.setLevel(logging.INFO)

    cache = ZKCache(zk, max_entries=max_entries)
    names = [(n.rstrip(".").lower(), _infer_qtype(n)) for n in args.names]
    shown = {}

    async def refresh(initial: bool = False) -> None:
        for name, qtype in names:
            res = await binderview.resolve(cache, name, qtype)
            lines = _resolution_lines(res)
            if shown.get(name) == lines and not initial:
                continue
            shown[name] = lines
            print(f";; {name} ({qtype}):")
            for line in lines or ["; no answers"]:
                print(line)
            sys.stdout.flush()

    def emit_status() -> None:
        status_log.info(
            "cache status",
            extra={
                "zdata": {
                    "hits": int(cache.stats["hits"]),
                    "misses": int(cache.stats["misses"]),
                    "hitRate": round(cache.hit_rate(), 4),
                    "invalidations": int(cache.stats["invalidations"]),
                    "entries": cache.entries,
                    "authoritative": cache.authoritative,
                    "degradedTotal": int(cache.stats["degraded_total"]),
                    "coherenceLagMsLast": round(
                        cache.stats["coherence_lag_ms_last"], 3
                    ),
                }
            },
        )

    dirty = asyncio.Event()
    cache.on("invalidated", lambda *_a: dirty.set())
    cache.on("restored", lambda *_a: dirty.set())

    loop = asyncio.get_running_loop()
    start = loop.time()
    next_status = start + args.status_interval
    try:
        await refresh(initial=True)
        emit_status()
        while True:
            now = loop.time()
            if args.duration and now - start >= args.duration:
                emit_status()
                return 0
            wait = next_status - now
            if args.duration:
                wait = min(wait, args.duration - (now - start))
            try:
                await asyncio.wait_for(dirty.wait(), timeout=max(wait, 0.01))
            except asyncio.TimeoutError:
                pass
            if dirty.is_set():
                dirty.clear()
                try:
                    await refresh()
                except (ZKError, ConnectionError, OSError) as e:
                    # Degraded (live-read) refresh against a down
                    # ensemble: keep the loop alive; the reconnect +
                    # restored event re-resolves when service returns.
                    print(f"zkcli: refresh failed: {e}", file=sys.stderr)
            if loop.time() >= next_status:
                emit_status()
                next_status += args.status_interval
    finally:
        cache.close()
        await zk.close()


async def _cmd_serve_sharded(args) -> int:
    """Run the namespace-sharded resolve tier standalone (ISSUE 12).

    Per the config's ``serve`` block: spawns ``serve.shards`` worker
    processes (each its own event loop + ZooKeeper session + watch-
    coherent cache, watch load spread per ``serve.attachSpread``),
    supervises them (crash → respawn, siblings keep serving), and
    answers the length-prefixed resolve protocol on
    ``serve.socketPath``.  SIGHUP re-reads the config and **reshards
    in place** — a shard-count change moves only ~K/N warm domains
    (consistent hashing) and every moving domain is pre-warmed by its
    new owner before the ring flips, so resolves never error and the
    tier never cold-starts.  With a ``metrics`` block, serves
    ``GET /metrics`` (``registrar_shard_*``) and the per-shard
    ``GET /status`` rollup on the configured listener.  ``--duration``
    bounds the run (0 = until SIGTERM/^C).
    """
    import signal as signal_mod

    from registrar_tpu import metrics as metrics_mod
    from registrar_tpu import trace as trace_mod
    from registrar_tpu.config import ConfigError, load_config
    from registrar_tpu.shard import ShardRouter

    try:
        cfg = load_config(args.file)
    except ConfigError as e:
        print(f"zkcli: serve-sharded: {e}", file=sys.stderr)
        return 2
    if cfg.serve is None:
        print(
            f"zkcli: serve-sharded: {args.file} has no `serve` block "
            "(serve: {shards, socketPath, attachSpread})",
            file=sys.stderr,
        )
        return 2
    # The `observability` block turns on CROSS-PROCESS tracing (ISSUE
    # 13): the router records shard.relay/shard.trace_collect spans,
    # every spawned worker gets its own recorder at the same sample
    # rate, and the wire protocol carries one trace id end to end.
    # Absent block: not a traced byte anywhere, exactly like the daemon.
    tracer = None
    obs = cfg.observability
    if obs is not None:
        tracer = trace_mod.Tracer(
            sample_rate=obs.sample_rate,
            slow_span_ms=obs.slow_span_ms,
            max_spans=obs.flight_recorder_spans,
        )
        trace_mod.set_tracer(tracer)
    router = ShardRouter(
        cfg.zookeeper.servers,
        cfg.serve.shards,
        cfg.serve.socket_path,
        attach_spread=cfg.serve.attach_spread,
        chroot=cfg.zookeeper.chroot,
        max_entries=cfg.cache.max_entries if cfg.cache is not None else None,
        timeout_ms=cfg.zookeeper.timeout_ms,
        connect_timeout_ms=cfg.zookeeper.connect_timeout_ms,
        request_timeout_ms=cfg.zookeeper.request_timeout_ms,
        worker_trace=(
            {
                "sampleRate": obs.sample_rate,
                "maxSpans": obs.flight_recorder_spans,
                "slowSpanMs": obs.slow_span_ms,
            }
            if obs is not None
            else None
        ),
        # Overload armor (ISSUE 17): admission bounds + shed policy
        # from config.serve.overload.  Absent block: None — not a knob
        # set anywhere, byte-identical to the unarmored tier.
        overload=(
            cfg.serve.overload.as_router_kwargs()
            if cfg.serve.overload is not None
            else None
        ),
        # DNS frontend (ISSUE 19): every worker binds an SO_REUSEPORT
        # UDP socket + TCP listener on config.serve.dns's host:port.
        # Absent block: None — no DNS socket anywhere.
        dns=(
            cfg.serve.dns.as_spec()
            if cfg.serve.dns is not None
            else None
        ),
    )
    try:
        await router.start()
    except Exception as e:  # noqa: BLE001 - startup failure, not a bug
        print(f"zkcli: serve-sharded: cannot start tier: {e!r}",
              file=sys.stderr)
        await router.stop()
        if tracer is not None:
            trace_mod.set_tracer(None)
        return 1

    metrics_server = None
    if cfg.metrics is not None:
        registry = metrics_mod.instrument_shards(router)
        metrics_server = metrics_mod.MetricsServer(
            registry, host=cfg.metrics.host, port=cfg.metrics.port,
            status_provider=router.status,
            trace_provider=(
                (lambda n: tracer.dump(n)) if tracer is not None else None
            ),
            # GET /debug/trace?id=<trace_id>: the OP_TRACE fan-out —
            # one assembled tree across router + every worker.
            trace_tree_provider=(
                router.collect_trace if tracer is not None else None
            ),
        )
        try:
            await metrics_server.start()
        except OSError as e:
            # Same stance as the daemon: a busy metrics port must not
            # block the tier from serving.
            print(f"zkcli: serve-sharded: metrics listener failed: {e}",
                  file=sys.stderr)
            metrics_server = None

    stop = asyncio.Event()
    reload_requested = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal_mod.SIGTERM, signal_mod.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    loop.add_signal_handler(signal_mod.SIGHUP, reload_requested.set)
    dns_note = (
        f" + dns {router.dns['host']}:{router.dns['port']}/udp+tcp"
        if router.dns
        else ""
    )
    print(
        f"zkcli: serve-sharded: {cfg.serve.shards} shards on "
        f"{cfg.serve.socket_path}{dns_note} (SIGHUP reshards)",
        file=sys.stderr,
    )
    deadline = (
        loop.time() + args.duration if args.duration else None
    )
    try:
        while not stop.is_set():
            timeout = 0.2
            if deadline is not None:
                timeout = min(timeout, max(deadline - loop.time(), 0))
            try:
                await asyncio.wait_for(stop.wait(), timeout=timeout)
            except asyncio.TimeoutError:
                pass
            if deadline is not None and loop.time() >= deadline:
                break
            if reload_requested.is_set():
                reload_requested.clear()
                try:
                    fresh = load_config(args.file)
                except ConfigError as e:
                    print(f"zkcli: serve-sharded: reload failed: {e}",
                          file=sys.stderr)
                    continue
                if fresh.serve is None:
                    print(
                        "zkcli: serve-sharded: reload dropped the "
                        "`serve` block; keeping the running shape",
                        file=sys.stderr,
                    )
                    continue
                if fresh.serve.shards != router.shards:
                    try:
                        outcome = await router.reshard(fresh.serve.shards)
                    except Exception as e:  # noqa: BLE001 - keep serving
                        # A failed reshard (a new worker missed its
                        # readiness window, the ensemble is slow) must
                        # NOT take down the healthy tier — the old ring
                        # is untouched and keeps serving; the operator
                        # retries the SIGHUP.
                        print(
                            "zkcli: serve-sharded: reshard to "
                            f"{fresh.serve.shards} failed ({e!r}); "
                            "keeping the running shape — fix and "
                            "SIGHUP again", file=sys.stderr,
                        )
                        continue
                    print(
                        "zkcli: serve-sharded: resharded to "
                        f"{outcome['shards']} shards "
                        f"({outcome['moved']} warm domains handed off "
                        f"in {outcome['duration_ms']:.0f} ms)",
                        file=sys.stderr,
                    )
                else:
                    print(
                        "zkcli: serve-sharded: reload: shard count "
                        "unchanged; nothing to do", file=sys.stderr,
                    )
    finally:
        for sig in (signal_mod.SIGTERM, signal_mod.SIGINT,
                    signal_mod.SIGHUP):
            loop.remove_signal_handler(sig)
        if metrics_server is not None:
            await metrics_server.stop()
        await router.stop()
        if tracer is not None:
            trace_mod.set_tracer(None)
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="zkcli",
        description="inspect registrar service-discovery state in ZooKeeper"
        " (no command: enter the interactive prompt over one session)",
    )
    parser.add_argument(
        "-s", "--servers", type=_parse_servers,
        default=[("127.0.0.1", 2181)], metavar="HOST:PORT[,...]",
        help="ZooKeeper servers (default 127.0.0.1:2181)",
    )
    parser.add_argument(
        "--auth", type=_parse_auth, action="append", default=[],
        metavar="SCHEME:CRED",
        help="authenticate after connecting (repeatable), e.g. "
        "digest:user:password — the zkCli.sh `addauth` equivalent",
    )
    parser.add_argument(
        "--chroot", metavar="/PATH", default=None,
        help="prefix every path with this znode (the connect-string "
        "\"host:port/app\" suffix of standard ZooKeeper clients)",
    )
    sub = parser.add_subparsers(dest="command")
    _register_commands(sub)
    return parser


def _register_commands(sub) -> None:
    """Attach every zkcli command to a subparsers object — shared between
    the one-shot argv parser and the interactive prompt's line parser."""
    p = sub.add_parser("ls", help="list children of a znode")
    p.add_argument("path")
    p.set_defaults(fn=_cmd_ls)

    p = sub.add_parser("get", help="print a znode's JSON payload")
    p.add_argument("path")
    p.add_argument("--pretty", action="store_true", help="indent the JSON")
    p.set_defaults(fn=_cmd_get)

    p = sub.add_parser("stat", help="print a znode's stat")
    p.add_argument("path")
    p.set_defaults(fn=_cmd_stat)

    p = sub.add_parser("tree", help="print a subtree with payloads")
    p.add_argument("path", nargs="?", default="/")
    p.set_defaults(fn=_cmd_tree)

    # aliases = zkCli.sh's names, so operator muscle memory carries over
    p = sub.add_parser("rm", aliases=["delete"], help="delete a znode")
    p.add_argument("path")
    p.add_argument(
        "--version", type=int, default=-1,
        help="expected data version (conditional delete; default: "
        "unconditional)",
    )
    p.set_defaults(fn=_cmd_rm)

    p = sub.add_parser(
        "rmr", aliases=["deleteall"],
        help="delete a znode subtree, children first",
    )
    p.add_argument("path")
    p.set_defaults(fn=_cmd_rmr)

    p = sub.add_parser("create", help="create a znode")
    p.add_argument("path")
    p.add_argument("data", nargs="?", default="")
    p.add_argument("-e", "--ephemeral", action="store_true")
    p.add_argument("-s", "--sequential", action="store_true")
    p.add_argument(
        "-a", "--acl", type=_parse_acl, action="append", default=[],
        metavar="SCHEME:ID:PERMS",
        help="ACL entries for the new node (repeatable; default "
        "world:anyone:cdrwa)",
    )
    p.set_defaults(fn=_cmd_create)

    p = sub.add_parser(
        "set",
        help="set a znode's data (creates if missing, unless --version "
        "makes it a conditional plain set)",
    )
    p.add_argument("path")
    p.add_argument("data")
    p.add_argument(
        "--version", type=int, default=-1,
        help="expected data version (conditional set, no create-if-missing; "
        "default: unconditional upsert)",
    )
    p.set_defaults(fn=_cmd_set)

    p = sub.add_parser("mkdirp", help="create a path and missing ancestors")
    p.add_argument("path")
    p.set_defaults(fn=_cmd_mkdirp)

    p = sub.add_parser("watch", help="stream change events for a znode")
    p.add_argument("path")
    p.add_argument(
        "--duration", type=float, default=0.0, metavar="SECONDS",
        help="stop after this many seconds (default: run until ctrl-C)",
    )
    p.set_defaults(fn=_cmd_watch)

    p = sub.add_parser(
        "admin",
        help="send a 4-letter-word admin command (ruok/srvr/stat/mntr/...)",
    )
    p.add_argument(
        "word",
        choices=["ruok", "srvr", "stat", "mntr", "cons", "dump", "wchs",
                 "isro", "wchc", "wchp", "envi", "conf"],
    )
    p.set_defaults(fn=_cmd_admin, raw=True)

    p = sub.add_parser(
        "sync",
        help="flush the server's commit pipeline for a path (read barrier "
        "before read-backs in multi-server ensembles)",
    )
    p.add_argument("path", nargs="?", default="/")
    p.set_defaults(fn=_cmd_sync)

    p = sub.add_parser(
        "getacl", aliases=["getAcl"], help="print a znode's ACL list"
    )
    p.add_argument("path")
    p.set_defaults(fn=_cmd_getacl)

    p = sub.add_parser(
        "setacl", aliases=["setAcl"],
        help="replace a znode's ACL list (requires ADMIN)",
    )
    p.add_argument("path")
    p.add_argument(
        "acl", type=_parse_acl, nargs="+", metavar="SCHEME:ID:PERMS",
        help="e.g. world:anyone:cdrwa, digest:user:HASH:rw, ip:10.0.0.1:r, "
        "auth::cdrwa (expands to your authenticated identities)",
    )
    p.add_argument(
        "--version", type=int, default=-1,
        help="expected aversion (default: unconditional)",
    )
    p.set_defaults(fn=_cmd_setacl)

    p = sub.add_parser(
        "verify",
        help="diff live ZooKeeper state against a registrar config's "
        "desired records, read-only (exit 0 in-sync / 1 drift / "
        "2 unreachable) — connects per the config's own zookeeper block",
    )
    p.add_argument(
        "-f", "--file", required=True, metavar="CONFIG",
        help="registrar config file (the daemon's -f argument)",
    )
    p.add_argument(
        "--hostname", default=None,
        help="audit this hostname's records (default: this machine's "
        "hostname, matching what the daemon would register)",
    )
    p.add_argument(
        "--timeout", type=float, default=10.0, metavar="SECONDS",
        help="connect budget before reporting unreachable (default 10)",
    )
    p.set_defaults(fn=_cmd_verify, raw=True)

    p = sub.add_parser(
        "state",
        help="inspect a registrar handoff state file (restart.stateFile): "
        "fields + resumability verdict (exit 0 resumable / 1 not / "
        "2 unreadable); local only, no ZooKeeper connection",
    )
    p.add_argument("file", metavar="STATEFILE")
    p.add_argument(
        "--config", default=None, metavar="CONFIG",
        help="also check the state's config fingerprint against this "
        "registrar config (a mismatched config makes a resume fall back "
        "to a fresh registration)",
    )
    p.set_defaults(fn=_cmd_state, raw=True)

    p = sub.add_parser(
        "drain",
        help="deregister a host's records from outside the daemon — pull "
        "a crashed/wedged instance out of DNS now instead of waiting out "
        "its session timeout (connects per the config's zookeeper block)",
    )
    p.add_argument(
        "-f", "--file", required=True, metavar="CONFIG",
        help="registrar config file (the daemon's -f argument)",
    )
    p.add_argument(
        "--hostname", default=None,
        help="drain this hostname's records (default: this machine's "
        "hostname)",
    )
    p.add_argument(
        "--timeout", type=float, default=10.0, metavar="SECONDS",
        help="connect budget before reporting unreachable (default 10)",
    )
    p.set_defaults(fn=_cmd_drain, raw=True)

    p = sub.add_parser(
        "status",
        help="one-shot daemon introspection: GET /status off the "
        "config's metrics listener, pretty-printed (exit 0 healthy / "
        "1 degraded / 2 unreachable) — the incident runbook's first stop",
    )
    p.add_argument(
        "-f", "--file", required=True, metavar="CONFIG",
        help="registrar config file (its `metrics` block names the "
        "listener)",
    )
    p.add_argument(
        "--timeout", type=float, default=5.0, metavar="SECONDS",
        help="HTTP budget before reporting unreachable (default 5)",
    )
    p.set_defaults(fn=_cmd_status, raw=True)

    p = sub.add_parser(
        "trace",
        help="dump the daemon's flight recorder: GET /debug/trace off "
        "the config's metrics listener, one line per span/event (exit "
        "0 entries / 1 tracing disabled or empty / 2 unreachable); "
        "--id TRACE_ID instead fetches ONE assembled trace tree — "
        "merged across every shard worker when the listener fronts "
        "the sharded tier",
    )
    p.add_argument(
        "-f", "--file", required=True, metavar="CONFIG",
        help="registrar config file (its `metrics` block names the "
        "listener)",
    )
    p.add_argument(
        "-n", type=int, default=200,
        help="most recent N entries to fetch (default 200)",
    )
    p.add_argument(
        "--id", default=None, metavar="TRACE_ID",
        help="assemble and pretty-print ONE trace as a parent tree "
        "(the 16-hex-digit id from a log line, slo-report.json, or a "
        "flight-recorder entry)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the raw JSON payload instead of one line per entry",
    )
    p.add_argument(
        "--timeout", type=float, default=5.0, metavar="SECONDS",
        help="HTTP budget before reporting unreachable (default 5)",
    )
    p.set_defaults(fn=_cmd_trace, raw=True)

    p = sub.add_parser(
        "resolve", help="answer a DNS query the way Binder would"
    )
    p.add_argument("name")
    p.add_argument("-t", "--qtype", default="A", type=str.upper,
                   choices=["A", "SRV", "TXT"])
    p.add_argument(
        "--cached", action="store_true",
        help="serve the answer from a watch-coherent in-memory cache "
        "(fills on first touch, then answers without ZooKeeper reads — "
        "the Binder hot path; see serve-view for the long-running loop)",
    )
    p.set_defaults(fn=_cmd_resolve)

    p = sub.add_parser(
        "dig",
        help="query the serve tier's DNS frontend with real UDP/TCP "
        "packets, dig-style output (exit 0 answers / 1 negative or "
        "refused / 2 unreachable) — the wire-level sibling of `resolve`",
    )
    p.add_argument("name")
    p.add_argument(
        "-t", "--qtype", default=None, type=str.upper,
        choices=["A", "SRV", "TXT"],
        help="query type (default: SRV for _svc._proto. names, else A)",
    )
    p.add_argument(
        "-f", "--file", default=None, metavar="CONFIG",
        help="find the frontend per this config's serve.dns block (a "
        "configured port of 0 is read off the running tier's /status)",
    )
    p.add_argument(
        "--server", default=None, metavar="HOST:PORT",
        help="query this address instead of the config's",
    )
    p.add_argument(
        "--tcp", action="store_true",
        help="query over TCP from the start (the codec retries over TCP "
        "on a truncated UDP answer automatically)",
    )
    p.add_argument(
        "--timeout", type=float, default=3.0, metavar="SECONDS",
        help="per-exchange budget before reporting unreachable "
        "(default 3)",
    )
    p.set_defaults(fn=_cmd_dig, raw=True)

    p = sub.add_parser(
        "serve-view",
        help="long-running Binder's-eye view: warm the watch-coherent "
        "resolve cache for NAMES, re-print answers as watches "
        "invalidate, emit periodic bunyan cache-status lines on stderr",
    )
    p.add_argument(
        "names", nargs="+", metavar="NAME",
        help="domains to serve (a _svc._proto. prefix implies SRV)",
    )
    p.add_argument(
        "--duration", type=float, default=0.0, metavar="SECONDS",
        help="stop after this many seconds (default: run until ctrl-C)",
    )
    p.add_argument(
        "--status-interval", type=float, default=30.0, metavar="SECONDS",
        help="seconds between cache-status log lines (default 30)",
    )
    p.add_argument(
        "--max-entries", type=int, default=None,
        help="cache entry bound (default: config cache.maxEntries, "
        "else 4096)",
    )
    p.add_argument(
        "-f", "--file", default=None, metavar="CONFIG",
        help="connect per this registrar config's zookeeper block "
        "(and honor its cache block) instead of -s",
    )
    p.set_defaults(fn=_cmd_serve_view, raw=True)

    p = sub.add_parser(
        "serve-sharded",
        help="run the namespace-sharded resolve tier per the config's "
        "`serve` block: N worker processes (own session + watch-coherent "
        "cache each) behind a consistent-hash router on a unix socket; "
        "SIGHUP reshards in place with a warm handoff",
    )
    p.add_argument(
        "-f", "--file", required=True, metavar="CONFIG",
        help="registrar config file with a `serve` block (its zookeeper/"
        "cache/metrics blocks are honored too)",
    )
    p.add_argument(
        "--duration", type=float, default=0.0, metavar="SECONDS",
        help="stop after this many seconds (default: run until SIGTERM)",
    )
    p.set_defaults(fn=_cmd_serve_sharded, raw=True)

    p = sub.add_parser(
        "setquota", help="set a soft quota on a subtree (zkCli.sh setquota)"
    )
    p.add_argument("path")
    p.add_argument("-n", "--count", type=int, default=None,
                   help="max znodes in the subtree")
    p.add_argument("-b", "--bytes", type=int, default=None,
                   help="max total data bytes in the subtree")
    p.set_defaults(fn=_cmd_setquota)

    p = sub.add_parser(
        "listquota", help="show a subtree's quota and live usage"
    )
    p.add_argument("path")
    p.set_defaults(fn=_cmd_listquota)

    p = sub.add_parser(
        "delquota", help="delete a subtree's quota (or one dimension of it)"
    )
    p.add_argument("path")
    p.add_argument("-n", "--count", action="store_true",
                   help="clear only the znode-count limit")
    p.add_argument("-b", "--bytes", action="store_true",
                   help="clear only the byte limit")
    p.set_defaults(fn=_cmd_delquota)


def _repl_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="",
        description="zkcli interactive commands (plus: addauth "
        "SCHEME:CRED, help, quit)",
    )
    sub = parser.add_subparsers(dest="command")
    _register_commands(sub)
    return parser


async def _repl(zk: ZKClient, args) -> int:
    """Interactive prompt: every command runs over the ONE connected
    session, like a ``zkCli.sh -server host:port`` session (the workflow
    the reference's debugging notes teach, reference README.md:785-807).
    One-shot invocations pay a fresh connect per command; here ephemeral
    nodes created with ``create -e`` live exactly as long as the prompt.
    """
    import signal

    interactive = sys.stdin.isatty()
    if interactive:
        try:
            # input() below is what readline hooks for editing/history
            import readline  # noqa: F401
        except ImportError:
            pass
        host, port = zk.connected_server or zk.servers[0]
        print(
            f"connected to {host}:{port} "
            f"(session 0x{zk.session_id:x}); "
            "'help' lists commands, 'quit' leaves"
        )

    def _read_line():
        if interactive:
            try:
                return input("zkcli> ")
            except EOFError:
                return None
        raw = sys.stdin.readline()
        return raw.rstrip("\n") if raw else None

    loop = asyncio.get_running_loop()

    def _install_sigint(handler) -> bool:
        try:
            loop.add_signal_handler(signal.SIGINT, handler)
            return True
        except (NotImplementedError, RuntimeError):
            return False

    def _sigint_at_prompt() -> None:
        # ctrl-C at the idle prompt must NOT tear down the session (the
        # ephemerals the operator is rehearsing with would vanish), and
        # letting KeyboardInterrupt escape would also leave the executor
        # thread blocked in input(), hanging interpreter shutdown until
        # a stray Enter.  Consume it and point at the real exits.
        print("^C (use 'quit' or ctrl-D to leave)", file=sys.stderr)

    sigint_managed = _install_sigint(_sigint_at_prompt)

    async def _run_cancellable(coro) -> None:
        # ctrl-C aborts the running command (e.g. an open-ended `watch`)
        # and returns to the prompt; the session — and any ephemerals the
        # operator is rehearsing with — survives.  Matches zkCli.sh.
        task = asyncio.ensure_future(coro)

        def _sigint_during_command() -> None:
            # A SIGINT can land in the gap after the command finishes but
            # before the prompt handler is reinstalled below; cancelling
            # a done task is a silent no-op, so treat that case as a
            # prompt-level interrupt instead of swallowing it.
            if task.done():
                _sigint_at_prompt()
            else:
                task.cancel()

        if sigint_managed:
            _install_sigint(_sigint_during_command)
        try:
            await task
        except asyncio.CancelledError:
            print("^C", file=sys.stderr)
        finally:
            if sigint_managed:
                _install_sigint(_sigint_at_prompt)

    parser = _repl_parser()
    try:
        return await _repl_loop(
            zk, args, parser, loop, _read_line, _run_cancellable
        )
    finally:
        if sigint_managed:
            loop.remove_signal_handler(signal.SIGINT)


async def _repl_loop(zk, args, parser, loop, _read_line, _run_cancellable) -> int:
    import shlex

    while True:
        line = await loop.run_in_executor(None, _read_line)
        if line is None:
            break  # EOF
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            words = shlex.split(line)
        except ValueError as e:
            print(f"zkcli: {e}", file=sys.stderr)
            continue
        if words[0] in ("quit", "exit"):
            break
        if words[0] == "help":
            parser.print_help()
            continue
        if words[0] == "addauth":
            # zkCli.sh's addauth: authenticate the LIVE session (the
            # one-shot mode's --auth flag, but mid-session).
            if len(words) != 2:
                print("usage: addauth SCHEME:CRED (e.g. digest:user:pw)",
                      file=sys.stderr)
                continue
            try:
                scheme, cred = _parse_auth(words[1])
                await zk.add_auth(scheme, cred)
            except (ZKError, argparse.ArgumentTypeError) as e:
                print(f"zkcli: {e}", file=sys.stderr)
            continue
        try:
            cmd = parser.parse_args(words)
        except SystemExit:
            continue  # argparse reported usage; the prompt survives
        if cmd.command is None:
            continue
        cmd.repl = True
        try:
            if getattr(cmd, "raw", False):
                # raw commands build their own connections: hand them the
                # session's servers AND chroot (serve-view resolving
                # un-chrooted paths while the sibling `resolve` answers
                # through the chroot would silently disagree)
                cmd.servers = args.servers
                cmd.chroot = getattr(args, "chroot", None)
                await _run_cancellable(cmd.fn(cmd))
            else:
                await _run_cancellable(cmd.fn(zk, cmd))
        except ZKError as e:
            print(f"zkcli: {e}", file=sys.stderr)
        except ValueError as e:
            print(f"zkcli: {e}", file=sys.stderr)
    return 0


async def _amain(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "raw", False):
        # Admin probes speak raw TCP per server; no ZK session involved.
        return await args.fn(args)
    try:
        # Argument validation (e.g. a malformed --chroot) must not be
        # reported as a connectivity problem.  One-shot commands never
        # reconnect (fail fast); the interactive prompt must ride out
        # transient blips mid-investigation, like zkCli.sh.
        zk = ZKClient(
            args.servers,
            reconnect=args.command is None,
            reconnect_policy=RetryPolicy(
                max_attempts=float("inf"), initial_delay=0.5, max_delay=15
            ),
            chroot=args.chroot,
            # Read-mostly operator tooling must keep answering during
            # quorum loss (ISSUE 10): attach to a read-only member when
            # nothing better serves; a write then fails truthfully with
            # NOT_READONLY instead of the whole session being refused.
            can_be_read_only=True,
        )
    except ValueError as e:
        print(f"zkcli: {e}", file=sys.stderr)
        return 2
    try:
        await asyncio.wait_for(zk.connect(), timeout=10)
    except Exception as e:  # noqa: BLE001
        print(f"zkcli: cannot connect to {args.servers}: {e}", file=sys.stderr)
        return 1
    try:
        for scheme, cred in args.auth:
            await zk.add_auth(scheme, cred)
        if args.command is None:
            return await _repl(zk, args)
        return await args.fn(zk, args)
    except ZKError as e:
        print(f"zkcli: {e}", file=sys.stderr)
        return 1
    except ValueError as e:
        # e.g. check_path rejecting a malformed znode path — a one-line
        # error, not a traceback.
        print(f"zkcli: {e}", file=sys.stderr)
        return 1
    finally:
        await zk.close()


def main(argv=None) -> None:
    try:
        code = asyncio.run(_amain(argv))
    except KeyboardInterrupt:
        code = 0  # the documented way to stop `watch`
    except BrokenPipeError:
        # Output piped into head/grep that exited early: not an error.
        # Redirect stdout to devnull so the interpreter's shutdown flush
        # doesn't raise again.
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    sys.exit(code)


if __name__ == "__main__":
    main()
