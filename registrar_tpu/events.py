"""A minimal event emitter for asyncio code.

The reference's public surfaces are Node EventEmitters (lib/index.js:38,
main.js:160-198, zkplus client events); this is the idiomatic-Python
equivalent used by :mod:`registrar_tpu.zk.client` and
:mod:`registrar_tpu.agent`.  Listeners may be plain callables or coroutine
functions; coroutine listeners are scheduled as tasks on the running loop.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
from collections import defaultdict
from typing import Any, Callable, Dict, List

log = logging.getLogger("registrar_tpu.events")


class EventEmitter:
    def __init__(self) -> None:
        self._listeners: Dict[str, List[Callable]] = defaultdict(list)
        self._once: Dict[str, List[Callable]] = defaultdict(list)

    def on(self, event: str, listener: Callable) -> Callable:
        """Register ``listener`` for ``event``; returns it (decorator-friendly)."""
        self._listeners[event].append(listener)
        return listener

    def once(self, event: str, listener: Callable) -> Callable:
        self._once[event].append(listener)
        return listener

    def off(self, event: str, listener: Callable) -> None:
        for registry in (self._listeners, self._once):
            if listener in registry.get(event, []):
                registry[event].remove(listener)

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, [])) + len(self._once.get(event, []))

    def emit(self, event: str, *args: Any) -> int:
        """Dispatch ``event``; returns the number of listeners invoked."""
        targets = list(self._listeners.get(event, []))
        once = self._once.pop(event, [])
        targets.extend(once)
        for listener in targets:
            try:
                result = listener(*args)
                if inspect.isawaitable(result):
                    asyncio.get_running_loop().create_task(_guard(event, result))
            except Exception:
                log.exception("listener for %r raised", event)
        return len(targets)

    async def wait_for(self, event: str, timeout: float = 30.0) -> tuple:
        """Await the next emission of ``event``; returns its args (test aid)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self.once(event, lambda *a: fut.done() or fut.set_result(a))
        return await asyncio.wait_for(fut, timeout)


async def _guard(event: str, awaitable) -> None:
    try:
        await awaitable
    except Exception:
        log.exception("async listener for %r raised", event)
