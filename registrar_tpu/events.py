"""A minimal event emitter for asyncio code.

The reference's public surfaces are Node EventEmitters (lib/index.js:38,
main.js:160-198, zkplus client events); this is the idiomatic-Python
equivalent used by :mod:`registrar_tpu.zk.client` and
:mod:`registrar_tpu.agent`.  Listeners may be plain callables or coroutine
functions; coroutine listeners are scheduled as tasks on the running loop.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
from collections import defaultdict
from typing import Any, Callable, Dict, List

log = logging.getLogger("registrar_tpu.events")

#: Strong references to in-flight coroutine-listener tasks.  The event
#: loop only weak-references running tasks, so the bare create_task()
#: handle emit() used to discard could be garbage-collected mid-dispatch
#: (the checker's dropped-task rule now flags exactly that).
_DISPATCH_TASKS: set = set()

#: The loop the last spawn_owned ran on — stranded-task eviction only
#: needs to run when this changes (see spawn_owned).
_LAST_SPAWN_LOOP = None


def spawn_owned(coro, registry: set) -> "asyncio.Task":
    """Run ``coro`` as a task strongly held by ``registry`` until done.

    THE one copy of the fire-and-forget ownership idiom the dropped-task
    rule enforces (the loop only weak-references running tasks).  The
    caller owns ``registry`` and decides the shutdown policy: the test
    server cancels its set in stop(); emit()'s dispatch tasks are never
    cancelled, because listeners for terminal events (``close``, ``end``)
    must still run while their emitter is being torn down.
    """
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        # No loop: close the already-built coroutine so the clean
        # RuntimeError isn't followed by a 'never awaited' warning.
        coro.close()
        raise
    # Evict tasks stranded by a loop that closed without draining them
    # (their done-callbacks can never fire).  Only the module-global
    # dispatch set needs this — it outlives every loop, while per-owner
    # registries die with their owners — and stranded entries can only
    # appear across a loop change, so the O(registry) scan is skipped
    # on the steady single-loop hot path (emit()'s listener dispatch).
    global _LAST_SPAWN_LOOP
    if registry is _DISPATCH_TASKS and _LAST_SPAWN_LOOP is not loop:
        for t in [t for t in registry if t.get_loop().is_closed()]:
            registry.discard(t)
        _LAST_SPAWN_LOOP = loop
    task = loop.create_task(coro)
    registry.add(task)
    task.add_done_callback(registry.discard)
    return task


class EventEmitter:
    def __init__(self) -> None:
        self._listeners: Dict[str, List[Callable]] = defaultdict(list)
        self._once: Dict[str, List[Callable]] = defaultdict(list)

    def on(self, event: str, listener: Callable) -> Callable:
        """Register ``listener`` for ``event``; returns it (decorator-friendly)."""
        self._listeners[event].append(listener)
        return listener

    def once(self, event: str, listener: Callable) -> Callable:
        self._once[event].append(listener)
        return listener

    def off(self, event: str, listener: Callable) -> None:
        for registry in (self._listeners, self._once):
            if listener in registry.get(event, []):
                registry[event].remove(listener)
            if event in registry and not registry[event]:
                # drop the empty key: per-path watch listeners come and
                # go for the process lifetime (zkcache churn), and a
                # leftover empty list per path ever watched is a slow
                # leak in the client's _watch_emitter
                del registry[event]

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, [])) + len(self._once.get(event, []))

    def emit(self, event: str, *args: Any) -> int:
        """Dispatch ``event``; returns the number of listeners invoked."""
        targets = list(self._listeners.get(event, []))
        once = self._once.pop(event, [])
        targets.extend(once)
        for listener in targets:
            try:
                result = listener(*args)
                if inspect.isawaitable(result):
                    try:
                        spawn_owned(_guard(event, result), _DISPATCH_TASKS)
                    except RuntimeError:
                        # No running loop: spawn_owned closed the _guard
                        # wrapper, but the listener coroutine it would
                        # have awaited needs closing too, or GC warns
                        # 'coroutine was never awaited'.
                        if inspect.iscoroutine(result):
                            result.close()
                        raise
            except Exception:
                log.exception("listener for %r raised", event)
        return len(targets)

    async def wait_for(self, event: str, timeout: float = 30.0) -> tuple:
        """Await the next emission of ``event``; returns its args (test aid)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self.once(event, lambda *a: fut.done() or fut.set_result(a))
        return await asyncio.wait_for(fut, timeout)


async def _guard(event: str, awaitable) -> None:
    try:
        await awaitable
    except Exception:
        log.exception("async listener for %r raised", event)
