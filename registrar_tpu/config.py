"""Configuration loading and validation.

The config file is the same JSON shape the reference reads (reference
main.js:52-84, README.md "Configuration reference"; sample:
etc/config.coal.json)::

    {
      "adminIp": "10.0.0.5",                   # optional
      "zookeeper": {
        "servers": [{"host": "...", "port": 2181}, ...],
        "timeout": 30000,                      # session timeout, ms
        "connectTimeout": 4000                 # per-attempt dial timeout, ms
      },
      "registration": {
        "domain": "...", "type": "...",
        "aliases": [...], "ttl": 30, "ports": [...],
        "service": {"type": "service",
                    "service": {"srvce": "...", "proto": "...", "port": N,
                                "ttl": N}},
        "heartbeatInterval": 3000              # ms (undocumented upstream,
      },                                       #  honored for parity)
      "healthCheck": {                         # optional; ms-based values
        "command": "...", "interval": 60000, "timeout": 1000,
        "threshold": 5, "period": 300000, "ignoreExitStatus": false,
        "stdoutMatch": {"pattern": "...", "flags": "...", "invert": false}
      },
      "logLevel": "info",                      # optional
      "maxAttempts": 5,                        # heartbeat retry attempts
      "repairHeartbeatMiss": false,            # opt-in extension (no
                                               #  reference analog): re-run
                                               #  registration when a
                                               #  heartbeat finds the znodes
                                               #  gone (SURVEY.md §3.2 note)
      "metrics": {"port": 9090,                # opt-in extension: Prometheus
                  "host": "127.0.0.1"},        #  /metrics endpoint (the
                                               #  node-artedi analog,
                                               #  SURVEY.md §5)
      "surviveSessionExpiry": false,           # opt-in (ISSUE 3): rebuild a
                                               #  fresh ZK session in-process
                                               #  on expiry instead of exit(1)
      "maxSessionRebirths": 5,                 # rebirth circuit-breaker bound
                                               #  (per 5-minute window)
      "reconcile": {"intervalSeconds": 60,     # opt-in (ISSUE 3): level-
                    "repair": false},          #  triggered drift reconciler;
                                               #  NOTE: seconds, not ms
      "cache": {"maxEntries": 4096},           # resolve-cache tuning for
                                               #  zkcli serve-view (ISSUE 4);
                                               #  the daemon ignores it
      "restart": {                             # opt-in (ISSUE 5): zero-
        "stateFile": "/var/run/registrar/state.json",  # downtime restarts;
        "mode": "handoff",                     #  "handoff" hands the live ZK
        "drainGraceSeconds": 0                 #  session to the successor,
      },                                       #  "drain" unregisters + waits
      "serve": {                               # opt-in (ISSUE 12): the
        "shards": 4,                           #  namespace-sharded resolve
        "socketPath": "/var/run/registrar/resolve.sock",  # tier for `zkcli
        "attachSpread": "spread"               #  serve-sharded`; the daemon
      }                                        #  ignores the block entirely
    }

All reference keys are camelCase and all durations are milliseconds; this
module translates them into the seconds-based snake_case surface of the
Python modules.  ``maxAttempts`` appears in the reference's sample config
but is read by nothing there (SURVEY.md §2.7 calls it inert) — here it is
wired to the heartbeat retry policy, which is what it was evidently meant
to configure.
"""

from __future__ import annotations

import json
import math
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

from registrar_tpu.retry import HEARTBEAT_RETRY, RetryPolicy


class ConfigError(ValueError):
    """Invalid configuration (parse or validation failure)."""


class ConfigUnreadableError(ConfigError):
    """The config file could not be *read* (missing, permissions, I/O).

    Distinct from semantic invalidity because the right supervisor
    reaction differs: a file that is not there yet (config-agent racing
    the unit at boot) is cured by restarting, while a config that parses
    but can never validate is not — main.py exits 1 for the former and
    EX_CONFIG (78) for the latter.
    """


@dataclass
class ZookeeperConfig:
    servers: List[Tuple[str, int]]
    timeout_ms: int = 30000
    connect_timeout_ms: int = 4000
    chroot: Optional[str] = None
    #: per-operation deadline (``requestTimeout``, ms).  None (the
    #: default) = wait forever, the reference's behavior; when set, a
    #: stalled reply tears the connection down and the op fails with the
    #: retryable OPERATION_TIMEOUT (docs/FAULTS.md).
    request_timeout_ms: Optional[int] = None
    #: ``canBeReadOnly`` (ISSUE 10): allow the client to attach to a
    #: read-only ensemble member during quorum loss / partition so
    #: heartbeat and resolve reads keep answering; writes fail with the
    #: retryable NOT_READONLY until the rw-probe fails the session over.
    #: Default False = reference-exact handshake bytes.
    can_be_read_only: bool = False
    #: ``eventLoop`` (ISSUE 11): "uvloop" swaps the asyncio event loop
    #: for uvloop when (and only when) the package is importable —
    #: import-guarded, falls back to asyncio with a warning, byte-
    #: identical wire behavior either way (parity pinned).  None/
    #: "asyncio" = the stdlib loop, the default.
    event_loop: Optional[str] = None
    #: ``connectRaceStaggerMs`` (ISSUE 20): raced happy-eyeballs connect
    #: passes — candidate k dials this many ms after candidate k-1 and
    #: the first successful read-write handshake wins (losers aborted
    #: cleanly).  None = the serial reference-exact pass.
    connect_race_stagger_ms: Optional[int] = None
    #: ``pingIntervalMs`` / ``deadAfterMs`` (ISSUE 20): override the
    #: keepalive/watchdog schedule (default: ping every negotiated/3,
    #: dead after 2/3 with no frame) for sub-session-timeout failure
    #: detection.  None/None = reference-exact thirds rule.
    ping_interval_ms: Optional[int] = None
    dead_after_ms: Optional[int] = None


@dataclass
class MetricsConfig:
    port: int
    host: str = "127.0.0.1"


@dataclass
class CacheConfig:
    """The ``cache`` block (ISSUE 4): tuning for the watch-coherent
    resolve cache (:mod:`registrar_tpu.zkcache`).  Consumed by ``zkcli
    serve-view -f`` (the Binder's-eye watch loop); the daemon itself
    never resolves, so its behavior is untouched — absent block =
    feature defaults, reference parity exactly preserved."""

    max_entries: int = 4096
    #: ``staleMaxAgeS`` (ISSUE 20) — **seconds, not milliseconds** (the
    #: name carries the unit, like ``reconcile.intervalSeconds``):
    #: serve-stale bound for degraded mode.  While the cache's session
    #: is down it keeps answering from last-known-good entries for at
    #: most this long (RFC 8767 at the resolver path); past the bound —
    #: or on any authority restoration / terminal expiry — everything
    #: retained is flushed.  None = reference-exact flush-on-degrade;
    #: 0 = fail closed the moment authority is lost.
    stale_max_age_s: Optional[float] = None


@dataclass
class RestartConfig:
    """The ``restart`` block (ISSUE 5): zero-downtime restart behavior.

    ``mode: "handoff"`` keeps ``stateFile`` current (session id, passwd,
    negotiated timeout, znode manifest — see
    :mod:`registrar_tpu.statefile`) and a SIGTERM detaches the TCP
    connection WITHOUT closing the session, so the ephemerals survive for
    the successor process to reattach; ``mode: "drain"`` unregisters
    cleanly, waits ``drainGraceSeconds``, and exits 0.  Absent block =
    the pre-existing graceful stop (close the session, ephemerals deleted
    immediately) — reference-parity-adjacent default, unchanged."""

    state_file: str
    mode: str = "handoff"
    drain_grace_s: float = 0.0


@dataclass
class OverloadConfig:
    """The ``serve.overload`` block (ISSUE 17): overload armor for the
    sharded tier.  Every knob is optional and None = that defense off;
    the whole block absent = no armor anywhere, byte-identical to
    today's behavior (reference parity — the reference registrar has no
    serve tier, let alone admission control).

    ``maxQueueDepth``: bound on resolve requests dispatched-but-
    unanswered per worker (excess fast-fails ``SHED:queue_full``).
    ``maxInflightPerConn``: bound on resolve requests in flight per
    worker connection (same shed reason, per-socket).
    ``clientRateLimit``: per-client resolves/second token bucket at the
    router's front socket (``SHED:rate_limited``).
    ``coldFillConcurrency``: bound on concurrent distinct-path cold
    fills in each worker's cache (``SHED:cold_fill_shed``; warm domains
    degrade to bounded-age stale answers instead).
    ``writeDeadlineS``: reply write deadline — a peer that stops
    reading (slow-loris / half-open) is disconnected after this many
    seconds instead of pinning its handler tasks forever."""

    max_queue_depth: Optional[int] = None
    max_inflight_per_conn: Optional[int] = None
    client_rate_limit: Optional[float] = None
    cold_fill_concurrency: Optional[int] = None
    write_deadline_s: Optional[float] = None

    def as_router_kwargs(self) -> Dict[str, Any]:
        """The dict :class:`registrar_tpu.shard.ShardRouter` takes as
        ``overload=`` (spec-key spelling, Nones dropped)."""
        raw = {
            "maxQueueDepth": self.max_queue_depth,
            "maxInflightPerConn": self.max_inflight_per_conn,
            "clientRateLimit": self.client_rate_limit,
            "coldFillConcurrency": self.cold_fill_concurrency,
            "writeDeadlineS": self.write_deadline_s,
        }
        return {k: v for k, v in raw.items() if v is not None}


@dataclass
class DnsConfig:
    """The ``serve.dns`` block (ISSUE 19): the real-DNS frontend over
    the sharded tier (:mod:`registrar_tpu.dnsfront`).  Presence of the
    block turns it ON: every shard worker binds one SO_REUSEPORT UDP
    socket (plus a TCP listener for TC-bit retries) on ``host:port``.
    ``port: 0`` means the router allocates a free port once at start
    (every worker must share it for the kernel fan-out).  Absent block
    = no DNS sockets anywhere, the tier's behavior untouched.

    ``udpPayloadMax``: EDNS answer-size ceiling we honor (default 1232).
    ``negativeTtl``: NXDOMAIN/NODATA SOA-minimum TTL, seconds — defaults
    to the cache's coherence bound (5 s), never believe an absence
    longer than the tier itself would.
    ``staleTtl``: how long (seconds) a front whose ZKCache lost
    authority keeps answering from pre-rendered templates (RFC 8767
    serve-stale, default 30); ``0`` fails closed — templates drop the
    moment authority is lost.
    ``maxPending`` / ``rateLimit``: the PR-17 armor mapped onto DNS —
    pending cold-resolve bound and queries/second token bucket; over
    either, the front answers REFUSED (never silence).  Warm
    encode-cache hits bypass both."""

    host: str = "127.0.0.1"
    port: int = 0
    udp_payload_max: Optional[int] = None
    negative_ttl: Optional[float] = None
    stale_ttl: Optional[float] = None
    max_pending: Optional[int] = None
    rate_limit: Optional[float] = None

    def as_spec(self) -> Dict[str, Any]:
        """The dict a worker spec carries as ``dns`` (spec-key
        spelling, Nones dropped)."""
        raw = {
            "host": self.host,
            "port": self.port,
            "udpPayloadMax": self.udp_payload_max,
            "negativeTtl": self.negative_ttl,
            "staleTtl": self.stale_ttl,
            "maxPending": self.max_pending,
            "rateLimit": self.rate_limit,
        }
        return {k: v for k, v in raw.items() if v is not None}


@dataclass
class ServeConfig:
    """The ``serve`` block (ISSUE 12): the namespace-sharded resolve
    tier (:mod:`registrar_tpu.shard`), run standalone by ``zkcli
    serve-sharded -f config``.  ``shards`` worker processes each own a
    consistent-hash slice of the domain space; ``socketPath`` is the
    router's front unix socket (worker sockets are suffixed onto it);
    ``attachSpread`` is the watch-load placement hint handed to each
    worker's ZK client (``"spread"`` → worker k of n gets
    ``spread:k-of-n``; ``"follower"`` / ``"any"`` pass through);
    ``overload`` is the opt-in overload armor (ISSUE 17,
    :class:`OverloadConfig`).  The daemon itself never resolves and
    ignores the block — absent block = today's in-process behavior,
    reference parity untouched."""

    shards: int
    socket_path: str
    attach_spread: str = "spread"
    overload: Optional[OverloadConfig] = None
    dns: Optional[DnsConfig] = None


@dataclass
class ObservabilityConfig:
    """The ``observability`` block (ISSUE 8): operation tracing.

    Presence of the block turns tracing ON (spans, the flight recorder,
    latency histograms, trace-correlated log records, the SIGUSR2 dump).
    Absent block = tracing off, byte-identical log/metric output to the
    untraced daemon — reference parity exactly preserved."""

    sample_rate: float = 1.0
    #: slow-span warn threshold, ms (None = never warn).  The default
    #: sits above the registration pipeline's mandated 1 s settle floor
    #: so a healthy registration does not warn on every run.
    slow_span_ms: Optional[float] = 1500.0
    flight_recorder_spans: int = 1024
    #: SIGUSR2 dump target (None = pid-suffixed file in the temp dir)
    dump_path: Optional[str] = None


@dataclass
class ReconcileConfig:
    """The ``reconcile`` block: the level-triggered registration
    reconciler (ISSUE 3, :mod:`registrar_tpu.reconcile`).  NOTE the unit
    departure: ``intervalSeconds`` is SECONDS (the name carries the
    unit), unlike the reference-derived millisecond keys."""

    interval_s: float = 60.0
    repair: bool = False


#: top-level keys the daemon understands (reference keys + extensions);
#: anything else is reported in Config.unknown_keys so the mainline can
#: warn about probable typos ("healthcheck" vs "healthCheck") without
#: breaking the reference's ignore-unknown-keys behavior.
KNOWN_TOP_LEVEL_KEYS = frozenset(
    {
        "adminIp", "zookeeper", "registration", "healthCheck", "logLevel",
        "maxAttempts", "repairHeartbeatMiss", "metrics",
        "surviveSessionExpiry", "maxSessionRebirths", "reconcile", "cache",
        "restart", "observability", "serve",
    }
)


@dataclass
class Config:
    zookeeper: ZookeeperConfig
    registration: Dict[str, Any]
    admin_ip: Optional[str] = None
    health_check: Optional[Dict[str, Any]] = None  # seconds-based kwargs
    log_level: Optional[str] = None
    heartbeat_interval_s: float = 3.0
    heartbeat_retry: RetryPolicy = field(default_factory=lambda: HEARTBEAT_RETRY)
    repair_heartbeat_miss: bool = False
    metrics: Optional[MetricsConfig] = None
    #: opt-in session lifecycle supervisor (ISSUE 3): survive expiry by
    #: building a fresh session in-process instead of exit(1)
    survive_session_expiry: bool = False
    #: rebirth circuit-breaker bound (None = client default, 5 / 5 min)
    max_session_rebirths: Optional[int] = None
    #: opt-in level-triggered reconciler (ISSUE 3)
    reconcile: Optional[ReconcileConfig] = None
    #: resolve-cache tuning for zkcli serve-view (ISSUE 4; None = defaults)
    cache: Optional[CacheConfig] = None
    #: opt-in zero-downtime restart behavior (ISSUE 5; None = today's
    #: graceful stop: close the session, ephemerals deleted at once)
    restart: Optional[RestartConfig] = None
    #: opt-in operation tracing (ISSUE 8; None = no spans, no flight
    #: recorder, no trace-correlated log fields — reference parity)
    observability: Optional[ObservabilityConfig] = None
    #: opt-in namespace-sharded resolve tier for zkcli serve-sharded
    #: (ISSUE 12; None = no tier — the daemon ignores it either way)
    serve: Optional[ServeConfig] = None
    #: unrecognized top-level keys (ignored, like the reference — but
    #: surfaced so the daemon can warn about probable typos)
    unknown_keys: Tuple[str, ...] = ()
    #: the file this config was loaded from (None when parsed from a
    #: dict) — the SIGHUP reload re-reads it
    source_path: Optional[str] = None


def parse_config(raw: Mapping[str, Any]) -> Config:
    if not isinstance(raw, Mapping):
        raise ConfigError("config must be a JSON object")

    zk_raw = raw.get("zookeeper")
    if not isinstance(zk_raw, Mapping):
        raise ConfigError("config.zookeeper must be an object")
    servers_raw = zk_raw.get("servers")
    if not isinstance(servers_raw, list) or not servers_raw:
        raise ConfigError("config.zookeeper.servers must be a non-empty array")
    servers: List[Tuple[str, int]] = []
    for i, s in enumerate(servers_raw):
        if (
            not isinstance(s, Mapping)
            or not isinstance(s.get("host"), str)
            or not isinstance(s.get("port"), int)
            or isinstance(s.get("port"), bool)
        ):
            raise ConfigError(
                f"config.zookeeper.servers[{i}] must be {{host, port}}"
            )
        servers.append((s["host"], s["port"]))
    chroot = zk_raw.get("chroot")
    if chroot is not None:
        # Same validation ZKClient applies at startup (zk.protocol
        # check_path), so the -n pre-flight and the daemon agree on what
        # is acceptable.
        from registrar_tpu.zk.protocol import check_path

        if not isinstance(chroot, str):
            raise ConfigError(
                "config.zookeeper.chroot must be an absolute znode path"
            )
        try:
            check_path(chroot)
        except ValueError as e:
            raise ConfigError(f"config.zookeeper.chroot: {e}") from e
        if chroot == "/":
            chroot = None
    can_be_read_only = zk_raw.get("canBeReadOnly", False)
    if not isinstance(can_be_read_only, bool):
        raise ConfigError("config.zookeeper.canBeReadOnly must be a boolean")
    event_loop = zk_raw.get("eventLoop")
    if event_loop is not None and event_loop not in ("asyncio", "uvloop"):
        raise ConfigError(
            'config.zookeeper.eventLoop must be "asyncio" or "uvloop"'
        )
    zookeeper = ZookeeperConfig(
        servers=servers,
        timeout_ms=_ms(zk_raw, "timeout", 30000),
        connect_timeout_ms=_ms(zk_raw, "connectTimeout", 4000),
        chroot=chroot,
        request_timeout_ms=_optional_ms(zk_raw, "requestTimeout"),
        can_be_read_only=can_be_read_only,
        event_loop=event_loop,
        connect_race_stagger_ms=_optional_ms(zk_raw, "connectRaceStaggerMs"),
        ping_interval_ms=_optional_ms(zk_raw, "pingIntervalMs"),
        dead_after_ms=_optional_ms(zk_raw, "deadAfterMs"),
    )

    registration = raw.get("registration")
    if not isinstance(registration, Mapping):
        raise ConfigError("config.registration must be an object")
    registration = dict(registration)

    # Back-compat shim: top-level adminIp hoisted into the registration
    # (reference main.js:146-147).
    admin_ip = registration.get("adminIp") or raw.get("adminIp")
    if admin_ip is not None and not isinstance(admin_ip, str):
        raise ConfigError("config.adminIp must be a string")

    heartbeat_interval_s = (
        _ms(registration, "heartbeatInterval", 3000) / 1000.0
    )
    registration.pop("heartbeatInterval", None)
    registration.pop("adminIp", None)

    health_check = None
    hc_raw = raw.get("healthCheck")
    if hc_raw is not None:
        if not isinstance(hc_raw, Mapping):
            raise ConfigError("config.healthCheck must be an object")
        if not isinstance(hc_raw.get("command"), str) or not hc_raw["command"]:
            raise ConfigError("config.healthCheck.command must be a string")
        threshold = hc_raw.get("threshold", 5)
        if (
            not isinstance(threshold, int)
            or isinstance(threshold, bool)
            or threshold < 1
        ):
            # Validated here (not only in HealthCheck.__init__) so a typo
            # like "threshold": "5" fails the -n pre-flight with EX_CONFIG
            # instead of killing the health consumer task at runtime.
            raise ConfigError(
                "config.healthCheck.threshold must be a positive integer"
            )
        health_check = {
            "command": hc_raw["command"],
            "interval": _ms(hc_raw, "interval", 60000) / 1000.0,
            "timeout": _ms(hc_raw, "timeout", 1000) / 1000.0,
            "period": _ms(hc_raw, "period", 300000) / 1000.0,
            "threshold": threshold,
            "ignore_exit_status": bool(hc_raw.get("ignoreExitStatus", False)),
        }
        if hc_raw.get("stdoutMatch") is not None:
            sm = hc_raw["stdoutMatch"]
            # Validate with the exact code the checker runs (pattern
            # compiles, flags supported, shape right), so a config that
            # passes -n can never throw when the daemon builds the checker.
            from registrar_tpu.health import _compile_stdout_match

            if (
                not isinstance(sm, Mapping)
                or not isinstance(sm.get("pattern"), str)
                or not sm["pattern"]  # "" would silently disable matching
            ):
                raise ConfigError(
                    "config.healthCheck.stdoutMatch must be "
                    "{pattern, flags?, invert?} with a non-empty pattern"
                )
            if "invert" in sm and not isinstance(sm["invert"], bool):
                # "false" (a string) is truthy — it would silently flip
                # the match and declare a healthy service down
                raise ConfigError(
                    "config.healthCheck.stdoutMatch.invert must be a boolean"
                )
            if "flags" in sm and not isinstance(sm["flags"], str):
                raise ConfigError(
                    "config.healthCheck.stdoutMatch.flags must be a string"
                )
            try:
                _compile_stdout_match(sm)
            except (ValueError, TypeError, re.error) as e:
                raise ConfigError(
                    f"config.healthCheck.stdoutMatch: {e}"
                ) from e
            health_check["stdout_match"] = sm

    log_level = raw.get("logLevel")
    if log_level is not None and not isinstance(log_level, str):
        raise ConfigError("config.logLevel must be a string")

    max_attempts = raw.get("maxAttempts")
    if max_attempts is not None and (
        not isinstance(max_attempts, int)
        or isinstance(max_attempts, bool)
        or max_attempts < 1
    ):
        raise ConfigError("config.maxAttempts must be a positive integer")
    heartbeat_retry = (
        RetryPolicy(
            max_attempts=max_attempts,
            initial_delay=HEARTBEAT_RETRY.initial_delay,
            max_delay=HEARTBEAT_RETRY.max_delay,
        )
        if max_attempts is not None
        else HEARTBEAT_RETRY
    )

    repair = raw.get("repairHeartbeatMiss", False)
    if not isinstance(repair, bool):
        raise ConfigError("config.repairHeartbeatMiss must be a boolean")

    survive = raw.get("surviveSessionExpiry", False)
    if not isinstance(survive, bool):
        raise ConfigError("config.surviveSessionExpiry must be a boolean")

    max_rebirths = raw.get("maxSessionRebirths")
    if max_rebirths is not None and (
        not isinstance(max_rebirths, int)
        or isinstance(max_rebirths, bool)
        or max_rebirths < 1
    ):
        raise ConfigError("config.maxSessionRebirths must be a positive integer")

    reconcile = None
    rec_raw = raw.get("reconcile")
    if rec_raw is not None:
        if not isinstance(rec_raw, Mapping):
            raise ConfigError("config.reconcile must be an object")
        interval = rec_raw.get("intervalSeconds", 60)
        if (
            not isinstance(interval, (int, float))
            or isinstance(interval, bool)
            or not math.isfinite(interval)
            or interval <= 0
        ):
            raise ConfigError(
                "config.reconcile.intervalSeconds must be a positive "
                "number (seconds)"
            )
        rec_repair = rec_raw.get("repair", False)
        if not isinstance(rec_repair, bool):
            raise ConfigError("config.reconcile.repair must be a boolean")
        reconcile = ReconcileConfig(
            interval_s=float(interval), repair=rec_repair
        )

    cache = None
    cache_raw = raw.get("cache")
    if cache_raw is not None:
        if not isinstance(cache_raw, Mapping):
            raise ConfigError("config.cache must be an object")
        max_entries = cache_raw.get("maxEntries", 4096)
        if (
            not isinstance(max_entries, int)
            or isinstance(max_entries, bool)
            or max_entries < 1
        ):
            raise ConfigError(
                "config.cache.maxEntries must be a positive integer"
            )
        stale_max_age = cache_raw.get("staleMaxAgeS")
        if stale_max_age is not None and (
            not isinstance(stale_max_age, (int, float))
            or isinstance(stale_max_age, bool)
            or not math.isfinite(stale_max_age)
            or stale_max_age < 0
        ):
            raise ConfigError(
                "config.cache.staleMaxAgeS must be a non-negative number "
                "of seconds"
            )
        cache = CacheConfig(
            max_entries=max_entries,
            stale_max_age_s=(
                None if stale_max_age is None else float(stale_max_age)
            ),
        )

    restart = None
    restart_raw = raw.get("restart")
    if restart_raw is not None:
        if not isinstance(restart_raw, Mapping):
            raise ConfigError("config.restart must be an object")
        state_file = restart_raw.get("stateFile")
        if not isinstance(state_file, str) or not state_file:
            raise ConfigError(
                "config.restart.stateFile must be a non-empty path"
            )
        mode = restart_raw.get("mode", "handoff")
        if mode not in ("handoff", "drain"):
            raise ConfigError(
                'config.restart.mode must be "handoff" or "drain"'
            )
        grace = restart_raw.get("drainGraceSeconds", 0)
        if (
            not isinstance(grace, (int, float))
            or isinstance(grace, bool)
            or not math.isfinite(grace)
            or grace < 0
        ):
            raise ConfigError(
                "config.restart.drainGraceSeconds must be a non-negative "
                "number (seconds)"
            )
        restart = RestartConfig(
            state_file=state_file, mode=mode, drain_grace_s=float(grace)
        )

    observability = None
    obs_raw = raw.get("observability")
    if obs_raw is not None:
        if not isinstance(obs_raw, Mapping):
            raise ConfigError("config.observability must be an object")
        sample_rate = obs_raw.get("sampleRate", 1.0)
        if (
            not isinstance(sample_rate, (int, float))
            or isinstance(sample_rate, bool)
            or not math.isfinite(sample_rate)
            or not 0.0 <= sample_rate <= 1.0
        ):
            raise ConfigError(
                "config.observability.sampleRate must be a number in [0, 1]"
            )
        slow_span = obs_raw.get("slowSpanMs", 1500)
        if slow_span is not None and (
            not isinstance(slow_span, (int, float))
            or isinstance(slow_span, bool)
            or not math.isfinite(slow_span)
            or slow_span <= 0
        ):
            raise ConfigError(
                "config.observability.slowSpanMs must be a positive number "
                "(ms) or null to disable slow-span warnings"
            )
        recorder_spans = obs_raw.get("flightRecorderSpans", 1024)
        if (
            not isinstance(recorder_spans, int)
            or isinstance(recorder_spans, bool)
            or recorder_spans < 1
        ):
            raise ConfigError(
                "config.observability.flightRecorderSpans must be a "
                "positive integer"
            )
        dump_path = obs_raw.get("dumpPath")
        if dump_path is not None and (
            not isinstance(dump_path, str) or not dump_path
        ):
            raise ConfigError(
                "config.observability.dumpPath must be a non-empty path"
            )
        observability = ObservabilityConfig(
            sample_rate=float(sample_rate),
            slow_span_ms=float(slow_span) if slow_span is not None else None,
            flight_recorder_spans=recorder_spans,
            dump_path=dump_path,
        )

    serve = None
    serve_raw = raw.get("serve")
    if serve_raw is not None:
        if not isinstance(serve_raw, Mapping):
            raise ConfigError("config.serve must be an object")
        shards = serve_raw.get("shards")
        if (
            not isinstance(shards, int)
            or isinstance(shards, bool)
            or shards < 1
        ):
            raise ConfigError(
                "config.serve.shards must be a positive integer"
            )
        socket_path = serve_raw.get("socketPath")
        if not isinstance(socket_path, str) or not socket_path:
            raise ConfigError(
                "config.serve.socketPath must be a non-empty path"
            )
        attach_spread = serve_raw.get("attachSpread", "spread")
        if attach_spread not in ("any", "follower", "spread"):
            raise ConfigError(
                'config.serve.attachSpread must be "any", "follower", '
                'or "spread"'
            )
        overload = None
        overload_raw = serve_raw.get("overload")
        if overload_raw is not None:
            if not isinstance(overload_raw, Mapping):
                raise ConfigError("config.serve.overload must be an object")

            def _overload_int(key: str, value) -> Optional[int]:
                if value is None:
                    return None
                if (
                    not isinstance(value, int)
                    or isinstance(value, bool)
                    or value < 1
                ):
                    raise ConfigError(
                        f"config.serve.overload.{key} must be a "
                        "positive integer"
                    )
                return value

            def _overload_num(key: str, value) -> Optional[float]:
                if value is None:
                    return None
                if (
                    not isinstance(value, (int, float))
                    or isinstance(value, bool)
                    or value <= 0
                ):
                    raise ConfigError(
                        f"config.serve.overload.{key} must be a "
                        "positive number"
                    )
                return float(value)

            overload = OverloadConfig(
                max_queue_depth=_overload_int(
                    "maxQueueDepth", overload_raw.get("maxQueueDepth")
                ),
                max_inflight_per_conn=_overload_int(
                    "maxInflightPerConn",
                    overload_raw.get("maxInflightPerConn"),
                ),
                client_rate_limit=_overload_num(
                    "clientRateLimit", overload_raw.get("clientRateLimit")
                ),
                cold_fill_concurrency=_overload_int(
                    "coldFillConcurrency",
                    overload_raw.get("coldFillConcurrency"),
                ),
                write_deadline_s=_overload_num(
                    "writeDeadlineS", overload_raw.get("writeDeadlineS")
                ),
            )
        dns = None
        dns_raw = serve_raw.get("dns")
        if dns_raw is not None:
            if not isinstance(dns_raw, Mapping):
                raise ConfigError("config.serve.dns must be an object")
            dns_host = dns_raw.get("host", "127.0.0.1")
            if not isinstance(dns_host, str) or not dns_host:
                raise ConfigError("config.serve.dns.host must be a string")
            dns_port = dns_raw.get("port", 0)
            if (
                not isinstance(dns_port, int)
                or isinstance(dns_port, bool)
                or not 0 <= dns_port < 65536
            ):
                raise ConfigError(
                    "config.serve.dns.port must be a port number "
                    "(0 = allocate at start)"
                )

            def _dns_int(key: str, value) -> Optional[int]:
                if value is None:
                    return None
                if (
                    not isinstance(value, int)
                    or isinstance(value, bool)
                    or value < 1
                ):
                    raise ConfigError(
                        f"config.serve.dns.{key} must be a positive integer"
                    )
                return value

            def _dns_num(key: str, value) -> Optional[float]:
                if value is None:
                    return None
                if (
                    not isinstance(value, (int, float))
                    or isinstance(value, bool)
                    or value <= 0
                ):
                    raise ConfigError(
                        f"config.serve.dns.{key} must be a positive number"
                    )
                return float(value)

            udp_payload_max = _dns_int(
                "udpPayloadMax", dns_raw.get("udpPayloadMax")
            )
            if udp_payload_max is not None and udp_payload_max < 512:
                raise ConfigError(
                    "config.serve.dns.udpPayloadMax must be >= 512 "
                    "(the pre-EDNS UDP ceiling)"
                )
            stale_ttl = dns_raw.get("staleTtl")
            if stale_ttl is not None:
                # Unlike the other dns numbers, 0 is meaningful here:
                # "no serve-stale window, fail closed on authority loss".
                if (
                    not isinstance(stale_ttl, (int, float))
                    or isinstance(stale_ttl, bool)
                    or stale_ttl < 0
                ):
                    raise ConfigError(
                        "config.serve.dns.staleTtl must be a "
                        "non-negative number"
                    )
                stale_ttl = float(stale_ttl)
            dns = DnsConfig(
                host=dns_host,
                port=dns_port,
                udp_payload_max=udp_payload_max,
                negative_ttl=_dns_num(
                    "negativeTtl", dns_raw.get("negativeTtl")
                ),
                stale_ttl=stale_ttl,
                max_pending=_dns_int("maxPending", dns_raw.get("maxPending")),
                rate_limit=_dns_num("rateLimit", dns_raw.get("rateLimit")),
            )
        serve = ServeConfig(
            shards=shards,
            socket_path=socket_path,
            attach_spread=attach_spread,
            overload=overload,
            dns=dns,
        )

    metrics = None
    metrics_raw = raw.get("metrics")
    if metrics_raw is not None:
        if not isinstance(metrics_raw, Mapping):
            raise ConfigError("config.metrics must be an object")
        port = metrics_raw.get("port")
        if (
            not isinstance(port, int)
            or isinstance(port, bool)
            or not 0 < port < 65536
        ):
            raise ConfigError("config.metrics.port must be a port number")
        host = metrics_raw.get("host", "127.0.0.1")
        if not isinstance(host, str) or not host:
            raise ConfigError("config.metrics.host must be a string")
        metrics = MetricsConfig(port=port, host=host)

    return Config(
        zookeeper=zookeeper,
        registration=registration,
        admin_ip=admin_ip,
        health_check=health_check,
        log_level=log_level,
        heartbeat_interval_s=heartbeat_interval_s,
        heartbeat_retry=heartbeat_retry,
        repair_heartbeat_miss=repair,
        metrics=metrics,
        survive_session_expiry=survive,
        max_session_rebirths=max_rebirths,
        reconcile=reconcile,
        cache=cache,
        restart=restart,
        observability=observability,
        serve=serve,
        unknown_keys=tuple(
            sorted(set(raw) - KNOWN_TOP_LEVEL_KEYS)
        ),
    )


def load_config(path: str) -> Config:
    """Read + parse the JSON config at ``path`` (reference main.js:57-62)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    except OSError as e:
        raise ConfigUnreadableError(
            f"unable to read configuration {path}: {e}"
        ) from e
    except json.JSONDecodeError as e:
        raise ConfigError(f"unable to parse configuration {path}: {e}") from e
    cfg = parse_config(raw)
    cfg.source_path = path
    return cfg


def _optional_ms(obj: Mapping[str, Any], key: str) -> Optional[int]:
    """:func:`_ms` for keys with no default at all: absent (or JSON null)
    means the feature is off, never a fallback number."""
    if obj.get(key) is None:
        return None
    return _ms(obj, key, obj[key])  # default unreachable: key is present


def _ms(obj: Mapping[str, Any], key: str, default: int) -> int:
    value = obj.get(key, default)
    if (
        not isinstance(value, (int, float))
        or isinstance(value, bool)
        or not math.isfinite(value)
        or value <= 0
    ):
        raise ConfigError(f"config {key} must be a positive number (ms)")
    return int(value)
