"""Namespace-sharded serve tier (ISSUE 12): near-linear multi-core resolve scaling.

One asyncio loop caps the cached resolve path at the single-core ceiling
(BENCH_BASELINE ``cached_resolve_qps_50_instances``) no matter how many
cores the box has.  This module partitions the DNS namespace across
worker *processes* the same way a ``PartitionSpec`` partitions an array
(ROADMAP item 2, the one transferable idea from the related sharding
material): each :class:`ShardWorker` owns a slice of the domain space —
its own event loop, its own ZooKeeper session, its own watch-coherent
:class:`~registrar_tpu.zkcache.ZKCache` — and, against a multi-member
ensemble, attaches its watch load to a *distinct* follower
(``attach_preference``), so read capacity scales with both cores and
ensemble size.

Topology::

    client ──UDS──> ShardRouter ──UDS──> ShardWorker[k]   (relay path)
    client ──UDS──────────────────────> ShardWorker[k]    (direct path)

The parent :class:`ShardRouter` consistent-hashes domains across N
workers (:class:`HashRing`, deterministic BLAKE2 points — stable across
process restarts), supervises them (a crashed worker is respawned while
its siblings keep serving their slices), and fronts them over a
length-prefixed unix-domain-socket resolve protocol sized for the future
DNS frontend:

  * **the router never copies answers** — a worker serializes each
    :class:`~registrar_tpu.binderview.Resolution` exactly once, and the
    router forwards those bytes verbatim (it parses only the fixed
    reply header to demultiplex);
  * **the router never caches** — a worker's answer is watch-coherent
    because its cache armed watches with the read; a second cache in
    the router would re-open exactly the arm-then-read window ZKCache
    closes (docs/DESIGN.md "Sharded serve tier");
  * **the ring is a performance hint, not a correctness boundary** —
    any worker answers any domain correctly (ZKCache is read-through),
    so a request that races a reshard to the old owner still gets the
    right answer.  That is what makes resharding zero-error, and it is
    the same property SO_REUSEPORT will lean on when the DNS frontend
    lands (the kernel, like the ring, only balances);
  * smart clients (the future DNS data plane, bench.py) fetch the ring
    (``OP_RING``) and talk to workers directly — the router stays the
    control plane + supervisor, exactly the SO_REUSEPORT shape.

Resharding is a first-class operation: a SIGHUP shard-count change
(``zkcli serve-sharded``) moves only ~K/N of K warm domains (consistent
hashing), and the warm set of every domain that changes owner is handed
to the new owner *by name* (``OP_DUMP`` → ``OP_WARM``): the new owner
pre-resolves each handed-off domain through its own session **before**
the ring flips, so a reshard never cold-starts the tier.  Names, not
cached bytes, are what move — an imported entry would be watch-orphaned
(its one-shot watches live on the departing worker's dying session),
which would silently break the coherence bound; a pre-resolve arms
fresh watches with the read, exactly like any other fill.

Wire protocol (all integers big-endian)::

    frame   := len:u32  payload
    request := req_id:u32  op:u8  [trace_ctx]  body
    reply   := req_id:u32  status:u8  [worker_us:u32]  body
                                                # status 0 = OK, 1 = error

    OP_RESOLVE  body = flags:u8 (bit0: live read)  qlen:u8  qtype  name
                reply body = compact JSON {"a": [[name, rtype, ttl,
                data], ...], "ad": [...]} (answers / additionals)
    OP_STATUS   reply body = per-worker status JSON (router: aggregate)
    OP_RING     (router only) reply = {"generation", "shards": [{"shard",
                "socket"}, ...]}
    OP_DUMP     (worker) reply = {"warm": [[name, qtype], ...]}
    OP_WARM     (worker) body = {"names": [[name, qtype], ...]};
                pre-resolves each, reply = {"warmed": N}
    OP_TRACE    body = {"trace_id": hex, "n"?: int}; worker reply = its
                flight recorder filtered to that trace (+ shard, pid);
                router reply = the ASSEMBLED cross-process tree
                (:mod:`registrar_tpu.traceview`)

**Trace-context extension (ISSUE 13).**  The :data:`TRACE_FLAG` bit on
the op byte gates a fixed ``trace_id:u64 + parent_span_id:u64 +
sampled:u8`` block between header and body — with tracing off not a
bit moves and every frame is byte-identical to the PR-12 format (pinned
by the golden parity test).  Clients inject the ambient span's context
(:func:`registrar_tpu.trace.current_context`), the router adopts it as
the parent of its ``shard.relay`` span and re-injects THAT span's
context toward the owning worker, and the worker adopts in turn so its
``resolve.query``/``cache.fill``/``zk.op`` subtree chains under the
relay — one trace id from resolver to znode.  A reply to a traced
request carries the same flag bit on the status byte gating a
``worker_us:u32`` block: the REMOTE PEER's self-reported handling time
(a worker reports its dispatch; the router, answering its own front
socket, reports the whole relay window), stamped on the requester's
span as the ``worker`` mark — so the router's relay span splits into
router-queue (the ``forwarded`` mark), socket, and worker time, the
sharded analog of PR 8's zk.op queue-vs-wire split.

Used by ``zkcli serve-sharded -f config`` (config block ``serve:
{shards, socketPath, attachSpread}``; absent block = today's in-process
behavior), benchmarked by bench.py (``sharded_resolve_qps_*``,
``reshard_warm_handoff_ms``), fault-injected by the SLO harness
(``shard-kill`` / ``reshard-wave``), and rolled up into metrics by
:func:`registrar_tpu.metrics.instrument_shards`.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import json
import logging
import os
import signal
import struct
import subprocess
import sys
import time
from contextlib import nullcontext
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from registrar_tpu import binderview, dnsfront, malformed, trace, traceview
from registrar_tpu.binderview import Answer, Resolution
from registrar_tpu.events import EventEmitter, spawn_owned
from registrar_tpu.retry import RetryPolicy, is_transient
from registrar_tpu.zk.client import ZKClient, connect_with_backoff
from registrar_tpu.zkcache import (
    CacheOverloadError, DEFAULT_MAX_ENTRIES, ZKCache,
)

log = logging.getLogger("registrar_tpu.shard")

#: shared reusable no-op context manager (nullcontext is reentrant and
#: stateless) — the untraced request path pays no per-request allocation
NULLCTX = nullcontext()

OP_RESOLVE = 1
OP_STATUS = 2
OP_RING = 3
OP_DUMP = 4
OP_WARM = 5
OP_TRACE = 6

STATUS_OK = 0
STATUS_ERR = 1

#: high bit of the op byte (request) / status byte (reply): a
#: trace-context block (request) or worker_us block (reply) follows the
#: header.  A bit, not a new frame layout, so tracing-off frames stay
#: byte-identical to the PR-12 wire format (module docstring).
TRACE_FLAG = 0x80

#: request/reply fixed header past the length prefix: req_id:u32 + op/status:u8
_HDR = struct.Struct(">IB")

#: the optional trace-context block: trace_id:u64 parent_span_id:u64 sampled:u8
_TRACE_CTX = struct.Struct(">QQB")

#: the optional traced-reply block: the worker's handling time in µs
_WORKER_US = struct.Struct(">I")

#: frame size bound — an answer set is a few KiB; anything bigger is a
#: protocol error, not a legitimate resolution (guards readexactly from
#: a corrupt length prefix commanding a gigabyte allocation)
MAX_FRAME = 4 << 20

#: virtual nodes per shard on the ring: enough for ±small-percent slice
#: balance at single-digit shard counts while keeping ring construction
#: trivially cheap (N*vnodes 8-byte points)
DEFAULT_VNODES = 64

#: worker spawn → socket-answering readiness budget (interpreter start +
#: ZK connect + bind); generous because CI boxes cold-start Python slowly
READY_TIMEOUT_S = 20.0

#: staleness bound for a worker's last-known-good fallback answers —
#: DNS-TTL scale (the tier's default answer TTL is 30 s; an answer that
#: age is one Binder would still be serving from its own cache)
DEFAULT_MAX_STALE_S = 30.0


class ShardError(Exception):
    """A sharded-tier request failed (worker down, protocol error)."""


#: wire marker for a deliberate overload reject: the STATUS_ERR body is
#: ``SHED:<reason>[ <detail>]``.  A prefix on the existing error body —
#: not a new status code — so every PR-12 peer (and the router's
#: verbatim error forwarding) carries it unchanged, while armor-aware
#: clients can tell "the tier refused fast" from "the tier broke".
SHED_PREFIX = b"SHED:"

#: the shed-reason taxonomy (docs/OPERATIONS.md "Overload"): the label
#: vocabulary of registrar_shed_total and the first word of every
#: SHED: reject body.  queue_full = worker admission (dispatch backlog
#: or per-connection in-flight bound), rate_limited = the router's
#: per-client token bucket, cold_fill_shed = ZKCache's bounded cold-fill
#: concurrency, slow_client = a reply write deadline expired (slow-loris
#: / half-open peer disconnected).
SHED_REASONS = ("queue_full", "rate_limited", "cold_fill_shed", "slow_client")


class ShardShedError(ShardError):
    """A request the overload armor deliberately rejected (fast-fail —
    the reply came back immediately, it did NOT time out).  ``reason``
    is one of :data:`SHED_REASONS`; callers that want to degrade (serve
    stale, back off, retry elsewhere) can catch this narrower class
    while plain :class:`ShardError` keeps meaning "broken"."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        self.detail = detail
        super().__init__(
            f"shed [{reason}]{' ' + detail if detail else ''}"
        )

    def payload(self) -> bytes:
        return shed_body(self.reason, self.detail)


def shed_body(reason: str, detail: str = "") -> bytes:
    """The wire body of a shed reject (STATUS_ERR + this)."""
    out = SHED_PREFIX + reason.encode("ascii")
    if detail:
        out += b" " + detail.encode("utf-8", "replace")
    return out


def shed_reason(body) -> Optional[str]:
    """The shed reason inside a STATUS_ERR body, or None if the error
    is not a shed reject (the client-side classifier)."""
    raw = bytes(body)
    if not raw.startswith(SHED_PREFIX):
        return None
    return (
        raw[len(SHED_PREFIX):].split(b" ", 1)[0].decode("ascii", "replace")
    )


def _opt_int(raw) -> Optional[int]:
    """An optional spec knob: None stays None (unbounded), anything
    else must coerce to int — a typo'd bound must fail the spawn, not
    silently disable the armor it claimed to configure."""
    return None if raw is None else int(raw)


# ---------------------------------------------------------------------------
# Consistent hashing
# ---------------------------------------------------------------------------


def _point(key: str) -> int:
    """Deterministic 64-bit ring coordinate.  BLAKE2, not ``hash()``:
    Python string hashing is salted per process, and the ring MUST be
    stable across process restarts (a restarted router that re-derived a
    different ring would orphan every worker's warm slice)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring over shard ids.

    ``vnodes`` virtual points per shard: adding or removing one shard
    moves only ~K/N of K keys (the resharding bound bench.py and
    tests/test_shard.py pin), and the points are pure functions of the
    shard id — two processes building a ring over the same ids agree on
    every owner.
    """

    def __init__(self, shard_ids: Iterable[int], vnodes: int = DEFAULT_VNODES):
        self.shard_ids = tuple(sorted(shard_ids))
        if not self.shard_ids:
            raise ValueError("a ring needs at least one shard")
        self.vnodes = vnodes
        points: List[Tuple[int, int]] = []
        for sid in self.shard_ids:
            for v in range(vnodes):
                points.append((_point(f"shard:{sid}#{v}"), sid))
        points.sort()
        self._hashes = [p[0] for p in points]
        self._owners = [p[1] for p in points]

    def owner(self, name: str) -> int:
        """The shard id owning ``name`` (domains are case-normalized by
        the resolve path before they get here)."""
        h = _point(name)
        i = bisect.bisect_right(self._hashes, h)
        if i == len(self._hashes):
            i = 0
        return self._owners[i]

    def moved(self, other: "HashRing", names: Iterable[str]) -> List[str]:
        """The subset of ``names`` whose owner differs under ``other`` —
        the resharding movement set (deterministic, so the bound tests
        pin is a fact, not a distribution)."""
        return [n for n in names if self.owner(n) != other.owner(n)]


# ---------------------------------------------------------------------------
# Framing + resolution serialization
# ---------------------------------------------------------------------------


def pack_frame(req_id: int, code: int, body) -> bytes:
    """One wire frame: length prefix + header + body."""
    return (
        struct.pack(">I", _HDR.size + len(body))
        + _HDR.pack(req_id, code)
        + bytes(body)
    )


def split_traced(frame, op: int):
    """Split an incoming request's optional trace-context block:
    ``(op, ctx, body)``.  A flagged frame too short for the block is a
    protocol error raised as :class:`ShardError` — the caller answers
    STATUS_ERR; it must never become a dead handler task that leaves
    the requester waiting forever."""
    if not op & TRACE_FLAG:
        return op, None, memoryview(frame)[_HDR.size:]
    if len(frame) < _HDR.size + _TRACE_CTX.size:
        malformed.note("shard")
        raise ShardError(
            f"traced frame too short for context block ({len(frame)})"
        )
    ctx = _TRACE_CTX.unpack_from(frame, _HDR.size)
    body = memoryview(frame)[_HDR.size + _TRACE_CTX.size:]
    return op & ~TRACE_FLAG & 0xFF, ctx, body


async def _answer_protocol_error(writer, req_id: int, err: Exception) -> None:
    """Answer a malformed frame with STATUS_ERR — shared by the worker
    and the router so the two peers' protocol-error behavior can never
    drift (a dead handler task would leave the requester, whose future
    has no timeout, waiting forever)."""
    try:
        writer.write(pack_frame(req_id, STATUS_ERR, repr(err).encode()))
        await writer.drain()
    except (ConnectionError, OSError):
        pass


def stamp_traced_reply(status: int, reply, t0: float) -> Tuple[int, bytes]:
    """The traced-reply extension, one copy for every hop: flag the
    status byte and prepend this peer's self-reported handling time
    (µs since ``t0``).  Gated by the caller on the REQUEST having
    carried context, so untraced peers never see the flag."""
    us = min(int((time.monotonic() - t0) * 1e6), 0xFFFFFFFF)
    return status | TRACE_FLAG, _WORKER_US.pack(us) + bytes(reply)


def pack_request(
    req_id: int, op: int, body, trace_ctx: Optional[Tuple] = None
) -> bytes:
    """One request frame.  Without ``trace_ctx`` this is byte-for-byte
    :func:`pack_frame` — the tracing-off parity the golden wire test
    pins; with a ``(trace_id, parent_span_id, sampled)`` int triple the
    op byte's :data:`TRACE_FLAG` bit gates the fixed context block
    between header and body."""
    if trace_ctx is None:
        return pack_frame(req_id, op, body)
    return (
        struct.pack(">I", _HDR.size + _TRACE_CTX.size + len(body))
        + _HDR.pack(req_id, op | TRACE_FLAG)
        + _TRACE_CTX.pack(*trace_ctx)
        + bytes(body)
    )


def pack_resolve(name: str, qtype: str = "A", live: bool = False) -> bytes:
    """An OP_RESOLVE request body."""
    qb = qtype.encode("ascii")
    return bytes((1 if live else 0, len(qb))) + qb + name.encode("utf-8")


def resolve_name(body) -> str:
    """The domain inside an OP_RESOLVE body — all the router ever parses
    of a resolve request (it hashes the name and forwards the body).

    Rejects malformed bodies as :class:`ShardError` — the single
    contract class the relay path answers with STATUS_ERR (a hostile
    qtype length must bound-check against the body, not silently slice
    past it)."""
    if len(body) < 2:
        malformed.note("shard")
        raise ShardError(f"resolve body too short ({len(body)} bytes)")
    qlen = body[1]
    if 2 + qlen > len(body):
        malformed.note("shard")
        raise ShardError(
            f"resolve qtype length {qlen} overruns body ({len(body)} bytes)"
        )
    try:
        return bytes(body[2 + qlen:]).decode("utf-8")
    except UnicodeDecodeError as err:
        malformed.note("shard")
        raise ShardError(f"resolve name not UTF-8: {err}") from err


def encode_resolution(res: Resolution) -> bytes:
    """Serialize a Resolution ONCE, worker-side; the router and direct
    clients forward/parse these bytes without the worker's involvement."""
    return json.dumps(
        {
            "a": [[a.name, a.rtype, a.ttl, a.data] for a in res.answers],
            "ad": [[a.name, a.rtype, a.ttl, a.data] for a in res.additionals],
        },
        separators=(",", ":"),
    ).encode("utf-8")


def decode_resolution(body) -> Resolution:
    raw = json.loads(bytes(body).decode("utf-8"))
    return Resolution(
        answers=[Answer(*row) for row in raw.get("a", ())],
        additionals=[Answer(*row) for row in raw.get("ad", ())],
    )


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    """One length-prefixed frame, or None on clean EOF at a boundary."""
    try:
        head = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    (size,) = struct.unpack(">I", head)
    if size < _HDR.size or size > MAX_FRAME:
        malformed.note("shard")
        raise ShardError(f"bad frame length {size}")
    try:
        return await reader.readexactly(size)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None


class Channel:
    """One multiplexed request/reply connection (client→router and
    router→worker both ride this): requests carry a channel-local req_id
    and replies resolve the matching future, so any number of requests
    can be in flight and replies may land out of order (a worker
    dispatches each request as its own task — a cold live fill never
    head-of-line-blocks warm answers behind it)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer
        self._next_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop()
        )

    @classmethod
    async def open(cls, socket_path: str) -> "Channel":
        reader, writer = await asyncio.open_unix_connection(socket_path)
        return cls(reader, writer)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def pending(self) -> int:
        return len(self._pending)

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await _read_frame(self._reader)
                if frame is None:
                    break
                req_id, status = _HDR.unpack_from(frame)
                fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    # The body is a view into this frame's buffer: the
                    # relay path writes it back out without a copy.
                    fut.set_result((status, memoryview(frame)[_HDR.size:]))
        except asyncio.CancelledError:
            raise  # close() cancelled us; finally still fails the waiters
        except (ShardError, OSError):
            pass
        finally:
            self._fail_pending(ShardError("shard connection lost"))
            self._closed = True

    def _fail_pending(self, err: Exception) -> None:
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(err)
                # Mark retrieved: a waiter whose task was cancelled (a
                # probe torn down mid-flight) never awaits this future,
                # and the GC warning would point at the wrong culprit.
                fut.exception()

    async def request(
        self,
        op: int,
        body,
        trace_ctx: Optional[Tuple] = None,
        span=None,
    ) -> Tuple[int, memoryview]:
        """Send one request; await ``(status, body_view)``.

        ``trace_ctx`` (a :func:`registrar_tpu.trace.current_context`
        triple) rides the op byte's trace extension; ``span`` (the
        caller's relay span) gets the ``forwarded`` mark when the frame
        clears our buffer and the ``worker`` mark from the traced
        reply's worker_us block.  The block is stripped here either
        way, so callers always see the plain PR-12 ``(status, body)``.
        """
        if self._closed:
            raise ShardError("shard connection closed")
        self._next_id = (self._next_id + 1) & 0xFFFFFFFF
        req_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            self._writer.write(pack_request(req_id, op, body, trace_ctx))
            await self._writer.drain()
        except (ConnectionError, OSError) as err:
            self._pending.pop(req_id, None)
            raise ShardError(f"shard write failed: {err!r}") from err
        if span is not None:
            span.mark("forwarded")
        try:
            status, reply = await fut
        finally:
            self._pending.pop(req_id, None)
        if status & TRACE_FLAG:
            if len(reply) < _WORKER_US.size:
                # Same hazard split_traced guards on the request side:
                # a malformed peer must surface as the documented
                # ShardError, never a stray struct.error (which the
                # relay path would not catch — a dead handler task).
                raise ShardError(
                    f"traced reply too short for worker_us block "
                    f"({len(reply)})"
                )
            (worker_us,) = _WORKER_US.unpack_from(reply)
            reply = reply[_WORKER_US.size:]
            status &= ~TRACE_FLAG & 0xFF
            if span is not None:
                span.set_mark("worker", worker_us / 1e6)
        return status, reply

    async def drain_pending(self, timeout: float = 2.0) -> None:
        """Wait (bounded) for in-flight requests to finish — the reshard
        retirement barrier, so a departing worker is never torn down
        under a relay that already chose it."""
        deadline = time.monotonic() + timeout
        while self._pending and time.monotonic() < deadline:
            await asyncio.sleep(0.01)

    async def close(self) -> None:
        self._closed = True
        self._reader_task.cancel()
        try:
            await self._reader_task
        except asyncio.CancelledError:
            pass
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001 - teardown best effort
            pass
        self._fail_pending(ShardError("shard connection closed"))


# ---------------------------------------------------------------------------
# The worker process
# ---------------------------------------------------------------------------

#: a worker rides out ensemble blips like the daemon does; the cache
#: degrades to live reads while down and resumes cold-but-authoritative.
#: Reconnects are AGGRESSIVE compared to the agent's 1-90 s envelope:
#: every disconnected second is serve-path downtime for this worker's
#: whole slice, and the herd is bounded by the shard count (a handful of
#: read sessions, not a fleet of registrants)
_WORKER_RECONNECT = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.05, max_delay=2.0,
    jitter="decorrelated",
)


class ShardWorker:
    """One shard: a self-contained, process-spawnable serve unit.

    Owns its event loop (one per process), one ZooKeeper session
    (attached per ``attach`` — against an ensemble, a *distinct*
    follower via ``spread:<k-of-n>``), one watch-coherent ZKCache over
    that session, and one unix-socket listener speaking the frame
    protocol.  ``serve()`` runs until ``stop()`` (SIGTERM in the spawned
    process).

    The worker also keeps a bounded **warm set** — the (name, qtype)
    pairs it has resolved, in LRU order, each with its last successfully
    serialized answer — which is what moves during a reshard (module
    docstring: names move, bytes don't).

    **Stale-while-unreachable** (ROADMAP item 4, scoped to the serve
    tier): when a cached resolve fails on a *transient connectivity*
    error (the session mid-reconnect, an ensemble member bouncing —
    exactly :func:`registrar_tpu.retry.is_transient`'s verdict), the
    worker answers the last-known-good serialization instead, bounded
    by ``maxStaleS`` (default :data:`DEFAULT_MAX_STALE_S`).  DNS TTLs
    already tolerate bounded staleness — Binder semantics — and a
    worker mid-blip serving yesterday's answer set beats SERVFAIL for
    every domain in its slice.  Explicit live reads (``flags`` bit 0)
    never serve stale, and a record older than the bound fails
    truthfully.
    """

    def __init__(self, spec: Dict):
        self.spec = spec
        self.shard_id = int(spec["shard"])
        self.socket_path = spec["socket"]
        self.max_entries = int(spec.get("maxEntries") or DEFAULT_MAX_ENTRIES)
        self.zk: Optional[ZKClient] = None
        self.cache: Optional[ZKCache] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._tasks: set = set()
        self._stop = asyncio.Event()
        self.started_at = time.time()
        self.resolves_total = 0
        self.errors_total = 0
        self.stale_serves = 0
        #: staleness bound for the last-known-good fallback (seconds)
        self.max_stale_s = float(
            spec.get("maxStaleS") or DEFAULT_MAX_STALE_S
        )
        # -- overload armor (ISSUE 17; every knob None = unbounded, the
        # pre-armor behavior — config absent means not a byte changes) --
        #: bound on resolve requests dispatched-but-unanswered across
        #: the whole worker (the dispatch backlog satellite 1 bounds)
        self.max_queue_depth = _opt_int(spec.get("maxQueueDepth"))
        #: bound on resolve requests in flight per connection (the
        #: per-connection in-flight map satellite 1 bounds)
        self.max_inflight_per_conn = _opt_int(spec.get("maxInflightPerConn"))
        #: reply write deadline (seconds): a peer that stops reading is
        #: disconnected rather than allowed to pin its handler tasks
        self.write_deadline_s = (
            float(spec["writeDeadlineS"])
            if spec.get("writeDeadlineS") is not None
            else None
        )
        #: bound on concurrent cold fills, threaded into ZKCache
        self.cold_fill_concurrency = _opt_int(spec.get("coldFillConcurrency"))
        #: resolve requests currently dispatched and unanswered
        self.queue_depth = 0
        #: deliberate rejects by reason (docs/OPERATIONS.md taxonomy)
        self.sheds: Dict[str, int] = {r: 0 for r in SHED_REASONS}
        #: LRU warm set: (name, qtype) -> (last-good serialized answer,
        #: monotonic stamp); dict order = recency
        self.warm: Dict[Tuple[str, str], Tuple[bytes, float]] = {}
        #: per-instance tracer override (ISSUE 13); None = the process
        #: default — the spawned worker installs one from spec["trace"]
        self.tracer = None
        #: DNS frontend (ISSUE 19): spec["dns"] present = this worker
        #: binds an SO_REUSEPORT UDP socket + TCP listener on the
        #: shared host:port at start(); absent = no DNS, byte-identical
        #: behavior to the pre-19 worker.
        self.dns_spec = spec.get("dns")
        self.dns: Optional[dnsfront.DnsFront] = None

    def _make_client(self) -> ZKClient:
        spec = self.spec
        return ZKClient(
            [(h, int(p)) for h, p in spec["servers"]],
            timeout_ms=int(spec.get("timeoutMs") or 30000),
            connect_timeout_ms=int(spec.get("connectTimeoutMs") or 4000),
            chroot=spec.get("chroot"),
            request_timeout_ms=spec.get("requestTimeoutMs"),
            reconnect_policy=_WORKER_RECONNECT,
            # A pure reader: keep serving through a read-only minority
            # member during quorum loss (ISSUE 10).
            can_be_read_only=bool(spec.get("canBeReadOnly", True)),
            attach_preference=spec.get("attach", "any"),
        )

    async def start(self) -> "ShardWorker":
        # Session first, socket second: an answering socket IS the
        # readiness signal the router's respawn bound is built on.
        self.zk = self._make_client()
        await connect_with_backoff(self.zk)
        self.cache = ZKCache(
            self.zk,
            max_entries=self.max_entries,
            fill_concurrency=self.cold_fill_concurrency,
        )
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        self._server = await asyncio.start_unix_server(
            self._on_connection, path=self.socket_path
        )
        if self.dns_spec:
            # The DNS presence (ISSUE 19): every worker binds the SAME
            # host:port with SO_REUSEPORT — the kernel fans queries out
            # across the sibling workers, and any worker answers any
            # domain (the ring is a warmth hint, not a correctness
            # boundary), so no router hop exists on this path at all.
            self.dns = dnsfront.DnsFront(
                self._dns_resolve,
                host=self.dns_spec.get("host", "127.0.0.1"),
                port=int(self.dns_spec.get("port") or 0),
                source=self.cache,
                udp_payload_max=int(
                    self.dns_spec.get("udpPayloadMax")
                    or dnsfront.DEFAULT_UDP_PAYLOAD_MAX
                ),
                negative_ttl=float(
                    self.dns_spec.get("negativeTtl")
                    or dnsfront.DEFAULT_NEGATIVE_TTL
                ),
                # `or`-defaulting would turn an explicit 0 (fail closed
                # on authority loss) back into the 30 s default.
                stale_ttl=(
                    float(self.dns_spec["staleTtl"])
                    if self.dns_spec.get("staleTtl") is not None
                    else dnsfront.DEFAULT_STALE_TTL
                ),
                max_entries=self.max_entries,
                max_pending=_opt_int(self.dns_spec.get("maxPending")),
                rate_limit=(
                    float(self.dns_spec["rateLimit"])
                    if self.dns_spec.get("rateLimit") is not None
                    else None
                ),
            )
            await self.dns.start()
        log.info(
            "shard %d serving on %s (session 0x%x via %s)%s",
            self.shard_id, self.socket_path, self.zk.session_id,
            self.zk.connected_server,
            (
                f" + dns {self.dns.host}:{self.dns.port}"
                if self.dns is not None
                else ""
            ),
        )
        return self

    async def serve(self) -> None:
        await self._stop.wait()

    def stop(self) -> None:
        self._stop.set()

    async def close(self) -> None:
        if self.dns is not None:
            await self.dns.close()
            self.dns = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        if self.cache is not None:
            self.cache.close()
        if self.zk is not None and not self.zk.closed:
            await self.zk.close()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    # -- request handling ---------------------------------------------------

    async def _on_connection(self, reader, writer) -> None:
        # Per-connection in-flight count, shared (mutably) with the
        # handler tasks this connection spawns.
        conn = {"inflight": 0}
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    return
                req_id, op = _HDR.unpack_from(frame)
                reason = self._admission_check(op, conn)
                if reason is not None:
                    # Fast-fail shed: answered inline from the read
                    # loop, never dispatched, normally never drained —
                    # a shed reply must not queue behind the very
                    # backlog it is refusing to join (and must never
                    # look like a timeout; the requester's future
                    # resolves now).
                    self.sheds[reason] += 1
                    writer.write(
                        pack_frame(
                            req_id, STATUS_ERR,
                            shed_body(reason, f"shard {self.shard_id}"),
                        )
                    )
                    transport = writer.transport
                    if (
                        self.write_deadline_s is not None
                        and transport is not None
                        and transport.get_write_buffer_size() > 65536
                    ):
                        # A peer that floods requests but never reads
                        # replies grows the reject buffer without bound
                        # — the slow-loris shape the admitted path's
                        # drain deadline can't see (sheds outnumber
                        # admissions by orders of magnitude under a
                        # flood).  Once the buffer is past the
                        # transport's high-water mark, drain under the
                        # same deadline; a well-behaved bursty reader
                        # drains in microseconds, a non-reader gets
                        # disconnected here.
                        try:
                            await asyncio.wait_for(
                                writer.drain(), self.write_deadline_s
                            )
                        except asyncio.TimeoutError:
                            self.sheds["slow_client"] += 1
                            log.warning(
                                "shard %d: shed backlog write stalled "
                                "> %.1fs; disconnecting slow client",
                                self.shard_id, self.write_deadline_s,
                            )
                            transport.abort()
                            return
                    continue
                # Each admitted request is its own task: a cold fill
                # awaiting the wire must not head-of-line-block the
                # warm answers pipelined behind it (replies demux by
                # req_id).  Control ops (OP_STATUS/OP_RING/OP_TRACE...)
                # skip admission entirely — the priority lane: they are
                # never shed and never wait behind a saturated resolve
                # backlog, because that backlog is bounded and anything
                # beyond the bound was refused above.
                if op & ~TRACE_FLAG & 0xFF == OP_RESOLVE:
                    conn["inflight"] += 1
                    self.queue_depth += 1
                    spawn_owned(
                        self._handle_admitted(frame, writer, conn),
                        self._tasks,
                    )
                else:
                    spawn_owned(self._handle(frame, writer), self._tasks)
        except (ShardError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass

    def _admission_check(self, op: int, conn: Dict) -> Optional[str]:
        """The admission decision for one incoming frame: a shed reason,
        or None for admitted.  Only OP_RESOLVE is ever shed."""
        if op & ~TRACE_FLAG & 0xFF != OP_RESOLVE:
            return None
        if (
            self.max_inflight_per_conn is not None
            and conn["inflight"] >= self.max_inflight_per_conn
        ):
            return "queue_full"
        if (
            self.max_queue_depth is not None
            and self.queue_depth >= self.max_queue_depth
        ):
            return "queue_full"
        return None

    async def _handle_admitted(self, frame: bytes, writer, conn: Dict) -> None:
        released = False

        def release() -> None:
            # The admission slot covers the resolve WORK, not the reply
            # flush: it must be free before the reply bytes can reach
            # the peer, or a well-behaved serial client races its own
            # slot (reply arrives, next request sent, worker's
            # decrement still parked behind the drain's loop yield) and
            # gets spuriously shed at maxInflightPerConn=1.  _handle
            # calls this right before writing; the finally covers every
            # early-exit path exactly once.
            nonlocal released
            if not released:
                released = True
                conn["inflight"] -= 1
                self.queue_depth -= 1

        try:
            await self._handle(frame, writer, release)
        finally:
            release()

    async def _handle(self, frame: bytes, writer, release=None) -> None:
        req_id, op = _HDR.unpack_from(frame)
        try:
            op, ctx, body = split_traced(frame, op)
        except ShardError as err:
            self.errors_total += 1
            await _answer_protocol_error(writer, req_id, err)
            return
        t0 = time.monotonic() if ctx is not None else 0.0
        try:
            # Adopt the wire context (ISSUE 13): this request's
            # resolve.query/cache.fill/zk.op subtree chains under the
            # router's relay span (or the direct caller's span) across
            # the process boundary.  A disabled tracer's adopt() is the
            # shared no-op span; the untraced path never even resolves
            # the tracer.
            with (
                trace.tracer_for(self).adopt(*ctx)
                if ctx is not None
                else NULLCTX
            ):
                reply = await self._dispatch(op, body)
            status = STATUS_OK
        except asyncio.CancelledError:
            raise
        except ShardShedError as err:
            # A deliberate overload reject (counted at the shed site),
            # not a failure: the SHED: body travels the plain error
            # rail, so every peer back to the client sees the reason.
            reply = err.payload()
            status = STATUS_ERR
        except Exception as err:  # noqa: BLE001 - one bad request != the worker
            self.errors_total += 1
            reply = repr(err).encode()
            status = STATUS_ERR
        if ctx is not None:
            # Traced reply extension: this worker's handling time, the
            # relay span's "worker" mark.
            status, reply = stamp_traced_reply(status, reply, t0)
        if release is not None:
            release()
        try:
            writer.write(pack_frame(req_id, status, reply))
            if self.write_deadline_s is None:
                await writer.drain()
            else:
                # Slow-loris armor: a peer that stops reading keeps the
                # transport's send buffer full and would park THIS task
                # (and its in-flight slot) on drain() forever.  Bound
                # the wait and abort the transport — the connection
                # handler's finally cleans up; in-flight accounting
                # unwinds through _handle_admitted's finally.
                await asyncio.wait_for(
                    writer.drain(), self.write_deadline_s
                )
        except asyncio.TimeoutError:
            self.sheds["slow_client"] += 1
            log.warning(
                "shard %d: reply write stalled > %.1fs; disconnecting "
                "slow client", self.shard_id, self.write_deadline_s,
            )
            transport = writer.transport
            if transport is not None:
                transport.abort()
        except (ConnectionError, OSError):
            pass  # requester went away; nothing owed

    async def _dispatch(self, op: int, body: memoryview) -> bytes:
        if op == OP_RESOLVE:
            return await self._resolve(body)
        if op == OP_STATUS:
            return json.dumps(self.status()).encode()
        if op == OP_DUMP:
            return json.dumps(
                {"warm": [list(pair) for pair in self.warm]}
            ).encode()
        if op == OP_WARM:
            names = json.loads(bytes(body).decode())["names"]
            for name, qtype in names:
                try:
                    res = await binderview.resolve(self.cache, name, qtype)
                    self._touch(name, qtype, encode_resolution(res))
                except Exception:  # noqa: BLE001 - warming is best-effort
                    log.warning("warm fill failed for %s (%s)", name, qtype)
            return json.dumps({"warmed": len(names)}).encode()
        if op == OP_TRACE:
            req = json.loads(bytes(body).decode()) if len(body) else {}
            dump = trace.tracer_for(self).dump(
                req.get("n"), trace_id=req.get("trace_id")
            )
            # Stamp the fragment's origin: the assembler labels each
            # span with the process it came from.
            dump["shard"] = self.shard_id
            dump["pid"] = os.getpid()
            return json.dumps(dump, default=str).encode()
        raise ShardError(f"unknown op {op}")

    async def _resolve(self, body: memoryview) -> bytes:
        if len(body) < 2:
            malformed.note("shard")
            raise ShardError(f"resolve body too short ({len(body)} bytes)")
        live = bool(body[0] & 1)
        qlen = body[1]
        if 2 + qlen > len(body):
            malformed.note("shard")
            raise ShardError(
                f"resolve qtype length {qlen} overruns body "
                f"({len(body)} bytes)"
            )
        try:
            qtype = bytes(body[2 : 2 + qlen]).decode("ascii")
            name = bytes(body[2 + qlen :]).decode("utf-8").rstrip(".").lower()
        except UnicodeDecodeError as err:
            malformed.note("shard")
            raise ShardError(f"resolve body not decodable: {err}") from err
        if live:
            res = await binderview.resolve(self.zk, name, qtype)
            self.resolves_total += 1
            return encode_resolution(res)
        try:
            res = await binderview.resolve(self.cache, name, qtype)
        except CacheOverloadError as err:
            # Cold-fill stampede shed: prefer stale over collapse — a
            # warm domain whose entry was churned out answers its
            # bounded-age last-known-good bytes instead of joining the
            # fill queue; a genuinely cold domain fails fast with the
            # explicit shed reason (never a timeout).
            self.sheds["cold_fill_shed"] += 1
            payload = self._stale_payload(name, qtype)
            if payload is None:
                raise ShardShedError("cold_fill_shed", str(err)) from err
            self.stale_serves += 1
            self.resolves_total += 1
            return payload
        except Exception as err:  # noqa: BLE001 - classified right below
            payload = self._stale_payload(name, qtype)
            if payload is None or not is_transient(err):
                raise
            # Stale-while-unreachable (class docstring): a transient
            # backend blip answers the bounded-age last-known-good
            # serialization instead of failing the whole slice.
            self.stale_serves += 1
            self.resolves_total += 1
            return payload
        self.resolves_total += 1
        payload = encode_resolution(res)
        self._touch(name, qtype, payload)
        return payload

    async def _dns_resolve(self, name: str, qtype: str):
        """The DnsFront's resolver hook: the same cache-backed resolve
        the unix-socket path uses, with overload classified into the
        DNS shed vocabulary (REFUSED, counted by reason in the front —
        NOT double-counted into the tier's ``sheds`` rollup; the DNS
        surface has its own metric family)."""
        try:
            return await binderview.resolve(self.cache, name, qtype)
        except CacheOverloadError as err:
            raise dnsfront.DnsRefused("cold_fill_shed") from err

    def _stale_payload(self, name: str, qtype: str) -> Optional[bytes]:
        entry = self.warm.get((name, qtype))
        if entry is None:
            return None
        payload, stamp = entry
        if time.monotonic() - stamp > self.max_stale_s:
            return None
        return payload

    def _touch(self, name: str, qtype: str, payload: bytes) -> None:
        key = (name, qtype)
        self.warm.pop(key, None)
        self.warm[key] = (payload, time.monotonic())
        while len(self.warm) > self.max_entries:
            self.warm.pop(next(iter(self.warm)))

    def status(self) -> Dict:
        cache = self.cache
        zk = self.zk
        return {
            "shard": self.shard_id,
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self.started_at, 1),
            "resolves_total": self.resolves_total,
            "errors_total": self.errors_total,
            "stale_serves": self.stale_serves,
            "overload": {
                "queue_depth": self.queue_depth,
                "max_queue_depth": self.max_queue_depth,
                "max_inflight_per_conn": self.max_inflight_per_conn,
                "sheds": dict(self.sheds),
                "fill_sheds": (
                    int(self.cache.stats.get("fill_sheds", 0))
                    if self.cache is not None
                    else 0
                ),
            },
            "dns": self.dns.stats() if self.dns is not None else None,
            "warm": len(self.warm),
            "entries": cache.entries if cache is not None else 0,
            "authoritative": (
                cache.authoritative if cache is not None else False
            ),
            "hit_rate": round(cache.hit_rate(), 4) if cache else 0.0,
            "coherence_lag_ms_last": (
                round(cache.stats["coherence_lag_ms_last"], 3)
                if cache is not None
                else None
            ),
            "session": {
                "id": f"0x{zk.session_id:x}" if zk is not None else None,
                "connected": bool(zk is not None and zk.connected),
                "readOnly": bool(zk is not None and zk.read_only),
                "server": (
                    f"{zk.connected_server[0]}:{zk.connected_server[1]}"
                    if zk is not None and zk.connected_server
                    else None
                ),
            },
        }


async def _worker_main(spec: Dict) -> int:
    tcfg = spec.get("trace")
    if tcfg:
        # The router's observability config rides the spec: the worker
        # installs its own process-wide tracer so the instrumented
        # cache/client paths (resolve.query, cache.fill, zk.op) record
        # into a per-process flight recorder OP_TRACE can hand back.
        trace.set_tracer(
            trace.Tracer(
                sample_rate=float(tcfg.get("sampleRate", 1.0)),
                slow_span_ms=tcfg.get("slowSpanMs"),
                max_spans=int(
                    tcfg.get("maxSpans") or trace.DEFAULT_MAX_SPANS
                ),
            )
        )
    worker = ShardWorker(spec)
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, worker.stop)
    await worker.start()
    try:
        await worker.serve()
    finally:
        await worker.close()
    return 0


def worker_entry(argv: Sequence[str]) -> int:
    """``python -m registrar_tpu.shard '<json spec>'`` — the spawned
    worker process's whole life."""
    logging.basicConfig(
        level=os.environ.get("SHARD_LOG_LEVEL", "WARNING"),
        format="%(asctime)s shard %(levelname)s %(message)s",
        stream=sys.stderr,
    )
    spec = json.loads(argv[0])
    return asyncio.run(_worker_main(spec))


# ---------------------------------------------------------------------------
# The router
# ---------------------------------------------------------------------------


def _dns_merge(base: Dict, live: Optional[Dict]) -> Dict:
    """Accumulate one worker's live DNS stats onto banked totals.

    Counters add (queries, latency ladder, encode-cache counters,
    sheds); ``entries`` and ``port`` are point-in-time and the live
    value wins.  Shared by crash banking (bank = merge(bank, dying
    incarnation)) and the tier rollup (fold every slot's total)."""
    live = live or {}
    budp = base.get("udp") or {}
    out = {
        "port": live.get("port", base.get("port")),
        "queries": dict(base.get("queries") or {}),
        "udp": {
            "counts": list(budp.get("counts") or []),
            "sum": float(budp.get("sum") or 0.0),
        },
        "encode_cache": dict(base.get("encode_cache") or {}),
        "sheds": dict(base.get("sheds") or {}),
    }
    for key, val in (live.get("queries") or {}).items():
        out["queries"][key] = out["queries"].get(key, 0) + int(val)
    lcounts = (live.get("udp") or {}).get("counts") or []
    counts = out["udp"]["counts"]
    if len(counts) < len(lcounts):
        counts.extend([0] * (len(lcounts) - len(counts)))
    for i, val in enumerate(lcounts):
        counts[i] += int(val)
    out["udp"]["sum"] += float((live.get("udp") or {}).get("sum") or 0.0)
    for key, val in (live.get("encode_cache") or {}).items():
        if key == "entries":
            out["encode_cache"][key] = int(val)
        else:
            out["encode_cache"][key] = (
                out["encode_cache"].get(key, 0) + int(val)
            )
    for key, val in (live.get("sheds") or {}).items():
        out["sheds"][key] = out["sheds"].get(key, 0) + int(val)
    return out


class _WorkerHandle:
    """Router-side bookkeeping for one shard slot."""

    __slots__ = (
        "shard_id", "seq", "socket_path", "proc", "chan", "up",
        "up_since", "respawns", "resolves_base", "sheds_base",
        "dns_base", "last_status",
    )

    def __init__(self, shard_id: int, seq: int, socket_path: str):
        self.shard_id = shard_id
        self.seq = seq
        self.socket_path = socket_path
        self.proc: Optional[subprocess.Popen] = None
        self.chan: Optional[Channel] = None
        self.up = False
        self.up_since: Optional[float] = None
        self.respawns = 0
        #: resolves accumulated by previous incarnations — a respawned
        #: worker restarts its counter at zero, and the rolled-up
        #: registrar_shard_resolves_total must stay monotonic
        self.resolves_base = 0
        #: same banking for the shed counters (registrar_shed_total is
        #: a counter too; a respawn must not rewind it)
        self.sheds_base: Dict[str, int] = {r: 0 for r in SHED_REASONS}
        #: and for the DNS surface's counters (queries, the latency
        #: ladder, encode-cache hits) — every registrar_dns_* family
        #: must stay monotonic across worker respawns
        self.dns_base: Dict = {}
        self.last_status: Dict = {}

    def resolves_total(self) -> int:
        return self.resolves_base + int(
            self.last_status.get("resolves_total", 0)
        )

    def sheds_total(self) -> Dict[str, int]:
        """Per-reason request sheds across every incarnation of this
        slot (the cache's per-FILL ``fill_sheds`` stat stays a status
        detail — different unit, one request can shed several fills)."""
        sheds = (self.last_status.get("overload") or {}).get("sheds") or {}
        return {
            r: self.sheds_base[r] + int(sheds.get(r, 0))
            for r in SHED_REASONS
        }

    def queue_depth(self) -> int:
        return int(
            (self.last_status.get("overload") or {}).get("queue_depth", 0)
        )

    def dns_total(self) -> Dict:
        """This slot's cumulative DNS stats across every incarnation."""
        return _dns_merge(self.dns_base, self.last_status.get("dns"))


class ShardRouter(EventEmitter):
    """Parent of the sharded serve tier: spawns N :class:`ShardWorker`
    processes, consistent-hashes domains across them, supervises them
    (crash → respawn while siblings keep serving), fronts them on
    ``socket_path``, and owns resharding (:meth:`reshard`).

    Events (consumed by :func:`registrar_tpu.metrics.instrument_shards`):
    ``respawn`` (shard_id), ``reshard`` (old_count, new_count, moved),
    ``poll`` (list of per-shard status dicts), ``admitted`` (seconds —
    one per successfully relayed resolve, the admitted-latency
    histogram's feed).
    """

    def __init__(
        self,
        servers: Sequence[Tuple[str, int]],
        shards: int,
        socket_path: str,
        *,
        attach_spread: str = "spread",
        chroot: Optional[str] = None,
        max_entries: Optional[int] = None,
        timeout_ms: int = 30000,
        connect_timeout_ms: int = 4000,
        request_timeout_ms: Optional[int] = None,
        vnodes: int = DEFAULT_VNODES,
        poll_interval_s: float = 1.0,
        supervise_interval_s: float = 0.05,
        python: Optional[str] = None,
        worker_log_level: Optional[str] = None,
        worker_trace: Optional[Dict] = None,
        overload: Optional[Dict] = None,
        dns: Optional[Dict] = None,
    ):
        super().__init__()
        if shards < 1:
            raise ValueError("shards must be >= 1")
        if attach_spread not in ("any", "follower", "spread"):
            raise ValueError(
                'attach_spread must be "any", "follower", or "spread"'
            )
        self.servers = [(h, int(p)) for h, p in servers]
        self.shards = shards
        self.socket_path = socket_path
        self.attach_spread = attach_spread
        self.chroot = chroot
        self.max_entries = max_entries
        self.timeout_ms = timeout_ms
        self.connect_timeout_ms = connect_timeout_ms
        self.request_timeout_ms = request_timeout_ms
        self.vnodes = vnodes
        self.poll_interval_s = poll_interval_s
        #: crash-detection + readiness-poll cadence (ISSUE 20): the
        #: respawn MTTR's fixed overhead is one detect interval plus
        #: one readiness interval — availability-tuned deployments (the
        #: SLO harness's lever mode) drop it to 0.01 s; the default is
        #: the pre-20 hardcoded 0.05 s, byte-identical supervision.
        if supervise_interval_s <= 0:
            raise ValueError("supervise_interval_s must be > 0")
        self.supervise_interval_s = supervise_interval_s
        self.python = python or sys.executable
        #: stderr log level for spawned workers (SHARD_LOG_LEVEL env;
        #: None = inherit — the SLO harness quiets its workers with it)
        self.worker_log_level = worker_log_level
        #: spec["trace"] block for spawned workers (ISSUE 13): e.g.
        #: {"sampleRate": 1.0, "maxSpans": 2048}; None = workers trace
        #: nothing, exactly the pre-13 behavior
        self.worker_trace = worker_trace
        #: overload-armor knobs (ISSUE 17, config ``serve.overload``):
        #: {"maxQueueDepth", "maxInflightPerConn", "clientRateLimit",
        #: "coldFillConcurrency", "writeDeadlineS"} — worker-side knobs
        #: ride each spawn spec, clientRateLimit is enforced HERE (a
        #: per-front-connection token bucket).  None = no armor, byte-
        #: identical specs and relays to the pre-17 tier.
        self.overload = dict(overload) if overload else None
        #: DNS frontend config (ISSUE 19, config ``serve.dns``):
        #: spec-key spelling ({"host", "port", "udpPayloadMax",
        #: "negativeTtl", "maxPending", "rateLimit"}).  A port of 0 is
        #: resolved to a concrete free port HERE, once — every worker
        #: must bind the SAME port for the SO_REUSEPORT kernel fan-out.
        #: None = no DNS sockets, byte-identical specs to the pre-19
        #: tier.
        self.dns = dict(dns) if dns else None
        if self.dns and not self.dns.get("port"):
            self.dns["port"] = dnsfront.allocate_port(
                self.dns.get("host", "127.0.0.1")
            )
        #: the router's own deliberate rejects (rate_limited lives here;
        #: worker reasons roll up from status polls + crash banking)
        self._sheds: Dict[str, int] = {r: 0 for r in SHED_REASONS}
        #: per-instance tracer override for the router's OWN spans
        #: (shard.relay, shard.trace_collect); None = process default
        self.tracer = None
        #: crash → respawn supervision; the SLO harness's repair-disabled
        #: runs turn this off (a withheld recovery action)
        self.respawn_enabled = True
        self.ring = HashRing(range(shards), vnodes=vnodes)
        self.generation = 0
        self.reshards = 0
        self.started_at: Optional[float] = None
        self.last_transition: Dict[str, Dict] = {}
        self._workers: Dict[int, _WorkerHandle] = {}
        self._seq = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._tasks: set = set()
        self._stopping = False
        self._reshard_lock = asyncio.Lock()

    # -- spawning -----------------------------------------------------------

    def _spec(self, shard_id: int, shards: int, socket_path: str) -> Dict:
        attach = self.attach_spread
        if attach == "spread":
            attach = f"spread:{shard_id}-of-{shards}"
        spec = {
            "socket": socket_path,
            "shard": shard_id,
            "shards": shards,
            "servers": [[h, p] for h, p in self.servers],
            "chroot": self.chroot,
            "attach": attach,
            "maxEntries": self.max_entries,
            "timeoutMs": self.timeout_ms,
            "connectTimeoutMs": self.connect_timeout_ms,
            "requestTimeoutMs": self.request_timeout_ms,
            "trace": self.worker_trace,
        }
        if self.overload:
            # Worker-side armor knobs only when configured: an un-armored
            # router's spec stays byte-identical to the pre-17 format.
            for key in (
                "maxQueueDepth", "maxInflightPerConn",
                "coldFillConcurrency", "writeDeadlineS",
            ):
                if self.overload.get(key) is not None:
                    spec[key] = self.overload[key]
        if self.dns:
            # Every worker gets the SAME (already-concrete) host:port —
            # SO_REUSEPORT is the fan-out (ISSUE 19).
            spec["dns"] = dict(self.dns)
        return spec

    def _spawn_proc(self, spec: Dict) -> subprocess.Popen:
        env = dict(os.environ)
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_root
        )
        if self.worker_log_level is not None:
            env["SHARD_LOG_LEVEL"] = self.worker_log_level
        return subprocess.Popen(
            [self.python, "-m", "registrar_tpu.shard", json.dumps(spec)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=None,  # worker logs land on the router's stderr
            start_new_session=True,
        )

    async def _wait_ready(self, handle: _WorkerHandle) -> None:
        deadline = time.monotonic() + READY_TIMEOUT_S
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            if handle.proc is not None and handle.proc.poll() is not None:
                raise ShardError(
                    f"shard {handle.shard_id} exited rc="
                    f"{handle.proc.returncode} before becoming ready"
                )
            try:
                chan = await Channel.open(handle.socket_path)
            except (OSError, ConnectionError) as err:
                last_err = err
                await asyncio.sleep(self.supervise_interval_s)
                continue
            try:
                status, body = await asyncio.wait_for(
                    chan.request(OP_STATUS, b""), timeout=2.0
                )
            except (ShardError, asyncio.TimeoutError) as err:
                last_err = err
                await chan.close()
                await asyncio.sleep(self.supervise_interval_s)
                continue
            if status != STATUS_OK:
                await chan.close()
                raise ShardError(
                    f"shard {handle.shard_id} refused status: "
                    f"{bytes(body)!r}"
                )
            handle.chan = chan
            handle.last_status = json.loads(bytes(body).decode())
            handle.up = True
            handle.up_since = time.time()
            return
        raise ShardError(
            f"shard {handle.shard_id} never became ready "
            f"({last_err!r})"
        )

    async def _start_worker(self, shard_id: int, shards: int) -> _WorkerHandle:
        self._seq += 1
        socket_path = f"{self.socket_path}.{self._seq}"
        handle = _WorkerHandle(shard_id, self._seq, socket_path)
        handle.proc = self._spawn_proc(
            self._spec(shard_id, shards, socket_path)
        )
        try:
            await self._wait_ready(handle)
        except BaseException:
            # A worker that missed its readiness window is still a live
            # process (its connect backoff retries forever) — reap it,
            # or every failed spawn leaks an orphan holding a session.
            await self._retire_worker(handle)
            raise
        return handle

    async def _retire_worker(self, handle: _WorkerHandle) -> None:
        if handle.chan is not None:
            await handle.chan.drain_pending()
            await handle.chan.close()
            handle.chan = None
        handle.up = False
        proc = handle.proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                await asyncio.to_thread(proc.wait, 5)
            except subprocess.TimeoutExpired:
                proc.kill()
                await asyncio.to_thread(proc.wait)
        try:
            # A SIGTERMed worker unlinks its own socket; a SIGKILLed
            # (or never-ready) one cannot — reap the file either way.
            os.unlink(handle.socket_path)
        except OSError:
            pass

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "ShardRouter":
        started = await asyncio.gather(
            *(
                self._start_worker(sid, self.shards)
                for sid in range(self.shards)
            ),
            return_exceptions=True,
        )
        failures = [h for h in started if isinstance(h, BaseException)]
        if failures:
            for h in started:
                if isinstance(h, _WorkerHandle):
                    await self._retire_worker(h)
            raise failures[0]
        for handle in started:
            self._workers[handle.shard_id] = handle
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        self._server = await asyncio.start_unix_server(
            self._on_connection, path=self.socket_path
        )
        self.started_at = time.time()
        self._mark("serve", "started")
        spawn_owned(self._supervise_loop(), self._tasks)
        log.info(
            "shard router serving %d shards on %s", self.shards,
            self.socket_path,
        )
        return self

    async def stop(self) -> None:
        self._stopping = True
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for handle in list(self._workers.values()):
            await self._retire_worker(handle)
        self._workers.clear()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    async def __aenter__(self) -> "ShardRouter":
        return await self.start()

    async def __aexit__(self, *_exc) -> None:
        await self.stop()

    def _mark(self, kind: str, state: str) -> None:
        self.last_transition[kind] = {"state": state, "at": time.time()}

    # -- supervision --------------------------------------------------------

    def kill_worker(self, shard_id: int) -> None:
        """SIGKILL one worker process (test/SLO fault injection — the
        ``shard-kill`` fault class; supervision respawns it)."""
        handle = self._workers.get(shard_id)
        if handle is None or handle.proc is None:
            raise ValueError(f"no worker for shard {shard_id}")
        handle.proc.kill()

    async def _supervise_loop(self) -> None:
        next_poll = 0.0
        while not self._stopping:
            await asyncio.sleep(self.supervise_interval_s)
            for handle in list(self._workers.values()):
                proc = handle.proc
                if (
                    handle.up
                    and proc is not None
                    and proc.poll() is not None
                ):
                    # Crashed: bank its counters (and CLEAR the dead
                    # incarnation's last status in the same breath —
                    # banking without clearing would double-count its
                    # resolves on every later read), drop the dead
                    # channel, reap its socket file, and (policy
                    # allowing) respawn — siblings keep serving their
                    # slices throughout.
                    handle.up = False
                    handle.resolves_base = handle.resolves_total()
                    handle.sheds_base = handle.sheds_total()
                    handle.dns_base = handle.dns_total()
                    handle.last_status = {}
                    if handle.chan is not None:
                        await handle.chan.close()
                        handle.chan = None
                    try:
                        os.unlink(handle.socket_path)
                    except OSError:
                        pass  # a SIGKILLed worker never unlinked it
                    log.warning(
                        "shard %d died (rc=%s)%s", handle.shard_id,
                        proc.returncode,
                        "; respawning" if self.respawn_enabled else "",
                    )
                    self._mark("serve", f"shard{handle.shard_id}-died")
                    self.emit("respawn", handle.shard_id)
                    if self.respawn_enabled:
                        spawn_owned(self._respawn(handle), self._tasks)
            now = time.monotonic()
            if now >= next_poll:
                next_poll = now + self.poll_interval_s
                await self._poll_statuses()

    async def _respawn(self, handle: _WorkerHandle) -> None:
        handle.respawns += 1
        handle.last_status = {}
        # Retry until the slot is live again (or moved on): a single
        # failed attempt must not abandon the shard forever — the
        # readiness window can miss during exactly the ensemble outage
        # the tier is supposed to serve through, and the supervise
        # loop's crash detection only fires for UP slots.
        delay = 0.5
        while True:
            current = self._workers.get(handle.shard_id)
            if current is not handle or self._stopping:
                return  # slot resharded away / router stopping
            try:
                fresh = await self._start_worker(
                    handle.shard_id, len(self.ring.shard_ids)
                )
                break
            except (ShardError, OSError) as err:
                log.error(
                    "shard %d respawn failed (retrying in %.1fs): %r",
                    handle.shard_id, delay, err,
                )
                await asyncio.sleep(delay)
                delay = min(delay * 2, 10.0)
        # Keep the slot's history (respawns, banked counters); adopt the
        # fresh incarnation's process/socket/channel.
        current = self._workers.get(handle.shard_id)
        if current is not handle or self._stopping:
            await self._retire_worker(fresh)  # slot moved on (reshard)
            return
        handle.proc = fresh.proc
        handle.seq = fresh.seq
        handle.socket_path = fresh.socket_path
        handle.chan = fresh.chan
        handle.last_status = fresh.last_status
        handle.up = True
        handle.up_since = fresh.up_since
        self._mark("serve", f"shard{handle.shard_id}-respawned")

    async def _poll_statuses(self) -> None:
        statuses = []
        for handle in list(self._workers.values()):
            if handle.chan is None:
                continue
            try:
                # Bounded: a frozen worker (alive, not scheduling) must
                # not wedge supervision — or GET /status, which rides
                # this — for every healthy sibling.
                status, body = await asyncio.wait_for(
                    handle.chan.request(OP_STATUS, b""), timeout=2.0
                )
            except (ShardError, asyncio.TimeoutError):
                continue
            if status == STATUS_OK:
                handle.last_status = json.loads(bytes(body).decode())
                statuses.append((handle.shard_id, handle.last_status))
        if statuses:
            self.emit("poll", statuses)

    # -- resharding ---------------------------------------------------------

    async def reshard(self, new_shards: int) -> Dict:
        """Change the shard count without cold-starting the tier.

        Consistent hashing bounds movement to ~K/N of the K warm
        domains; every moving domain is pre-resolved by its NEW owner
        (warm handoff by name) before the ring flips, and departing
        workers drain their in-flight replies before retirement — a
        resolver polling right through the reshard sees zero errors
        (pinned by tests/test_shard.py and bench.py's
        ``reshard_warm_handoff_ms`` measurement).
        """
        if new_shards < 1:
            raise ValueError("shards must be >= 1")
        async with self._reshard_lock:
            t0 = time.monotonic()
            old_ids = set(self.ring.shard_ids)
            new_ids = set(range(new_shards))
            if new_ids == old_ids:
                return {"moved": 0, "duration_ms": 0.0,
                        "shards": new_shards}
            # 1. Arrivals first: spawn new slots while the old ring keeps
            #    serving everything.  A partial arrival failure retires
            #    the siblings that DID come up (they are not in
            #    self._workers yet, so nothing else could ever reap
            #    them) and aborts the reshard — the old ring keeps
            #    serving untouched.
            arrivals = await asyncio.gather(
                *(
                    self._start_worker(sid, new_shards)
                    for sid in sorted(new_ids - old_ids)
                ),
                return_exceptions=True,
            )
            failures = [
                h for h in arrivals if isinstance(h, BaseException)
            ]
            if failures:
                for h in arrivals:
                    if isinstance(h, _WorkerHandle):
                        await self._retire_worker(h)
                raise failures[0]
            for handle in arrivals:
                self._workers[handle.shard_id] = handle
            new_ring = HashRing(new_ids, vnodes=self.vnodes)
            # 2. Warm handoff: every worker dumps its warm names; names
            #    whose owner changes are pre-resolved by the new owner
            #    (fresh watches armed with the read — see module
            #    docstring for why bytes never move).
            moves: Dict[int, List[List[str]]] = {}
            for handle in list(self._workers.values()):
                if handle.chan is None or handle.shard_id not in old_ids:
                    continue
                try:
                    status, body = await handle.chan.request(OP_DUMP, b"")
                except ShardError:
                    continue  # a dead worker's slice re-warms on demand
                if status != STATUS_OK:
                    continue
                for name, qtype in json.loads(bytes(body).decode())["warm"]:
                    new_owner = new_ring.owner(name)
                    if new_owner != handle.shard_id:
                        moves.setdefault(new_owner, []).append(
                            [name, qtype]
                        )
            moved = sum(len(v) for v in moves.values())
            warm_jobs = []
            for owner_id, names in moves.items():
                target = self._workers.get(owner_id)
                if target is None or target.chan is None:
                    continue
                warm_jobs.append(
                    target.chan.request(
                        OP_WARM, json.dumps({"names": names}).encode()
                    )
                )
            if warm_jobs:
                await asyncio.gather(*warm_jobs, return_exceptions=True)
            # 3. Flip — atomic between awaits; every relay from here on
            #    routes by the new ring.
            self.ring = new_ring
            self.shards = new_shards
            self.generation += 1
            self.reshards += 1
            # 4. Departures last, after their in-flight replies drain.
            for sid in sorted(old_ids - new_ids):
                handle = self._workers.pop(sid, None)
                if handle is not None:
                    await self._retire_worker(handle)
            duration_ms = (time.monotonic() - t0) * 1000.0
            self._mark("serve", f"resharded-{len(old_ids)}to{new_shards}")
            self.emit("reshard", len(old_ids), new_shards, moved)
            log.info(
                "resharded %d -> %d shards: %d warm domains moved in "
                "%.1f ms", len(old_ids), new_shards, moved, duration_ms,
            )
            return {
                "moved": moved,
                "duration_ms": duration_ms,
                "shards": new_shards,
            }

    # -- the front socket ---------------------------------------------------

    async def _on_connection(self, reader, writer) -> None:
        tasks: set = set()
        # Per-client token bucket (ISSUE 17): one bucket per front
        # connection, resolves only — control ops (status, ring, trace)
        # are the priority lane and are never rate limited.  Burst =
        # one second's refill, so a well-behaved client never notices.
        rate = float((self.overload or {}).get("clientRateLimit") or 0)
        tokens = rate
        last = time.monotonic()
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    return
                if rate > 0:
                    req_id, op = _HDR.unpack_from(frame)
                    if op & ~TRACE_FLAG & 0xFF == OP_RESOLVE:
                        now = time.monotonic()
                        tokens = min(rate, tokens + (now - last) * rate)
                        last = now
                        if tokens < 1.0:
                            # Fast-fail from the read loop, like the
                            # worker's admission reject: the client
                            # hears "rate_limited" now, not a timeout.
                            self._sheds["rate_limited"] += 1
                            writer.write(
                                pack_frame(
                                    req_id, STATUS_ERR,
                                    shed_body(
                                        "rate_limited",
                                        f"limit {rate:g}/s per client",
                                    ),
                                )
                            )
                            continue
                        tokens -= 1.0
                spawn_owned(self._serve_frame(frame, writer), tasks)
        except (ShardError, ConnectionError, OSError):
            pass
        finally:
            for task in tasks:
                task.cancel()
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass

    async def _serve_frame(self, frame: bytes, writer) -> None:
        req_id, op = _HDR.unpack_from(frame)
        try:
            op, ctx, body = split_traced(frame, op)
        except ShardError as err:
            await _answer_protocol_error(writer, req_id, err)
            return
        t0 = time.monotonic() if ctx is not None else 0.0
        if op == OP_RESOLVE:
            status, reply = await self._relay_resolve(body, ctx)
        elif op == OP_RING:
            status, reply = STATUS_OK, json.dumps(self.ring_info()).encode()
        elif op == OP_STATUS:
            status, reply = STATUS_OK, json.dumps(
                await self.status()
            ).encode()
        elif op == OP_TRACE:
            status, reply = await self._serve_trace(body)
        else:
            status, reply = STATUS_ERR, f"unknown op {op}".encode()
        if ctx is not None:
            # The traced-reply contract holds on EVERY hop: each peer
            # reports ITS handling time (for the router that spans
            # queue + socket + worker — the relay span's whole window),
            # so a traced client of the front socket gets its "worker"
            # mark exactly as a direct client of a worker does.
            status, reply = stamp_traced_reply(status, reply, t0)
        try:
            writer.write(pack_frame(req_id, status, reply))
            await writer.drain()
        except (ConnectionError, OSError):
            pass

    async def _serve_trace(self, body: memoryview):
        """OP_TRACE on the front socket: the ASSEMBLED cross-process
        tree (the same view GET /debug/trace?id= serves, reachable
        without a metrics listener)."""
        try:
            req = json.loads(bytes(body).decode()) if len(body) else {}
            trace_id = req.get("trace_id")
            if not trace_id:
                return STATUS_ERR, b"trace_id required"
            tree = await self.collect_trace(trace_id)
        except (ValueError, ShardError) as err:
            return STATUS_ERR, repr(err).encode()
        return STATUS_OK, json.dumps(tree, default=str).encode()

    async def _relay_resolve(self, body: memoryview, ctx=None):
        """Forward one resolve to its owner and hand back the worker's
        reply bytes untouched (the router never copies answers — the
        body view below is a slice of the worker's reply frame).

        With tracing on, the hop is a ``shard.relay`` span: adopted
        from the client's wire context (``ctx``), re-injected toward
        the worker so its subtree chains under the relay, and marked
        with the router-queue/socket/worker split (module docstring).
        """
        try:
            name = resolve_name(body).rstrip(".").lower()
        except ShardError as err:
            return STATUS_ERR, f"bad resolve request: {err!r}".encode()
        owner = self.ring.owner(name)
        handle = self._workers.get(owner)
        tracer = trace.tracer_for(self)
        if not tracer.enabled:
            # Tracing off here: forward the peer's context untouched —
            # a traced client still joins the worker's fragments even
            # through an untraced router (pass-through, no relay span).
            return await self._relay_to(handle, body, ctx, None)
        with tracer.adopt(*ctx) if ctx is not None else NULLCTX:
            with tracer.span("shard.relay", shard=owner, domain=name) as sp:
                fwd = (
                    int(sp.trace_id, 16),
                    int(sp.span_id, 16),
                    1 if sp.sampled else 0,
                )
                return await self._relay_to(handle, body, fwd, sp)

    async def _relay_to(self, handle, body, ctx, span):
        """THE one copy of the relay's error contract (down shard /
        dead channel → STATUS_ERR), shared by the traced and untraced
        paths.  A failing hop is evidence: the errored relay span says
        exactly which shard's slice refused, even when no worker
        fragment exists."""
        if handle is None or handle.chan is None:
            if span is not None:
                span.finish("error", err="shard down")
            return STATUS_ERR, b"shard down"
        t0 = time.monotonic()
        try:
            status, reply = await handle.chan.request(
                OP_RESOLVE, body, trace_ctx=ctx, span=span
            )
        except ShardError as err:
            if span is not None:
                span.finish("error", err=repr(err))
            return STATUS_ERR, repr(err).encode()
        if status == STATUS_OK:
            # One observation per ADMITTED resolve (ISSUE 17): the
            # registrar_admitted_resolve_seconds histogram's feed — a
            # shed request (refused by us or by the worker) never lands
            # here, so the histogram prices exactly the work the armor
            # let through.
            self.emit("admitted", time.monotonic() - t0)
        return status, reply

    def ring_info(self) -> Dict:
        return {
            "generation": self.generation,
            "vnodes": self.vnodes,
            "shards": [
                {
                    "shard": handle.shard_id,
                    "socket": handle.socket_path,
                    "up": handle.up,
                }
                for handle in sorted(
                    self._workers.values(), key=lambda h: h.shard_id
                )
                if handle.shard_id in self.ring.shard_ids
            ],
        }

    # -- rollup -------------------------------------------------------------

    def respawns_total(self) -> int:
        """Worker crashes detected (and, policy allowing, respawned)
        across every shard slot since start."""
        return sum(h.respawns for h in self._workers.values())

    def shard_resolves_total(self, shard_id: int) -> int:
        """Cumulative resolves served by a shard slot across every
        incarnation of its worker (the metrics rollup's monotonic
        source)."""
        handle = self._workers.get(shard_id)
        return handle.resolves_total() if handle is not None else 0

    def sheds_total(self) -> Dict[str, int]:
        """Deliberate rejects by reason, tier-wide: the router's own
        (rate_limited) plus every worker slot's rollup, monotonic
        across worker respawns (registrar_shed_total's source)."""
        out = dict(self._sheds)
        for handle in self._workers.values():
            for reason, count in handle.sheds_total().items():
                out[reason] += count
        return out

    def shard_queue_depth(self, shard_id: int) -> int:
        """The shard worker's last-polled resolve dispatch backlog
        (registrar_queue_depth's source)."""
        handle = self._workers.get(shard_id)
        return handle.queue_depth() if handle is not None else 0

    def dns_rollup(self) -> Optional[Dict]:
        """Tier-wide DNS stats: every slot's cumulative total folded
        into one dict (queries by "QTYPE RCODE", the UDP latency
        ladder, encode-cache counters, sheds) — monotonic across
        respawns; the registrar_dns_* families' source.  None when the
        DNS frontend is not configured."""
        if self.dns is None:
            return None
        out: Dict = {}
        entries = 0
        for handle in self._workers.values():
            total = handle.dns_total()
            # entries is a point-in-time gauge per worker: SUM across
            # the tier (the merge's live-wins rule is for one slot).
            entries += int((total.get("encode_cache") or {}).get(
                "entries", 0
            ))
            out = _dns_merge(out, total)
        out.setdefault("encode_cache", {})["entries"] = entries
        out["port"] = self.dns.get("port")
        return out

    def shards_down(self) -> List[int]:
        return sorted(
            sid
            for sid in self.ring.shard_ids
            if not (
                self._workers.get(sid) is not None
                and self._workers[sid].up
            )
        )

    async def status(self) -> Dict:
        """The router's ``GET /status`` snapshot: per-shard session /
        entries / coherence lag rolled up, plus the uptime_s +
        last_transition stamps the PR-9 MTTR-from-status contract
        expects."""
        await self._poll_statuses()
        down = self.shards_down()
        shards: Dict[str, Dict] = {}
        for handle in sorted(
            self._workers.values(), key=lambda h: h.shard_id
        ):
            st = handle.last_status
            shards[str(handle.shard_id)] = {
                "up": handle.up,
                "pid": handle.proc.pid if handle.proc else None,
                "socket": handle.socket_path,
                "respawns": handle.respawns,
                "resolves_total": handle.resolves_total(),
                "queue_depth": handle.queue_depth(),
                "sheds": handle.sheds_total(),
                "dns": handle.dns_total() if self.dns is not None else None,
                "entries": st.get("entries", 0),
                "warm": st.get("warm", 0),
                "authoritative": st.get("authoritative", False),
                "coherence_lag_ms_last": st.get("coherence_lag_ms_last"),
                "session": st.get("session", {}),
            }
        return {
            "serve": {
                "socketPath": self.socket_path,
                "shards": self.shards,
                "generation": self.generation,
                "reshards": self.reshards,
                "attachSpread": self.attach_spread,
                "respawns_total": self.respawns_total(),
                "overload": self.overload,
                "sheds_total": self.sheds_total(),
                "dns": self.dns,
            },
            "degraded": bool(down),
            "shards_down": down,
            "shards": shards,
            "uptime_s": (
                round(time.time() - self.started_at, 1)
                if self.started_at
                else None
            ),
            "last_transition": dict(self.last_transition),
        }

    async def collect_trace(self, trace_id: str) -> Dict:
        """Assemble ONE cross-process tree for ``trace_id`` (ISSUE 13).

        Fans an ``OP_TRACE`` query to every worker, merges the
        fragments with the router's own flight recorder (which in-
        process callers like the SLO harness share, so their spans fold
        in automatically), and reconstructs the parent tree via
        :mod:`registrar_tpu.traceview`.  A dead or frozen worker cannot
        hand over its fragment; its absence is recorded in ``sources``
        and any span whose parent lived there surfaces under the
        ``<missing parent>`` node — a crashed worker must not silently
        erase its subtree.  ``GET /debug/trace?id=`` and ``zkcli trace
        --id`` ride this.
        """
        tracer = trace.tracer_for(self)
        entries: List[Dict] = []
        sources: List[Dict] = []

        def take(raw_entries, proc: str) -> int:
            count = 0
            for raw in raw_entries:
                entry = dict(raw)
                entry.setdefault("proc", proc)
                entries.append(entry)
                count += 1
            return count

        with tracer.span("shard.trace_collect", trace_id=trace_id) as sp:
            own = tracer.dump(trace_id=trace_id)
            sources.append(
                {
                    "proc": "router",
                    "pid": os.getpid(),
                    "entries": take(own.get("entries", ()), "router"),
                }
            )
            req = json.dumps({"trace_id": trace_id}).encode()

            async def query(handle) -> Dict:
                proc = f"shard{handle.shard_id}"
                if handle.chan is None:
                    return {"proc": proc, "error": "worker down"}
                try:
                    status, reply = await asyncio.wait_for(
                        handle.chan.request(OP_TRACE, req), timeout=2.0
                    )
                except (ShardError, asyncio.TimeoutError) as err:
                    return {"proc": proc, "error": repr(err)}
                if status != STATUS_OK:
                    return {
                        "proc": proc,
                        "error": bytes(reply).decode("utf-8", "replace"),
                    }
                return {"proc": proc, "dump": json.loads(bytes(reply))}

            # Concurrent fan-out: the per-worker queries are independent
            # pipelined channel requests, so N frozen workers cost ONE
            # 2 s window, not 2 s × N of serialized /debug/trace stall.
            answers = await asyncio.gather(
                *(
                    query(handle)
                    for handle in sorted(
                        self._workers.values(), key=lambda h: h.shard_id
                    )
                )
            )
            for answer in answers:
                dump = answer.pop("dump", None)
                if dump is not None:
                    answer["pid"] = dump.get("pid")
                    answer["entries"] = take(
                        dump.get("entries", ()), answer["proc"]
                    )
                sources.append(answer)
            sp.set_attr("entries", len(entries))
        tree = traceview.assemble(entries, trace_id)
        tree["sources"] = sources
        return tree


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------


def _raise_reply_error(reply) -> None:
    """Raise the client-side class for one STATUS_ERR reply: a SHED:
    body (any hop's deliberate overload reject — the router forwards
    worker error bodies verbatim) becomes :class:`ShardShedError` with
    its reason; anything else stays plain :class:`ShardError`."""
    reason = shed_reason(reply)
    text = bytes(reply).decode("utf-8", "replace")
    if reason is not None and reason in SHED_REASONS:
        detail = text[len(SHED_PREFIX) + len(reason):].strip()
        raise ShardShedError(reason, detail)
    raise ShardError(text)


class ShardClient:
    """Resolve through the router's front socket (the simple path: one
    connection, the router relays to owners)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._chan: Optional[Channel] = None
        #: serializes the lazy reconnect: N concurrent requests racing a
        #: dropped channel must share ONE reopen, not leak N-1 channels
        #: (each with a live reader task) to the last-write-wins store
        self._reopen_lock: Optional[asyncio.Lock] = None

    async def connect(self) -> "ShardClient":
        self._reopen_lock = asyncio.Lock()
        self._chan = await Channel.open(self.socket_path)
        return self

    async def close(self) -> None:
        if self._chan is not None:
            await self._chan.close()
            self._chan = None

    async def __aenter__(self) -> "ShardClient":
        return await self.connect()

    async def __aexit__(self, *_exc) -> None:
        await self.close()

    async def _request(
        self, op: int, body, trace_ctx: Optional[Tuple] = None
    ) -> memoryview:
        if self._chan is None or self._chan.closed:
            if self._reopen_lock is None:
                self._reopen_lock = asyncio.Lock()
            async with self._reopen_lock:
                if self._chan is None or self._chan.closed:
                    self._chan = await Channel.open(self.socket_path)
        status, reply = await self._chan.request(
            op, body, trace_ctx=trace_ctx
        )
        if status != STATUS_OK:
            _raise_reply_error(reply)
        return reply

    async def resolve(
        self, name: str, qtype: str = "A", live: bool = False
    ) -> Resolution:
        # Inject the ambient span's context (ISSUE 13): a traced caller
        # (the SLO prober, a future DNS frontend) joins its span tree
        # to the router's relay and the worker's resolve subtree.  With
        # no active span this is None and the frame is byte-identical
        # to the PR-12 format.
        return decode_resolution(
            await self._request(
                OP_RESOLVE,
                pack_resolve(name, qtype, live),
                trace_ctx=trace.current_context(),
            )
        )

    async def trace_tree(self, trace_id: str) -> Dict:
        """The assembled cross-process tree for ``trace_id`` (the
        router's OP_TRACE fan-out)."""
        return json.loads(
            bytes(
                await self._request(
                    OP_TRACE, json.dumps({"trace_id": trace_id}).encode()
                )
            ).decode()
        )

    async def ring(self) -> Dict:
        return json.loads(bytes(await self._request(OP_RING, b"")).decode())

    async def status(self) -> Dict:
        return json.loads(
            bytes(await self._request(OP_STATUS, b"")).decode()
        )


class ShardDirectClient:
    """The SO_REUSEPORT-shaped data plane: fetch the ring once from the
    router, then talk to every worker directly — no middleman in the
    request path (what the DNS frontend will do, and what bench.py
    measures for the scaling matrix).  Re-fetch via :meth:`refresh`
    after a reshard."""

    def __init__(self, router_socket: str):
        self.router_socket = router_socket
        self.generation: Optional[int] = None
        self._ring: Optional[HashRing] = None
        self._chans: Dict[int, Channel] = {}
        self._sockets: Dict[int, str] = {}
        #: serializes per-shard channel opens: N concurrent resolves
        #: racing a cold (or dropped) channel must share ONE open — each
        #: leaked loser would keep a live reader task forever (same
        #: hazard ShardClient's _reopen_lock guards)
        self._chan_locks: Dict[int, asyncio.Lock] = {}

    async def connect(self) -> "ShardDirectClient":
        await self.refresh()
        return self

    async def refresh(self) -> None:
        async with ShardClient(self.router_socket) as rc:
            info = await rc.ring()
        await self._close_chans()
        self.generation = info["generation"]
        self._sockets = {
            entry["shard"]: entry["socket"] for entry in info["shards"]
        }
        self._ring = HashRing(
            self._sockets.keys(), vnodes=info.get("vnodes", DEFAULT_VNODES)
        )

    async def _close_chans(self) -> None:
        chans, self._chans = self._chans, {}
        for chan in chans.values():
            await chan.close()

    async def close(self) -> None:
        await self._close_chans()

    async def __aenter__(self) -> "ShardDirectClient":
        return await self.connect()

    async def __aexit__(self, *_exc) -> None:
        await self.close()

    def owner(self, name: str) -> int:
        return self._ring.owner(name.rstrip(".").lower())

    async def channel(self, shard_id: int) -> Channel:
        chan = self._chans.get(shard_id)
        if chan is None or chan.closed:
            lock = self._chan_locks.setdefault(shard_id, asyncio.Lock())
            async with lock:
                chan = self._chans.get(shard_id)
                if chan is None or chan.closed:
                    chan = await Channel.open(self._sockets[shard_id])
                    self._chans[shard_id] = chan
        return chan

    async def resolve(
        self, name: str, qtype: str = "A", live: bool = False
    ) -> Resolution:
        chan = await self.channel(self.owner(name))
        # Same injection rule as ShardClient: the direct data plane
        # skips the router, so the worker's subtree parents straight
        # under the caller's ambient span (what the DNS frontend will
        # do — its query id maps onto this trace id).
        status, reply = await chan.request(
            OP_RESOLVE,
            pack_resolve(name, qtype, live),
            trace_ctx=trace.current_context(),
        )
        if status != STATUS_OK:
            _raise_reply_error(reply)
        return decode_resolution(reply)


if __name__ == "__main__":
    sys.exit(worker_entry(sys.argv[1:]))
