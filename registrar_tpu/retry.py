"""Exponential-backoff retry, mirroring the reference's `backoff` library use.

Two policies exist in the reference and both are preserved exactly
(BASELINE.md):

  * initial ZK connect: infinite attempts, exponential 1 s -> 90 s
    (reference lib/zk.js:97-101)
  * application heartbeat: 5 attempts, exponential 1 s -> 30 s
    (reference lib/zk.js:38-42)

Delay schedule matches node-backoff's ExponentialStrategy: the first retry
waits ``initial_delay``, each subsequent retry doubles it, capped at
``max_delay``.

Beyond the reference, two robustness layers ride here (ISSUE 2):

  * **Decorrelated jitter** (``jitter="decorrelated"``): pure doubling makes
    every client of a restarted ensemble reconnect in lockstep — N workers
    all retry at t+1, t+3, t+7, ... and the herd re-stampedes the servers at
    each step.  The decorrelated schedule (AWS architecture blog's
    "Exponential Backoff And Jitter") draws each delay uniformly from
    ``[initial_delay, 3 * previous_delay]`` capped at ``max_delay``, so
    retries spread out instead of synchronizing.  :data:`RECONNECT_RETRY`
    adopts it for the client's default *reconnect* policy; the initial
    connect (:data:`CONNECT_RETRY`) keeps the reference's exact schedule.
  * **Error classification** (:func:`is_transient`): the predicate the
    retry layers share for "could retrying possibly help?" — connection
    loss, per-operation timeouts, and plain socket errors are transient;
    SESSION_EXPIRED (and every other ZooKeeper semantic error) is not.
"""

from __future__ import annotations

import asyncio
import math
import random
from dataclasses import dataclass
from typing import Awaitable, Callable, Iterator, Optional, TypeVar

from registrar_tpu.zk.protocol import Err, ZKError

T = TypeVar("T")

#: jitter modes accepted by :class:`RetryPolicy`
JITTER_MODES = ("none", "decorrelated")


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: float = 5  # math.inf for unbounded
    initial_delay: float = 1.0  # seconds
    max_delay: float = 30.0  # seconds
    #: "none" = the reference's pure doubling; "decorrelated" = each delay
    #: drawn from [initial_delay, 3 * previous] capped at max_delay, so a
    #: fleet that lost its ensemble together does not retry in lockstep.
    jitter: str = "none"

    def __post_init__(self) -> None:
        if self.jitter not in JITTER_MODES:
            raise ValueError(
                f"jitter must be one of {JITTER_MODES}, got {self.jitter!r}"
            )

    def delay(self, attempt: int) -> float:
        """Deterministic delay before retry number ``attempt`` (0-based) —
        the pure doubling schedule, jitter ignored (kept stable for the
        reference-parity pins in tests/test_retry.py).

        The exponent is clamped: an unbounded reconnect loop that has
        been retrying for hours reaches attempts past 1024, where a raw
        ``2**attempt`` overflows float conversion and the retry loop —
        the thing keeping a disconnected daemon alive — dies with
        OverflowError.  2**64 × any initial_delay is already beyond any
        real max_delay, so the clamp never changes a produced value.
        """
        return min(self.initial_delay * (2 ** min(attempt, 64)), self.max_delay)

    def schedule(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """Yield successive backoff delays, honoring the jitter mode.

        With ``jitter="none"`` this is exactly ``delay(0), delay(1), ...``.
        With ``jitter="decorrelated"``, each delay is drawn from
        ``uniform(initial_delay, 3 * previous)`` capped at ``max_delay``
        (``rng`` makes a schedule reproducible in tests; default is the
        module RNG).  Every jittered delay stays within
        ``[initial_delay, max_delay]`` — the same envelope operators
        already budget for.
        """
        if self.jitter == "none":
            attempt = 0
            while True:
                yield self.delay(attempt)
                attempt += 1
        else:
            uniform = (rng or random).uniform
            prev = self.initial_delay
            while True:
                prev = min(self.max_delay, uniform(self.initial_delay, prev * 3))
                yield prev


#: reference lib/zk.js:38-42
HEARTBEAT_RETRY = RetryPolicy(max_attempts=5, initial_delay=1.0, max_delay=30.0)
#: reference lib/zk.js:97-101
CONNECT_RETRY = RetryPolicy(max_attempts=math.inf, initial_delay=1.0, max_delay=90.0)
#: the client's default *reconnect* policy: the reference's 1-90 s envelope
#: with decorrelated jitter, so a fleet dropped by an ensemble restart does
#: not reconnect as a thundering herd (ISSUE 2 satellite).
RECONNECT_RETRY = RetryPolicy(
    max_attempts=math.inf, initial_delay=1.0, max_delay=90.0,
    jitter="decorrelated",
)


def is_transient(err: BaseException) -> bool:
    """True when retrying the failed operation could plausibly succeed.

    Transient: CONNECTION_LOSS (the connection died; a reconnect may
    already be in progress), OPERATION_TIMEOUT (a per-operation deadline
    tore the connection down, :class:`~registrar_tpu.zk.client.
    OperationTimeoutError`), NOT_READONLY (the write reached a read-only
    minority member — it succeeds once the client fails over to a
    read-write member or quorum returns, which the client's rw-probe
    drives), and plain socket/timeout errors.

    NOT transient: SESSION_EXPIRED (a dead session cannot be retried back
    to life — the orchestrator must build a new one) and every other
    ZooKeeper semantic error (NO_NODE, NODE_EXISTS, NO_AUTH, ...), where a
    retry would just repeat the same answer.

    Explicitly FATAL: ``ValueError``/``RuntimeError`` (and subclasses —
    record validation, the interface-probe failure in
    ``records.default_address``, jute encode errors): the operation's
    *input* is wrong, so every retry replays the same failure.  These
    were always non-transient by the fall-through default; naming them
    keeps the classification deliberate — checklib's
    retry-contract-drift rule verifies every class that can reach a
    retry boundary is decided HERE, not by silence.
    """
    if isinstance(err, ZKError):
        return err.code in (
            Err.CONNECTION_LOSS, Err.OPERATION_TIMEOUT, Err.NOT_READONLY
        )
    if isinstance(err, (ValueError, RuntimeError)):
        return False
    return isinstance(err, (ConnectionError, asyncio.TimeoutError, OSError))


async def call_with_backoff(
    fn: Callable[[], Awaitable[T]],
    policy: RetryPolicy,
    on_backoff: Optional[Callable[[int, float, Exception], object]] = None,
    retryable: Optional[Callable[[Exception], bool]] = None,
    rng: Optional[random.Random] = None,
) -> T:
    """Run ``fn`` until it succeeds or the policy's attempts are exhausted.

    ``on_backoff(attempt_number, delay_seconds, error)`` is invoked before
    each sleep, mirroring node-backoff's 'backoff' event (used by the
    reference for connect-attempt logging, lib/zk.js:104-119).  Cancelling
    the awaiting task aborts the loop (the analog of `retry.abort()`).

    ``retryable(err)`` returning False makes the error fatal: it propagates
    immediately without further attempts (e.g. session expiry during a
    reconnect loop — retrying cannot resurrect an expired session).

    ``rng`` seeds a jittered policy's delay draws (tests); ignored for
    ``jitter="none"`` policies.
    """
    attempt = 0
    delays = policy.schedule(rng)
    while True:
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except Exception as err:
            if retryable is not None and not retryable(err):
                raise
            if attempt + 1 >= policy.max_attempts:
                raise
            delay = next(delays)
            if on_backoff is not None:
                on_backoff(attempt, delay, err)
            await asyncio.sleep(delay)
            attempt += 1
