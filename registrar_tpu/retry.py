"""Exponential-backoff retry, mirroring the reference's `backoff` library use.

Two policies exist in the reference and both are preserved exactly
(BASELINE.md):

  * initial ZK connect: infinite attempts, exponential 1 s -> 90 s
    (reference lib/zk.js:97-101)
  * application heartbeat: 5 attempts, exponential 1 s -> 30 s
    (reference lib/zk.js:38-42)

Delay schedule matches node-backoff's ExponentialStrategy: the first retry
waits ``initial_delay``, each subsequent retry doubles it, capped at
``max_delay``.
"""

from __future__ import annotations

import asyncio
import math
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional, TypeVar

T = TypeVar("T")


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: float = 5  # math.inf for unbounded
    initial_delay: float = 1.0  # seconds
    max_delay: float = 30.0  # seconds

    def delay(self, attempt: int) -> float:
        """Delay before retry number ``attempt`` (0-based)."""
        return min(self.initial_delay * (2**attempt), self.max_delay)


#: reference lib/zk.js:38-42
HEARTBEAT_RETRY = RetryPolicy(max_attempts=5, initial_delay=1.0, max_delay=30.0)
#: reference lib/zk.js:97-101
CONNECT_RETRY = RetryPolicy(max_attempts=math.inf, initial_delay=1.0, max_delay=90.0)


async def call_with_backoff(
    fn: Callable[[], Awaitable[T]],
    policy: RetryPolicy,
    on_backoff: Optional[Callable[[int, float, Exception], object]] = None,
    retryable: Optional[Callable[[Exception], bool]] = None,
) -> T:
    """Run ``fn`` until it succeeds or the policy's attempts are exhausted.

    ``on_backoff(attempt_number, delay_seconds, error)`` is invoked before
    each sleep, mirroring node-backoff's 'backoff' event (used by the
    reference for connect-attempt logging, lib/zk.js:104-119).  Cancelling
    the awaiting task aborts the loop (the analog of `retry.abort()`).

    ``retryable(err)`` returning False makes the error fatal: it propagates
    immediately without further attempts (e.g. session expiry during a
    reconnect loop — retrying cannot resurrect an expired session).
    """
    attempt = 0
    while True:
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except Exception as err:
            if retryable is not None and not retryable(err):
                raise
            if attempt + 1 >= policy.max_attempts:
                raise
            delay = policy.delay(attempt)
            if on_backoff is not None:
                on_backoff(attempt, delay, err)
            await asyncio.sleep(delay)
            attempt += 1
