"""``python -m registrar_tpu`` entry point (the reference's `node main.js`)."""

from registrar_tpu.main import main

main()
