"""Real DNS over the sharded serve tier (ISSUE 19).

The paper's point is that registrar writes znodes *so that Binder can
answer DNS* — yet through PR 18 every resolve in this repo traveled a
bespoke unix-socket protocol.  This module closes ROADMAP item 1 as a
*performance* feature, not a gateway: every `ShardWorker` binds its own
UDP socket to the same host:port with ``SO_REUSEPORT`` so the kernel
does the fan-out (zero router hops on the hot path), plus a TCP
listener on the same port for TC-bit retries.  Correctness never
depends on which worker the kernel picks — `ZKCache` is read-through,
so any worker answers any domain (the ring is a warmth hint; see
docs/DESIGN.md "Sharded serve tier").  A dead worker's sockets close
with it and the kernel rebalances onto the survivors.

Three layers:

* **Wire codec** — dependency-free: header, QNAME parse/encode with
  compression pointers, A/SRV/TXT answers, SOA-backed NXDOMAIN/NODATA
  negatives, EDNS0 size negotiation, 0x20 case preservation (the
  response echoes the query's exact qname bytes; answer owners point
  at the question via a compression pointer, so the case propagates),
  and malformed packets rejected through the PR-15
  ``registrar_malformed_frames_total`` machinery (surface ``dns``).
  Every peer-read integer is bound-checked before it sizes a loop or
  slice — the generation-5 taint analysis enforces it (this module is
  a declared trust boundary, docs/DESIGN.md appendix).

* **Answer-encode cache** (:class:`EncodeCache`) — each warm
  `Resolution` is rendered into final RR wire bytes exactly once and
  the template is invalidated by the same ZKCache watch events that
  drop the underlying entry (including negative entries: a cached
  NXDOMAIN rides the exists-watch ZKCache arms on NO_NODE, so even
  "this name does not exist" is watch-coherent).  A warm UDP answer is
  parse-header → memcpy-template → patch-id/0x20-name → sendto.
  Answer TTLs are the record TTLs registrar itself wrote; the
  *negative* TTL derives from the cache's coherence bound (staleness
  ≤ watch delivery while authoritative), so a resolver never believes
  an absence longer than the tier itself would.  When the backing
  ZKCache *loses* authority the front serves stale (RFC 8767): the
  templates rendered before the drop keep answering for a bounded
  window (``staleTtl``, default 30 s) so a backend election is not a
  DNS outage for names whose data never changed — while nothing new is
  cached, and restoration flushes everything, because the watch events
  missed during the outage make every surviving template unprovable.

* **Overload armor** — the PR-17 discipline mapped onto DNS: a
  token-bucket rate limit and a pending-resolve bound shed with rcode
  REFUSED, *never* silence (a silent drop looks like packet loss and
  triggers client retry storms).  Warm encode-cache hits bypass the
  bounds — they cost a memcpy, and shedding them would reduce
  capacity, not protect it.

The protocol constants below are machine-checked the same way the
shard tier's are: checklib's ``opcode-dispatch-drift`` diffs the
``QTYPE_*``/``RCODE_*`` families against the dispatch tables here and
the protocol table in docs/DESIGN.md, and ``flag-bit-overlap`` proves
the header flag masks disjoint.
"""

from __future__ import annotations

import asyncio
import socket
import struct
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import malformed
from .metrics import DEFAULT_BUCKETS

# ---- protocol constants -----------------------------------------------------

QTYPE_A = 1
QTYPE_SOA = 6
QTYPE_TXT = 16
QTYPE_SRV = 33
QTYPE_OPT = 41

RCODE_NOERROR = 0
RCODE_FORMERR = 1
RCODE_SERVFAIL = 2
RCODE_NXDOMAIN = 3
RCODE_NOTIMP = 4
RCODE_REFUSED = 5

CLASS_IN = 1

#: Header flag masks (16-bit flags word).  Pairwise bit-disjoint and
#: disjoint from every code value above — checklib `flag-bit-overlap`.
FLAG_QR = 0x8000
FLAG_AA = 0x0400
FLAG_TC = 0x0200
FLAG_RD = 0x0100
FLAG_RA = 0x0080

#: Dispatch tables: code -> presentation name.  These are the codec's
#: dispatch arms (every constant above must appear as a key — checklib
#: `opcode-dispatch-drift`), and `zkcli dig` renders through them.
QTYPE_NAMES = {
    QTYPE_A: "A",
    QTYPE_SOA: "SOA",
    QTYPE_TXT: "TXT",
    QTYPE_SRV: "SRV",
    QTYPE_OPT: "OPT",
}
RCODE_NAMES = {
    RCODE_NOERROR: "NOERROR",
    RCODE_FORMERR: "FORMERR",
    RCODE_SERVFAIL: "SERVFAIL",
    RCODE_NXDOMAIN: "NXDOMAIN",
    RCODE_NOTIMP: "NOTIMP",
    RCODE_REFUSED: "REFUSED",
}
TYPE_CODES = {name: code for code, name in QTYPE_NAMES.items()}

#: The qtypes a worker actually resolves (binderview's vocabulary).
SERVED_QTYPES = (QTYPE_A, QTYPE_SRV, QTYPE_TXT)

_DNS_HDR = struct.Struct(">HHHHHH")   # id, flags, qd, an, ns, ar
_QFIXED = struct.Struct(">HH")        # qtype, qclass
_RR_FIXED = struct.Struct(">HHIH")    # type, class, ttl, rdlength
_SRV_FIXED = struct.Struct(">HHH")    # priority, weight, port
_SOA_NUMS = struct.Struct(">IIIII")   # serial, refresh, retry, expire, min
_U16 = struct.Struct(">H")

MAX_LABEL_LEN = 63
MAX_NAME_LEN = 255
MAX_RRS = 256          # decode-side bound on peer RR counts
MAX_PTR_JUMPS = 16     # compression-pointer chain bound
MIN_UDP_PAYLOAD = 512  # the classic pre-EDNS ceiling
MAX_UDP_PAYLOAD = 4096  # clamp on a peer's advertised EDNS size
MAX_TCP_MSG = 65535    # the 2-byte length prefix's own ceiling

#: A compression pointer to offset 12 — the question name.  Every
#: answer whose owner IS the queried name points here, which is also
#: how 0x20 case preservation propagates into the answer section.
QUESTION_PTR = b"\xc0\x0c"

DEFAULT_UDP_PAYLOAD_MAX = 1232  # EDNS answer-size we advertise (no frag risk)
DEFAULT_NEGATIVE_TTL = 5  # seconds; ~the cache's watch-delivery bound
DEFAULT_STALE_TTL = 30  # seconds a degraded front may serve stale (RFC 8767)

#: Synthesized SOA timers (serial/refresh/retry/expire) for negative
#: answers.  registrar has no zone file and no serial discipline — the
#: values are conventional and fixed; only `minimum` (the negative
#: TTL) is meaningful, and it derives from the coherence bound.
SOA_TIMERS = (1, 3600, 600, 86400)


class DnsError(ValueError):
    """Any DNS wire-format violation (the codec's contract class)."""


class DnsFormatError(DnsError):
    """A parseable-enough header with garbage behind it: answer
    FORMERR (the query id is recoverable)."""

    def __init__(self, message: str, qid: Optional[int] = None):
        super().__init__(message)
        self.qid = qid


class DnsIgnore(DnsError):
    """A packet that must be dropped without a reply (a response
    echoed back at us, a header too short to even carry an id) —
    answering would risk reflection loops."""


class DnsRefused(DnsError):
    """Raised by a resolver callable to shed the query: answered
    REFUSED and counted under ``reason`` (the PR-17 taxonomy)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


# ---- names ------------------------------------------------------------------


def encode_name(name: str) -> bytes:
    """Dotted name -> uncompressed wire form (len-prefixed labels)."""
    name = name.rstrip(".")
    if not name:
        return b"\x00"
    out = bytearray()
    for label in name.split("."):
        raw = label.encode("latin-1")
        if not raw or len(raw) > MAX_LABEL_LEN:
            raise DnsError(f"bad label {label!r} in {name!r}")
        out.append(len(raw))
        out += raw
    out.append(0)
    if len(out) > MAX_NAME_LEN:
        raise DnsError(f"name too long: {name!r}")
    return bytes(out)


def parse_name(pkt: bytes, pos: int) -> Tuple[str, int]:
    """Decode a (possibly compressed) name at ``pos``.

    Returns ``(dotted_name, end)`` where ``end`` is the offset just
    past the name *at its original location* (pointers do not move
    it).  Pointer chains are bounded and must point strictly backward,
    so a hostile packet cannot loop the parser.
    """
    labels: List[bytes] = []
    end = -1
    jumps = 0
    total = 0
    while True:
        if pos >= len(pkt):
            raise DnsFormatError("name runs off the packet")
        length = pkt[pos]
        if length & 0xC0 == 0xC0:
            if pos + 1 >= len(pkt):
                raise DnsFormatError("truncated compression pointer")
            target = ((length & 0x3F) << 8) | pkt[pos + 1]
            if end < 0:
                end = pos + 2
            jumps += 1
            if jumps > MAX_PTR_JUMPS or target >= pos:
                raise DnsFormatError("compression pointer loop")
            pos = target
            continue
        if length & 0xC0:
            raise DnsFormatError("reserved label type")
        pos += 1
        if length == 0:
            if end < 0:
                end = pos
            return b".".join(labels).decode("latin-1"), end
        total += length + 1
        if total > MAX_NAME_LEN:
            raise DnsFormatError("name exceeds 255 octets")
        if pos + length > len(pkt):
            raise DnsFormatError("label runs off the packet")
        labels.append(pkt[pos:pos + length])
        pos += length


# ---- query parsing (the server side) ----------------------------------------


class DnsQuery:
    """One parsed query: everything the serve path needs, including the
    qname's exact wire bytes for the 0x20 case echo."""

    __slots__ = ("qid", "flags", "qname_wire", "lname", "qtype", "qclass",
                 "edns_size")

    def __init__(self, qid, flags, qname_wire, lname, qtype, qclass,
                 edns_size):
        self.qid = qid
        self.flags = flags
        self.qname_wire = qname_wire  # exact case, trailing 0x00 included
        self.lname = lname            # lowercased dotted form (cache key)
        self.qtype = qtype
        self.qclass = qclass
        self.edns_size = edns_size    # clamped advertised size, or 0


def parse_query(pkt: bytes) -> DnsQuery:
    """Parse one incoming query or raise the codec's contract classes:
    :class:`DnsIgnore` (drop), :class:`DnsFormatError` (FORMERR)."""
    if len(pkt) < _DNS_HDR.size:
        raise DnsIgnore("short header")
    qid, flags, qd, an, ns, ar = _DNS_HDR.unpack_from(pkt, 0)
    if flags & FLAG_QR:
        raise DnsIgnore("QR set: a response, not a query")
    if qd != 1 or an != 0 or ns != 0:
        raise DnsFormatError("expected exactly one question", qid=qid)
    if ar > MAX_RRS:
        raise DnsFormatError("additional count out of bounds", qid=qid)
    name, pos = parse_name(pkt, _DNS_HDR.size)
    if pos + _QFIXED.size > len(pkt):
        raise DnsFormatError("truncated question", qid=qid)
    qname_wire = pkt[_DNS_HDR.size:pos]
    qtype, qclass = _QFIXED.unpack_from(pkt, pos)
    pos += _QFIXED.size
    # EDNS0: scan the additional section for an OPT RR; its CLASS field
    # is the sender's UDP payload size.  Every RR length is bound-checked
    # before it advances the cursor (taint discipline).
    edns_size = 0
    for _ in range(ar):
        if pos >= len(pkt):
            break
        _, rpos = parse_name(pkt, pos)
        if rpos + _RR_FIXED.size > len(pkt):
            raise DnsFormatError("truncated additional RR", qid=qid)
        rtype, rclass, _rttl, rdlen = _RR_FIXED.unpack_from(pkt, rpos)
        next_pos = rpos + _RR_FIXED.size
        if next_pos + rdlen > len(pkt):
            raise DnsFormatError("additional RR runs off the packet",
                                 qid=qid)
        if rtype == QTYPE_OPT:
            edns_size = max(MIN_UDP_PAYLOAD, min(rclass, MAX_UDP_PAYLOAD))
        pos = next_pos + rdlen
    return DnsQuery(qid, flags, qname_wire, name.lower(), qtype, qclass,
                    edns_size)


# ---- RR rendering -----------------------------------------------------------


def render_rdata(rtype: int, data: str) -> bytes:
    """binderview's presentation data (`Answer.data`) -> RDATA bytes.
    The single place RR bodies are rendered — the encode cache and
    `Resolution.to_wire_records()` both come through here."""
    if rtype == QTYPE_A:
        return socket.inet_aton(data)
    if rtype == QTYPE_SRV:
        prio, weight, port, target = data.split()
        return _SRV_FIXED.pack(int(prio), int(weight), int(port)) + \
            encode_name(target)
    if rtype == QTYPE_TXT:
        raw = data.encode("latin-1")
        out = bytearray()
        while True:
            chunk, raw = raw[:255], raw[255:]
            out.append(len(chunk))
            out += chunk
            if not raw:
                return bytes(out)
    raise DnsError(f"unrenderable rtype {rtype}")


def wire_records(resolution) -> Tuple[list, list]:
    """A `binderview.Resolution` -> ``(answers, additionals)`` as
    ``(name, type_code, ttl, rdata_bytes)`` tuples — the stable hook
    behind ``Resolution.to_wire_records()``."""
    def _rr(answer):
        code = TYPE_CODES[answer.rtype]
        return (answer.name, code, answer.ttl,
                render_rdata(code, answer.data))
    return ([_rr(a) for a in resolution.answers],
            [_rr(a) for a in resolution.additionals])


def _encode_rr(owner_wire: bytes, rtype: int, ttl: int,
               rdata: bytes) -> bytes:
    return owner_wire + _RR_FIXED.pack(rtype, CLASS_IN, int(ttl),
                                       len(rdata)) + rdata


def _opt_rr(payload_size: int) -> bytes:
    # root name, type OPT, class = our payload size, ttl = 0 flags, no rdata
    return b"\x00" + _RR_FIXED.pack(QTYPE_OPT, payload_size, 0, 0)


def build_answer_template(lname: str, qtype: int, resolution) -> bytes:
    """Render a Resolution into a full response template: id 0, flags
    QR|AA, canonical-lowercase question, answers/additionals.  Owners
    equal to the queried name become compression pointers at the
    question (12 bytes in), which is also how the 0x20 case echo
    propagates.  No OPT — that is appended per-query at serve time."""
    question = encode_name(lname) + _QFIXED.pack(qtype, CLASS_IN)
    answers, additionals = wire_records(resolution)
    body = bytearray()

    def owner_wire(name: str) -> bytes:
        if name.lower().rstrip(".") == lname.rstrip("."):
            return QUESTION_PTR
        return encode_name(name)

    for name, code, ttl, rdata in answers:
        body += _encode_rr(owner_wire(name), code, ttl, rdata)
    for name, code, ttl, rdata in additionals:
        body += _encode_rr(owner_wire(name), code, ttl, rdata)
    header = _DNS_HDR.pack(0, FLAG_QR | FLAG_AA, 1, len(answers), 0,
                           len(additionals))
    return header + question + bytes(body)


def build_negative_template(lname: str, qtype: int, rcode: int,
                            negative_ttl: int) -> bytes:
    """NXDOMAIN (rcode 3) or NODATA (NOERROR, zero answers), both with
    an SOA authority record so resolvers can cache the negative.  The
    SOA owner is the queried name's parent (registrar has no zone cuts;
    the parent is the closest enclosing name Binder would also pick),
    its timers are the fixed :data:`SOA_TIMERS`, and `minimum` — the
    field negative caches honor — is the coherence-bound TTL."""
    question = encode_name(lname) + _QFIXED.pack(qtype, CLASS_IN)
    apex = lname.split(".", 1)[1] if "." in lname else lname
    serial, refresh, retry, expire = SOA_TIMERS
    soa_rdata = (encode_name("ns0." + apex)
                 + encode_name("hostmaster." + apex)
                 + _SOA_NUMS.pack(serial, refresh, retry, expire,
                                  int(negative_ttl)))
    soa = _encode_rr(encode_name(apex), QTYPE_SOA, int(negative_ttl),
                     soa_rdata)
    header = _DNS_HDR.pack(0, FLAG_QR | FLAG_AA | rcode, 1, 0, 1, 0)
    return header + question + soa


def render_from_template(template: bytes, query: DnsQuery,
                         limit: int) -> bytes:
    """The warm path: copy the template, patch the query id, echo the
    exact qname bytes (0x20 case) and the RD bit, append OPT when the
    query negotiated EDNS, truncate to ``limit`` with TC if needed."""
    out = bytearray(template)
    _U16.pack_into(out, 0, query.qid)
    tflags = _U16.unpack_from(template, 2)[0] | (query.flags & FLAG_RD)
    _U16.pack_into(out, 2, tflags)
    out[12:12 + len(query.qname_wire)] = query.qname_wire
    if query.edns_size:
        out += _opt_rr(DEFAULT_UDP_PAYLOAD_MAX)
        _U16.pack_into(out, 10, _U16.unpack_from(template, 10)[0] + 1)
    if len(out) <= limit:
        return bytes(out)
    # Too big for the transport: header + question (+ OPT) with TC set,
    # zero RR counts — the client retries over TCP.
    qend = 12 + len(query.qname_wire) + _QFIXED.size
    short = bytearray(out[:qend])
    _U16.pack_into(short, 2, tflags | FLAG_TC)
    _U16.pack_into(short, 6, 0)
    _U16.pack_into(short, 8, 0)
    if query.edns_size:
        _U16.pack_into(short, 10, 1)
        short += _opt_rr(DEFAULT_UDP_PAYLOAD_MAX)
    else:
        _U16.pack_into(short, 10, 0)
    return bytes(short)


def build_error_response(query: DnsQuery, rcode: int) -> bytes:
    """A minimal answerless response carrying ``rcode`` (REFUSED,
    SERVFAIL, NOTIMP): header + the echoed question."""
    flags = FLAG_QR | rcode | (query.flags & FLAG_RD)
    header = _DNS_HDR.pack(query.qid, flags, 1, 0, 0, 0)
    return header + query.qname_wire + _QFIXED.pack(query.qtype,
                                                    query.qclass)


def build_formerr_response(qid: int) -> bytes:
    """FORMERR with an empty question section — the packet was too
    mangled to echo its question back."""
    return _DNS_HDR.pack(qid, FLAG_QR | RCODE_FORMERR, 0, 0, 0, 0)


# ---- client side (zkcli dig, the SLO probe, bench, tests) -------------------


def build_query(qid: int, name: str, qtype: int, *, rd: bool = False,
                edns_size: int = 0) -> bytes:
    """One query packet.  ``name`` is sent byte-exact (callers doing
    0x20 mixing pass the mixed-case form)."""
    flags = FLAG_RD if rd else 0
    ar = 1 if edns_size else 0
    pkt = _DNS_HDR.pack(qid, flags, 1, 0, 0, ar) + encode_name(name) + \
        _QFIXED.pack(qtype, CLASS_IN)
    if edns_size:
        pkt += _opt_rr(edns_size)
    return pkt


class DnsResponse:
    """A decoded response, presentation-ready (dig-style strings)."""

    __slots__ = ("qid", "flags", "rcode", "tc", "qname", "qtype",
                 "answers", "authorities", "additionals")

    def __init__(self, qid, flags, qname, qtype):
        self.qid = qid
        self.flags = flags
        self.rcode = flags & 0x000F
        self.tc = bool(flags & FLAG_TC)
        self.qname = qname
        self.qtype = qtype
        self.answers: List[Tuple[str, str, int, str]] = []
        self.authorities: List[Tuple[str, str, int, str]] = []
        self.additionals: List[Tuple[str, str, int, str]] = []


def _render_rr_text(pkt: bytes, rtype: int, pos: int, rdlen: int) -> str:
    """RDATA at ``pos`` -> dig-style presentation text."""
    if rtype == QTYPE_A and rdlen == 4:
        return socket.inet_ntoa(pkt[pos:pos + 4])
    if rtype == QTYPE_SRV and rdlen >= _SRV_FIXED.size:
        prio, weight, port = _SRV_FIXED.unpack_from(pkt, pos)
        target, _ = parse_name(pkt, pos + _SRV_FIXED.size)
        return f"{prio} {weight} {port} {target}."
    if rtype == QTYPE_TXT:
        chunks = []
        cur, end = pos, pos + rdlen
        while cur < end:
            n = pkt[cur]
            cur += 1
            if cur + n > end:
                raise DnsFormatError("TXT string runs off its RDATA")
            chunks.append(pkt[cur:cur + n].decode("latin-1"))
            cur += n
        return " ".join(f'"{c}"' for c in chunks)
    if rtype == QTYPE_SOA:
        mname, p = parse_name(pkt, pos)
        rname, p = parse_name(pkt, p)
        if p + _SOA_NUMS.size > pos + rdlen:
            raise DnsFormatError("truncated SOA RDATA")
        serial, refresh, retry, expire, minimum = _SOA_NUMS.unpack_from(
            pkt, p)
        return (f"{mname}. {rname}. {serial} {refresh} {retry} "
                f"{expire} {minimum}")
    return pkt[pos:pos + rdlen].hex()


def decode_response(pkt: bytes) -> DnsResponse:
    """Decode a response into presentation form.  Every peer count and
    length is bound-checked before it drives a loop or slice."""
    if len(pkt) < _DNS_HDR.size:
        raise DnsFormatError("short header")
    qid, flags, qd, an, ns, ar = _DNS_HDR.unpack_from(pkt, 0)
    if qd > 1 or an > MAX_RRS or ns > MAX_RRS or ar > MAX_RRS:
        raise DnsFormatError("RR counts out of bounds", qid=qid)
    pos = _DNS_HDR.size
    qname, qtype = "", 0
    if qd:
        qname, pos = parse_name(pkt, pos)
        if pos + _QFIXED.size > len(pkt):
            raise DnsFormatError("truncated question", qid=qid)
        qtype, _ = _QFIXED.unpack_from(pkt, pos)
        pos += _QFIXED.size
    resp = DnsResponse(qid, flags, qname, qtype)
    for section, count in ((resp.answers, an), (resp.authorities, ns),
                           (resp.additionals, ar)):
        for _ in range(count):
            name, rpos = parse_name(pkt, pos)
            if rpos + _RR_FIXED.size > len(pkt):
                raise DnsFormatError("truncated RR", qid=qid)
            rtype, _rclass, ttl, rdlen = _RR_FIXED.unpack_from(pkt, rpos)
            rstart = rpos + _RR_FIXED.size
            if rstart + rdlen > len(pkt):
                raise DnsFormatError("RDATA runs off the packet", qid=qid)
            if rtype != QTYPE_OPT:
                section.append(
                    (name, QTYPE_NAMES.get(rtype, str(rtype)), ttl,
                     _render_rr_text(pkt, rtype, rstart, rdlen)))
            pos = rstart + rdlen
    return resp


async def query_udp(host: str, port: int, packet: bytes, *,
                    timeout: float = 2.0) -> bytes:
    """One UDP exchange.  Raises ``asyncio.TimeoutError`` on silence —
    the tier's armor answers REFUSED rather than dropping, so a timeout
    here means the tier (or the path to it) is down, not busy."""
    loop = asyncio.get_running_loop()
    fut: asyncio.Future = loop.create_future()

    class _Proto(asyncio.DatagramProtocol):
        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

        def error_received(self, exc):
            if not fut.done():
                fut.set_exception(exc)

    transport, _ = await loop.create_datagram_endpoint(
        _Proto, remote_addr=(host, port))
    try:
        transport.sendto(packet)
        return await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()


async def query_tcp(host: str, port: int, packet: bytes, *,
                    timeout: float = 5.0) -> bytes:
    """One TCP exchange (2-byte length prefix both ways) — the TC-bit
    retry path."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout)
    try:
        writer.write(_U16.pack(len(packet)) + packet)
        await asyncio.wait_for(writer.drain(), timeout)
        hdr = await asyncio.wait_for(reader.readexactly(2), timeout)
        (rlen,) = _U16.unpack(hdr)
        if rlen > MAX_TCP_MSG:
            raise DnsFormatError("TCP response length out of bounds")
        return await asyncio.wait_for(reader.readexactly(rlen), timeout)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass


# ---- the answer-encode cache ------------------------------------------------


class EncodeCache:
    """Warm `Resolution`s rendered to final RR wire bytes exactly once.

    Keys are ``(lname, qtype_code)``; every template is additionally
    indexed under its *base domain* — the queried name with service
    underscore labels stripped — so one ZKCache ``invalidated`` event
    (node write, instance child churn, or a negative entry's
    exists-watch firing on creation) drops every answer shape rendered
    from that znode's subtree.  Negative templates (NXDOMAIN/NODATA)
    are cached under the same contract: ZKCache arms an exists-watch on
    NO_NODE, so the creation that would change the answer fires the
    same event.  ``flush()`` empties everything; the front calls it
    when authority is *restored* after an outage — the watch events
    missed while degraded make every surviving template unprovable —
    or when the bounded serve-stale window expires.  Deliberately NOT
    at the moment of degradation: that would turn every backend
    election into a DNS outage for names whose data never changed
    (RFC 8767 serve-stale; the PR-17 armor stance).
    """

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max_entries
        self._templates: Dict[Tuple[str, int], bytes] = {}
        self._by_domain: Dict[str, set] = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.flushes = 0

    @staticmethod
    def base_domain(lname: str) -> str:
        """The cache-index domain: strip leading ``_service``/``_proto``
        labels so ``_http._tcp.foo`` and ``foo`` share one index slot."""
        parts = lname.rstrip(".").split(".")
        while parts and parts[0].startswith("_"):
            parts = parts[1:]
        return ".".join(parts)

    def __len__(self) -> int:
        return len(self._templates)

    def get(self, key: Tuple[str, int]) -> Optional[bytes]:
        tpl = self._templates.get(key)
        if tpl is None:
            self.misses += 1
        else:
            self.hits += 1
        return tpl

    def put(self, key: Tuple[str, int], template: bytes) -> None:
        if len(self._templates) >= self.max_entries and \
                key not in self._templates:
            # Bounded exactly like ZKCache: oldest-first eviction; an
            # evicted template transparently re-renders on next miss.
            oldest = next(iter(self._templates))
            self._drop(oldest)
        self._templates[key] = template
        self._by_domain.setdefault(self.base_domain(key[0]), set()).add(key)

    def _drop(self, key: Tuple[str, int]) -> None:
        self._templates.pop(key, None)
        dom = self.base_domain(key[0])
        keys = self._by_domain.get(dom)
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_domain[dom]

    def invalidate_domain(self, domain: str) -> None:
        """Drop every template indexed under ``domain`` — called with
        the invalidated znode's own domain AND its parent, so instance-
        child churn under a service node drops the parent's answers."""
        keys = self._by_domain.pop(domain, None)
        if not keys:
            return
        for key in keys:
            self._templates.pop(key, None)
        self.invalidations += len(keys)

    def flush(self) -> None:
        if self._templates:
            self.flushes += 1
        self._templates.clear()
        self._by_domain.clear()

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "invalidations": self.invalidations,
            "flushes": self.flushes,
            "entries": len(self._templates),
        }


# ---- the server -------------------------------------------------------------


class _Bucket:
    """The PR-17 token bucket (rate req/s, burst = one second's refill),
    applied per front — the DNS analog of the router's per-connection
    bucket (UDP has no connections to scope it to)."""

    def __init__(self, rate: float):
        self.rate = float(rate)
        self.tokens = float(rate)
        self.stamp = time.monotonic()

    def admit(self) -> bool:
        now = time.monotonic()
        self.tokens = min(self.rate,
                          self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class DnsFront:
    """One worker's DNS presence: an SO_REUSEPORT UDP endpoint + a TCP
    listener on the same port, an :class:`EncodeCache`, and the
    overload armor.

    ``resolver(lname, qtype_name)`` is the only coupling to the serve
    tier: an async callable returning a `binderview.Resolution` (or
    raising :class:`DnsRefused` to shed) — `ShardWorker` passes its
    cache-backed resolve path; tests pass a stub.  ``source`` is the
    read source used to tell NXDOMAIN from NODATA (``read_node`` rides
    the negative cache) and whose ``authoritative`` flag gates
    template caching; ``attach_cache`` wires the watch events.
    """

    def __init__(self, resolver: Callable, *, host: str = "127.0.0.1",
                 port: int = 0, source=None,
                 udp_payload_max: int = DEFAULT_UDP_PAYLOAD_MAX,
                 negative_ttl: float = DEFAULT_NEGATIVE_TTL,
                 stale_ttl: float = DEFAULT_STALE_TTL,
                 max_entries: int = 4096,
                 max_pending: Optional[int] = None,
                 rate_limit: Optional[float] = None):
        self._resolver = resolver
        self.host = host
        self.port = port
        self._source = source
        self.udp_payload_max = int(udp_payload_max)
        self.negative_ttl = negative_ttl
        self.stale_ttl = float(stale_ttl)
        self.cache = EncodeCache(max_entries)
        # monotonic stamp of the source's authority loss; None while
        # authoritative.  Bounds the RFC 8767 serve-stale window.
        self._stale_since: Optional[float] = None
        self._max_pending = max_pending
        self._bucket = _Bucket(rate_limit) if rate_limit else None
        self._pending: set = set()
        self._udp_transport = None
        self._tcp_server = None
        self._subscribed = None
        self._unsubscribes: List[Tuple[str, Callable]] = []
        # qtype/rcode counters + a DEFAULT_BUCKETS latency ladder, the
        # shape metrics.instrument_shards aggregates across workers.
        self.queries: Dict[str, int] = {}
        self.udp_counts = [0] * (len(DEFAULT_BUCKETS) + 1)
        self.udp_sum = 0.0
        self.sheds: Dict[str, int] = {}

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> Tuple[str, int]:
        loop = asyncio.get_running_loop()
        reuse = hasattr(socket, "SO_REUSEPORT")
        self._udp_transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self),
            local_addr=(self.host, self.port),
            reuse_port=reuse or None,
        )
        self.port = self._udp_transport.get_extra_info("sockname")[1]
        self._tcp_server = await asyncio.start_server(
            self._serve_tcp, self.host, self.port,
            reuse_port=reuse or None)
        if self._source is not None:
            self.attach_cache(self._source)
        return self.host, self.port

    def attach_cache(self, zkcache) -> None:
        """Subscribe the encode cache to the watch events that keep it
        coherent.  Invalidation drops the changed znode's domain AND
        its parent: an instance child landing under a service node
        changes the parent's answers too.  Authority loss does NOT
        flush — the front serves stale for ``stale_ttl`` seconds
        (RFC 8767; new templates are already blocked by
        :meth:`_cacheable`), and the *restored* event flushes instead,
        because the invalidations missed during the outage make every
        surviving template unprovable."""
        from . import records

        def on_invalidated(path, _event=None):
            try:
                domain = records.path_to_domain(path)
            except ValueError:
                return
            self.cache.invalidate_domain(domain)
            if "." in domain:
                self.cache.invalidate_domain(domain.split(".", 1)[1])

        def on_degraded(_reason=None):
            if self._stale_since is None:
                self._stale_since = time.monotonic()

        def on_restored(*_args):
            self.cache.flush()
            self._stale_since = None

        self._subscribed = zkcache
        zkcache.on("invalidated", on_invalidated)
        zkcache.on("degraded", on_degraded)
        zkcache.on("restored", on_restored)
        self._unsubscribes.append(("invalidated", on_invalidated))
        self._unsubscribes.append(("degraded", on_degraded))
        self._unsubscribes.append(("restored", on_restored))

    async def close(self) -> None:
        if self._subscribed is not None:
            for event, listener in self._unsubscribes:
                self._subscribed.off(event, listener)
            self._subscribed = None
        self._unsubscribes.clear()
        if self._udp_transport is not None:
            self._udp_transport.close()
            self._udp_transport = None
        if self._tcp_server is not None:
            self._tcp_server.close()
            await self._tcp_server.wait_closed()
            self._tcp_server = None
        for task in list(self._pending):
            task.cancel()
        self._pending.clear()

    # -- accounting --------------------------------------------------------

    def _count(self, qtype: int, rcode: int, started: float) -> None:
        qname = QTYPE_NAMES.get(qtype, "OTHER")
        rname = RCODE_NAMES.get(rcode, str(rcode))
        key = f"{qname} {rname}"
        self.queries[key] = self.queries.get(key, 0) + 1
        elapsed = time.perf_counter() - started
        self.udp_sum += elapsed
        for i, bound in enumerate(DEFAULT_BUCKETS):
            if elapsed <= bound:
                self.udp_counts[i] += 1
                return
        self.udp_counts[len(DEFAULT_BUCKETS)] += 1

    def _shed(self, reason: str) -> None:
        self.sheds[reason] = self.sheds.get(reason, 0) + 1

    def stats(self) -> dict:
        return {
            "port": self.port,
            "queries": dict(self.queries),
            "udp": {"counts": list(self.udp_counts),
                    "sum": round(self.udp_sum, 6)},
            "encode_cache": self.cache.stats(),
            "sheds": dict(self.sheds),
        }

    # -- the serve path ----------------------------------------------------

    def _handle_packet(self, data: bytes, reply: Callable[[bytes], None],
                       udp: bool) -> None:
        started = time.perf_counter()
        try:
            query = parse_query(data)
        except DnsFormatError as exc:
            malformed.note("dns")
            reply(build_formerr_response(exc.qid or 0))
            return
        except DnsError:
            malformed.note("dns")
            return
        limit = MAX_TCP_MSG
        if udp:
            limit = min(query.edns_size or MIN_UDP_PAYLOAD,
                        self.udp_payload_max)
        if query.qclass != CLASS_IN:
            reply(build_error_response(query, RCODE_REFUSED))
            self._count(query.qtype, RCODE_REFUSED, started)
            return
        if query.qtype not in SERVED_QTYPES:
            reply(build_error_response(query, RCODE_NOTIMP))
            self._count(query.qtype, RCODE_NOTIMP, started)
            return
        key = (query.lname, query.qtype)
        if self._stale_since is not None and \
                time.monotonic() - self._stale_since > self.stale_ttl:
            # The serve-stale window expired with authority still lost:
            # past this bound a stale answer is worse than SERVFAIL.
            self.cache.flush()
            self._stale_since = None
        template = self.cache.get(key)
        if template is not None:
            # The line-rate path: memcpy + id/0x20 patch + sendto.
            # Warm hits bypass the admission bounds on purpose.
            reply(render_from_template(template, query, limit))
            self._count(query.qtype, template[3] & 0x0F, started)
            return
        if self._bucket is not None and not self._bucket.admit():
            self._shed("rate_limited")
            reply(build_error_response(query, RCODE_REFUSED))
            self._count(query.qtype, RCODE_REFUSED, started)
            return
        if self._max_pending is not None and \
                len(self._pending) >= self._max_pending:
            self._shed("queue_full")
            reply(build_error_response(query, RCODE_REFUSED))
            self._count(query.qtype, RCODE_REFUSED, started)
            return
        task = asyncio.ensure_future(
            self._answer_miss(query, reply, limit, started))
        self._pending.add(task)
        task.add_done_callback(self._pending.discard)

    async def _answer_miss(self, query: DnsQuery, reply, limit: int,
                           started: float) -> None:
        try:
            resolution = await self._resolver(query.lname,
                                              QTYPE_NAMES[query.qtype])
            if resolution.empty:
                rcode = RCODE_NXDOMAIN if await self._is_absent(
                    query.lname) else RCODE_NOERROR
                template = build_negative_template(
                    query.lname, query.qtype, rcode, self.negative_ttl)
            else:
                rcode = RCODE_NOERROR
                template = build_answer_template(
                    query.lname, query.qtype, resolution)
            if self._cacheable():
                self.cache.put((query.lname, query.qtype), template)
            reply(render_from_template(template, query, limit))
            self._count(query.qtype, rcode, started)
        except asyncio.CancelledError:
            raise
        except DnsRefused as exc:
            self._shed(exc.reason)
            reply(build_error_response(query, RCODE_REFUSED))
            self._count(query.qtype, RCODE_REFUSED, started)
        except Exception:
            reply(build_error_response(query, RCODE_SERVFAIL))
            self._count(query.qtype, RCODE_SERVFAIL, started)

    def _cacheable(self) -> bool:
        # Only an authoritative (watch-armed) source can promise the
        # invalidation events that keep a template coherent.
        return self._source is not None and \
            getattr(self._source, "authoritative", False)

    async def _is_absent(self, lname: str) -> bool:
        """NXDOMAIN vs NODATA: does the base znode exist?  Rides the
        read source's negative cache (one live read, then watch-armed
        absence) when the source is a ZKCache."""
        if self._source is None:
            return True
        from . import records
        base = EncodeCache.base_domain(lname)
        if not base:
            return True
        try:
            node = await self._source.read_node(records.domain_to_path(base))
        except Exception:
            return True
        return node is None

    # -- transports --------------------------------------------------------

    async def _serve_tcp(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    hdr = await reader.readexactly(2)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                (length,) = _U16.unpack(hdr)
                if length < _DNS_HDR.size:
                    malformed.note("dns")
                    return
                body = await reader.readexactly(length)

                def reply(resp: bytes, _w=writer) -> None:
                    _w.write(_U16.pack(len(resp)) + resp)

                self._handle_packet(body, reply, udp=False)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, front: DnsFront):
        self._front = front
        self._transport = None

    def connection_made(self, transport):
        self._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        transport = self._transport

        def reply(resp: bytes) -> None:
            if transport is not None:
                transport.sendto(resp, addr)

        self._front._handle_packet(data, reply, udp=True)

    def error_received(self, exc) -> None:
        pass


def allocate_port(host: str) -> int:
    """Resolve a configured port of 0 to a concrete free port, once,
    before worker spawn: every worker must bind the SAME port for
    SO_REUSEPORT fan-out, so the router picks it and passes the
    concrete value in each worker's spec."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        if hasattr(socket, "SO_REUSEPORT"):
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.bind((host, 0))
        return sock.getsockname()[1]
    finally:
        sock.close()
