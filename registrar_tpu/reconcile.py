"""Level-triggered registration reconciler (ISSUE 3 tentpole).

Everything else in the daemon is edge-triggered: the heartbeat probes
existence, the health checker reacts to threshold crossings, repair runs
when a specific event fires.  Edge triggers miss state that drifted
*between* edges — an operator's ``zkcli set`` over the host record, a
znode deleted while the probe was backing off, a service record a tool
clobbered, a deregistration that failed mid-flight.  This module closes
the loop the level-triggered way: periodically read back every znode the
registration *should* own (one pipelined ``get_many`` sweep), diff the
observed bytes/stat against the desired records, surface each divergence
as a structured ``drift`` event with a reason from :data:`REASONS`, and —
when ``reconcile.repair`` is on — converge through the existing
idempotent registration pipeline.

Desired state is a pure function of the configuration (plus the health
checker's verdict): ``ee.down`` flips the desired state to *absent*, so a
deregistration that failed mid-flight (agent.py's ``on_fail``) is
finished by the sweep instead of leaking live znodes for a host health
declared dead.

One deliberate non-goal: an ephemeral owned by a **foreign live session**
(reason ``owner``) is detected and counted but never repaired.  The
pipeline's cleanup stage would delete the foreign node — stealing a
hostname two live registrars both claim, and the pair would then steal it
back and forth forever.  That tug-of-war converges to nothing and
destroys the evidence; leaving the node (while alarming on the drift
metric) keeps exactly one registrar serving and hands operators a stable
state to debug.  See docs/DESIGN.md "Why repair never steals".

The read-only half (:func:`audit`) is also the engine behind
``zkcli verify -f config.json`` (exit 0/1/2 = in-sync/drift/unreachable)
for cron- and runbook-driven auditing from outside the daemon.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional

from registrar_tpu import registration as register_mod
from registrar_tpu import trace
from registrar_tpu.registration import (
    _validate_registration,
    registration_payloads,
)
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import Err, ZKError

log = logging.getLogger("registrar_tpu.reconcile")

# -- drift reason taxonomy (docs/OPERATIONS.md carries the operator matrix) --

#: a desired znode does not exist
R_MISSING = "missing"
#: a host record exists, we own it, but its payload diverged
R_PAYLOAD = "payload"
#: a host record's ephemeral is held by a foreign session (never repaired)
R_OWNER = "owner"
#: a host record exists but is persistent — it lost its session binding,
#: so a crash would leave it in DNS forever
R_NOT_EPHEMERAL = "notEphemeral"
#: the persistent service record diverged (payload, or wrongly ephemeral)
R_STALE_SERVICE = "staleService"
#: a znode is still present while the desired state is absent
#: (health-deregistered host; finishes a failed mid-flight unregister)
R_LINGERING = "lingering"

#: every reason the sweep can emit, in stable order (metrics pre-seeding)
REASONS = (
    R_MISSING, R_PAYLOAD, R_OWNER, R_NOT_EPHEMERAL, R_STALE_SERVICE,
    R_LINGERING,
)


@dataclass(frozen=True)
class Desired:
    """One znode the registration owns, as it should read back."""

    path: str
    payload: bytes
    ephemeral: bool


@dataclass(frozen=True)
class Drift:
    """One observed divergence from the desired state."""

    path: str
    reason: str
    detail: str = ""
    #: False for divergences repair must never act on (foreign owner)
    repairable: bool = True
    #: a service record observed wrongly EPHEMERAL — needs an unlink
    #: before any put/pipeline can restore it (a put cannot change
    #: ephemeral-ness, and nothing can create children under it)
    ephemeral_service: bool = False


def desired_records(
    registration: Mapping[str, Any],
    admin_ip: Optional[str] = None,
    hostname: Optional[str] = None,
) -> List[Desired]:
    """The registration's desired znodes, byte-exact.

    Thin shaping over :func:`registration.registration_payloads` — the
    ONE shared record-construction helper the write pipeline also uses —
    so the bytes a sweep expects are definitionally the bytes
    ``register`` writes (tests/test_reconcile.py additionally pins the
    round trip against the live pipeline).
    """
    _validate_registration(registration)
    nodes, record_payload, service_path, service_payload = (
        registration_payloads(registration, admin_ip, hostname)
    )
    desired = [Desired(n, record_payload, True) for n in nodes]
    if service_path is not None:
        for i, d in enumerate(desired):
            if d.path == service_path:
                # Alias == domain collision.  The pipeline can never
                # actually register this shape (its stage-3 mkdirp
                # creates the domain node persistent as the host
                # record's parent, and stage 4's ephemeral create then
                # dies with NODE_EXISTS), so there is no converged state
                # to describe — but an *audit* of such a config must not
                # report the same path twice with conflicting
                # expectations.  One entry, the service record's,
                # matching what stage 5 would have left.
                desired[i] = Desired(service_path, service_payload, False)
                break
        else:
            desired.append(Desired(service_path, service_payload, False))
    return desired


async def sweep(
    zk: ZKClient,
    desired: List[Desired],
    session_id: Optional[int] = None,
) -> List[Drift]:
    """Read back every desired znode (one pipelined sweep) and diff.

    Pure read: nothing is mutated.  ``session_id`` enables the ownership
    check — pass the registrar's own session to flag foreign-owned
    ephemerals; pass None (an external auditor, ``zkcli verify``) to
    accept any live owner, since an auditor's session never owns the
    nodes.  Transport errors propagate (the caller decides whether a
    failed sweep is retried or reported as unreachable).
    """
    results = await zk.get_many([d.path for d in desired])
    drifts: List[Drift] = []
    for d, res in zip(desired, results):
        if res is None:
            drifts.append(Drift(d.path, R_MISSING))
            continue
        data, stat = res
        if not d.ephemeral:
            if stat.ephemeral_owner != 0:
                # A wrongly-ephemeral service record will vanish with its
                # owning session.  Repairable only when WE own it (unlink
                # + persistent put); a foreign session's ephemeral is
                # never touched — not even by a put, which would both
                # write into someone else's node and leave the
                # ephemeral-ness unconverged (see docs/DESIGN.md).
                foreign = (
                    session_id is not None
                    and stat.ephemeral_owner != session_id
                )
                drifts.append(
                    Drift(
                        d.path, R_STALE_SERVICE,
                        f"service record is ephemeral "
                        f"(owner 0x{stat.ephemeral_owner:x})",
                        repairable=not foreign,
                        ephemeral_service=True,
                    )
                )
            elif data != d.payload:
                drifts.append(
                    Drift(d.path, R_STALE_SERVICE, "payload diverged")
                )
            continue
        if stat.ephemeral_owner == 0:
            # No session owns it: safe (and necessary) to recreate as a
            # proper ephemeral — nothing will ever clean it up otherwise.
            drifts.append(Drift(d.path, R_NOT_EPHEMERAL))
            continue
        if session_id is not None and stat.ephemeral_owner != session_id:
            drifts.append(
                Drift(
                    d.path, R_OWNER,
                    f"owner 0x{stat.ephemeral_owner:x} != "
                    f"ours 0x{session_id:x}",
                    repairable=False,
                )
            )
            continue  # the foreign session's payload is not ours to judge
        if data != d.payload:
            drifts.append(Drift(d.path, R_PAYLOAD))
    return drifts


async def audit(
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str] = None,
    hostname: Optional[str] = None,
) -> List[Drift]:
    """Read-only diff of live ZooKeeper state against a config's desired
    records — the engine behind ``zkcli verify``.  No ownership claim is
    made (session_id None): any live ephemeral owner passes."""
    return await sweep(
        zk, desired_records(registration, admin_ip, hostname)
    )


class Reconciler:
    """The in-daemon periodic sweep-and-repair loop.

    Wired by :func:`registrar_tpu.agent.register_plus`; ``ee`` is the
    agent's event surface (read for ``down``/``znodes``/``stopped``,
    written via ``drift`` / ``driftRepaired`` / ``reconcile`` events and
    — for a completed down-state deregistration — ``unregister``).

    ``repair_fn(expect_epoch)`` is the agent's single-flight guarded
    registration pipeline (returns True when the registration was
    refreshed); it receives the ``ee.epoch`` observed *before* the
    sweep's read-back, so a repair decided on stale observations is
    skipped if any other recovery path refreshed the registration in
    between.  The down-state repair path takes ``lock`` itself, so every
    znode-mutating flow in the daemon serializes on the one lock.
    """

    def __init__(
        self,
        zk: ZKClient,
        ee,
        registration: Mapping[str, Any],
        admin_ip: Optional[str] = None,
        hostname: Optional[str] = None,
        interval_s: float = 60.0,
        repair: bool = False,
        repair_fn=None,
        lock: Optional[asyncio.Lock] = None,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        if repair and repair_fn is None:
            raise ValueError("repair=True requires repair_fn")
        self.zk = zk
        self.ee = ee
        self.registration = registration
        self.admin_ip = admin_ip
        self.hostname = hostname
        self.interval_s = interval_s
        self.repair = repair
        self.repair_fn = repair_fn
        self.lock = lock if lock is not None else asyncio.Lock()
        #: observability (metrics read these through events; tests directly)
        self.sweeps = 0
        self.drift_seen = 0
        self.repaired = 0
        self.owner_conflicts = 0
        self.last_duration_s = 0.0
        self._sweep_epoch = 0

    async def run(self) -> None:
        """Sweep every ``interval_s`` until the agent stops.

        A failed sweep (connection blip mid-storm, reconnect in flight)
        is logged and retried at the next tick — the loop itself must
        never die, that is the whole point of level triggering.
        """
        while not self.ee.stopped:
            await asyncio.sleep(self.interval_s)
            if self.ee.stopped:
                return
            try:
                await self.sweep_once()
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001 - next tick retries
                log.debug("reconcile sweep failed: %r", err)

    async def sweep_once(self) -> List[Drift]:
        """One sweep: diff, emit drift, repair (when configured)."""
        with trace.tracer_for(self.zk).span("reconcile.sweep") as sp:
            drifts = await self._sweep_traced()
            sp.set_attr("drift", len(drifts))
            return drifts

    async def _sweep_traced(self) -> List[Drift]:
        start = time.monotonic()
        # Epoch BEFORE the read-back: the sweep's observations are only
        # actionable if no other recovery path refreshes the
        # registration between this point and the repair holding the
        # lock (see agent._reregister_guarded).
        self._sweep_epoch = getattr(self.ee, "epoch", 0)
        if self.lock.locked():
            # Another actor (health transition, heartbeat repair,
            # rebirth) is mid-mutation: reading now would observe its
            # pipeline's delete+settle window and report the healthy
            # in-flight refresh as "missing" drift.  Level-triggered
            # means the next tick re-reads; skip this one (no sweep
            # counted, no events — the tick observed nothing).
            return []
        if self.ee.down:
            drifts = await self._sweep_down()
        else:
            drifts = await sweep(
                self.zk,
                desired_records(
                    self.registration, self.admin_ip, self.hostname
                ),
                session_id=self.zk.session_id,
            )
        if (
            getattr(self.ee, "epoch", 0) != self._sweep_epoch
            or self.lock.locked()
        ):
            # The registration was (or is being) refreshed while we were
            # reading: the observations straddle a mutation and any
            # "drift" in them is an artifact.  Discard; next tick
            # re-reads the settled state.
            return []
        self.sweeps += 1
        self.drift_seen += len(drifts)
        self.owner_conflicts += sum(
            1 for d in drifts if d.reason == R_OWNER
        )
        for d in drifts:
            log.warning(
                "drift: %s at %s%s", d.reason, d.path,
                f" ({d.detail})" if d.detail else "",
            )
            self.ee.emit("drift", d)
        repaired: List[Drift] = []
        if self.repair and drifts and not self.ee.stopped:
            repaired = await self._repair(drifts)
            self.repaired += len(repaired)
            for d in repaired:
                self.ee.emit("driftRepaired", d)
        self.last_duration_s = time.monotonic() - start
        self.ee.emit(
            "reconcile",
            {
                "duration": self.last_duration_s,
                "drift": len(drifts),
                "repaired": len(repaired),
            },
        )
        return drifts

    async def _sweep_down(self) -> List[Drift]:
        """Desired state while health-deregistered: our znodes ABSENT.

        Catches a health-driven ``unregister`` that failed mid-flight
        (the agent leaves ``ee.down`` set with the error surfaced) —
        every still-present node we own is ``lingering`` drift and the
        repair pass finishes the deregistration.  A shared service node
        kept alive by siblings' ephemerals is not drift (deleting it is
        refused with NOT_EMPTY anyway), and a foreign-owned ephemeral is
        not ours to delete even here.
        """
        paths = list(self.ee.znodes)
        if not paths:
            return []
        results = await self.zk.get_many(paths)
        drifts = []
        for p, res in zip(paths, results):
            if res is None:
                continue
            _, stat = res
            if stat.ephemeral_owner == 0 and stat.num_children > 0:
                continue  # shared service node: siblings still live under it
            if (
                stat.ephemeral_owner
                and stat.ephemeral_owner != self.zk.session_id
            ):
                continue  # foreign-owned: never steal, even to delete
            drifts.append(Drift(p, R_LINGERING))
        return drifts

    async def _repair(self, drifts: List[Drift]) -> List[Drift]:
        """Converge: pipeline re-registration, targeted service put, or
        (down) completing the deregistration.  Returns the drifts
        actually repaired; failures are logged and retried next sweep."""
        if self.ee.down:
            lingering = [d for d in drifts if d.reason == R_LINGERING]
            if not lingering:
                return []
            try:
                async with self.lock:
                    if not self.ee.down or self.ee.stopped:
                        return []  # recovered while waiting: nothing to finish
                    deleted = await register_mod.unregister(
                        self.zk, [d.path for d in lingering]
                    )
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001 - next sweep retries
                log.warning("reconcile: deregistration repair failed: %r", err)
                return []
            log.info(
                "reconcile: completed pending deregistration (%s)", deleted
            )
            self.ee.emit("unregister", None, deleted)
            return lingering

        repairable = [d for d in drifts if d.repairable]
        conflicts = [d for d in drifts if d.reason == R_OWNER]
        if conflicts:
            # The pipeline's cleanup stage unlinks EVERY owned path —
            # running it now would steal the foreign-owned node.  Only
            # the targeted service-record put (which touches no
            # ephemeral) stays safe while a conflict stands.
            log.error(
                "reconcile: %d ownership conflict(s) (%s); refusing "
                "pipeline repair — two live claimants for one hostname "
                "is an operator problem",
                len(conflicts), [d.path for d in conflicts],
            )
            repairable = [
                d for d in repairable if d.reason == R_STALE_SERVICE
            ]
        if not repairable:
            return []

        if any(d.ephemeral_service for d in repairable):
            # Pre-clean: a service record that became OUR ephemeral
            # blocks every other repair — a put cannot change its
            # ephemeral-ness, and the pipeline cannot create host
            # records under it (NO_CHILDREN_FOR_EPHEMERALS) — so unlink
            # it first (it is childless by ZooKeeper's own invariant;
            # an "ephemeral with children", mintable only by test
            # controls, is refused and logged).  Live state is re-read
            # under the lock: a foreign owner (raced since the sweep)
            # is never touched.
            if not await self._unlink_ephemeral_services(
                [d for d in repairable if d.ephemeral_service]
            ):
                return []

        if all(d.reason == R_STALE_SERVICE for d in repairable):
            # Only the persistent service record drifted: a targeted put
            # converges it without the pipeline's delete+recreate of the
            # live host ephemerals (a real, Binder-visible blip).
            # Desired payloads are computed ONCE for the pass.
            payloads = {
                want.path: want.payload
                for want in desired_records(
                    self.registration, self.admin_ip, self.hostname
                )
            }
            repaired: List[Drift] = []
            try:
                async with self.lock:
                    if self.ee.down or self.ee.stopped:
                        return []
                    for d in repairable:
                        payload = payloads.get(d.path)
                        if payload is None:
                            continue
                        st = await self.zk.exists(d.path)
                        if st is not None and st.ephemeral_owner:
                            continue  # still ephemeral: pre-clean refused
                        await self.zk.put(d.path, payload)
                        repaired.append(d)
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001 - next sweep retries
                log.warning("reconcile: service-record repair failed: %r", err)
                return repaired
            return repaired

        try:
            refreshed = await self.repair_fn(self._sweep_epoch)
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001 - next sweep retries
            log.warning("reconcile: pipeline repair failed: %r", err)
            self.ee.emit("error", err)
            return []
        return repairable if refreshed else []

    async def _unlink_ephemeral_services(self, drifts: List[Drift]) -> bool:
        """Unlink OUR wrongly-ephemeral service records (see _repair's
        pre-clean comment).  Returns False when the pass failed and the
        repair should be abandoned until the next sweep."""
        try:
            async with self.lock:
                if self.ee.down or self.ee.stopped:
                    return False
                for d in drifts:
                    st = await self.zk.exists(d.path)
                    if st is None or not st.ephemeral_owner:
                        continue  # already settled
                    if st.ephemeral_owner != self.zk.session_id:
                        log.error(
                            "reconcile: service record %s is an ephemeral "
                            "owned by foreign session 0x%x; refusing to "
                            "repair", d.path, st.ephemeral_owner,
                        )
                        continue
                    try:
                        await self.zk.unlink(d.path)
                    except ZKError as err:
                        if err.code != Err.NOT_EMPTY:
                            raise
                        log.error(
                            "reconcile: %s is an ephemeral WITH children "
                            "(cannot exist in real ZooKeeper); refusing "
                            "to repair", d.path,
                        )
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001 - next sweep retries
            log.warning(
                "reconcile: ephemeral service pre-clean failed: %r", err
            )
            return False
        return True


def summarize(drifts: List[Drift]) -> Dict[str, int]:
    """Reason -> count rollup (zkcli verify's summary line, log fields)."""
    out: Dict[str, int] = {}
    for d in drifts:
        out[d.reason] = out.get(d.reason, 0) + 1
    return out
