"""The registrar daemon mainline (CLI).

Rebuild of reference main.js:102-200.  Usage::

    python -m registrar_tpu -f /opt/registrar/etc/config.json [-v ...]

Behavior parity:

  * ``-f`` config file (required), ``-v`` repeatable verbosity escalation,
    ``-h`` usage (reference main.js:29-46,107-121);
  * log level: LOG_LEVEL env < config ``logLevel`` < ``-v`` flags
    (reference main.js:24,66-76); bunyan-shaped JSON lines on stdout;
  * ZooKeeper connect retries forever with exponential 1-90 s backoff
    (reference lib/zk.js:97-101);
  * ``session_expired`` => log fatal + ``exit(1)`` so the supervisor
    (systemd/SMF) restarts the process with a fresh session — crash-restart
    is the load-bearing recovery design (reference main.js:141-144,
    SURVEY.md §3.4).  The opt-in ``surviveSessionExpiry`` config key
    (ISSUE 3) absorbs expiry in-process instead: the client builds a
    fresh session, the agent re-registers, and exit(1) only remains as
    the fallback when the rebirth circuit breaker trips;
  * every lifecycle event is logged, with heartbeat failures edge-triggered
    through an ``is_down`` latch so a long outage logs once
    (reference main.js:149,187-198).

Addition over the reference: SIGTERM/SIGINT run a graceful stop that
closes the ZK session, which deletes the ephemerals *immediately* instead
of waiting out the session timeout — an instance drained with
``systemctl stop registrar`` leaves DNS as fast as Binder's cache allows.
(The reference is stopped with SMF ``:kill`` and waits for expiry,
README.md:85-87.)

Zero-downtime restarts (ISSUE 5, opt-in ``restart`` config block):

  * ``mode: "handoff"`` — SIGTERM persists the live session's handoff
    state (:mod:`registrar_tpu.statefile`) and detaches the TCP
    connection WITHOUT closing the session: the ephemerals stay up for
    the negotiated timeout, the successor process reattaches the same
    session from the state file and verifies (not recreates) the
    registration — a watching resolver sees **zero** NO_NODE across the
    restart.  Every degraded shape (stale/foreign/tampered state file,
    config change, a reattach the server refuses) falls back to today's
    fresh-session registration;
  * ``mode: "drain"`` — SIGTERM unregisters cleanly, waits
    ``drainGraceSeconds``, then exits 0;
  * a second SIGTERM/SIGINT during a wedged graceful stop forces an
    immediate exit (:data:`EX_FORCED`) — operators are never pushed to
    SIGKILL;
  * SIGHUP re-reads the config file and hot-applies the registration
    delta through the agent's single-flight pipeline lock (unchanged
    znodes are never touched); keys that cannot hot-apply are named in
    a warning and need a restart.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys
import time

from registrar_tpu import __version__
from registrar_tpu import jlog
from registrar_tpu import statefile
from registrar_tpu import trace as trace_mod
from registrar_tpu.events import spawn_owned
from registrar_tpu.agent import register_plus
from registrar_tpu.config import (
    Config,
    ConfigError,
    ConfigUnreadableError,
    RestartConfig,
    load_config,
)
from registrar_tpu.registration import unlink_tolerant
from registrar_tpu.zk.client import (
    ZKClient,
    connect_with_backoff,
)


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="registrar",
        description="service-discovery sidecar: registers this host in "
        "ZooKeeper for Binder-served DNS",
    )
    parser.add_argument(
        "-f", "--file", metavar="FILE", required=True,
        help="configuration file to process",
    )
    parser.add_argument(
        "-v", "--verbose", action="count", default=0,
        help="verbose output; use multiple times for more verbosity",
    )
    parser.add_argument(
        "-n", "--check-config", action="store_true",
        help="validate the configuration file and exit (0 = valid); "
        "no ZooKeeper connection is made",
    )
    parser.add_argument(
        "--version", action="version", version=f"registrar {__version__}"
    )
    return parser.parse_args(argv)


#: exit status for configuration errors (BSD sysexits EX_CONFIG).
#: Distinct from the generic exit(1) used for runtime failures (session
#: expiry, failed initial registration) so the supervisor can crash-
#: restart on the latter but stop retrying a config that can never work
#: (systemd/registrar.service sets RestartPreventExitStatus=78).
EX_CONFIG = 78

#: exit status when a SECOND SIGTERM/SIGINT lands while a graceful stop
#: is still running (ISSUE 5 satellite): the operator's escape hatch out
#: of a wedged shutdown (an unreachable ensemble stalling the drain, a
#: long drainGraceSeconds) without reaching for SIGKILL.  Distinct from
#: 0 (clean stop) and 1 (runtime failure) so supervisors and humans can
#: tell a forced exit from both.  BSD sysexits EX_SOFTWARE.
EX_FORCED = 70


def configure(argv=None) -> Config:
    """Parse args + config, set up logging (reference main.js:52-84)."""
    args = parse_args(argv)
    log = jlog.setup("registrar")
    try:
        cfg = load_config(args.file)
    except ConfigUnreadableError as e:
        # Read failures (file not provisioned yet, permissions) are often
        # transient — exit 1 so the supervisor's restart can cure them,
        # unlike the EX_CONFIG path below which it must not retry.
        log.critical("unable to read configuration %s", args.file,
                     exc_info=(type(e), e, e.__traceback__))
        sys.exit(1)
    except ConfigError as e:
        log.critical("invalid configuration %s", args.file,
                     exc_info=(type(e), e, e.__traceback__))
        sys.exit(EX_CONFIG)
    if cfg.unknown_keys:
        # Ignored like the reference ignores them — but a typo like
        # "healthcheck" silently disabling health checking is worth a
        # warning.  Emitted BEFORE the config's own logLevel applies, so
        # a {"logLevel": "error"} config cannot suppress it.
        log.warning(
            "configuration has unrecognized top-level keys (ignored): %s",
            ", ".join(cfg.unknown_keys),
            extra={"zdata": {"keys": list(cfg.unknown_keys)}},
        )
    if cfg.log_level:
        level = jlog.LEVELS.get(cfg.log_level.lower())
        if level is None:
            log.critical("invalid logLevel %r", cfg.log_level)
            sys.exit(EX_CONFIG)
        logging.getLogger().setLevel(level)
    if args.verbose:
        jlog.escalate(args.verbose)
    if args.check_config:
        # nginx -t style pre-flight for config-agent/CI pipelines: the same
        # validation the daemon would apply, without touching ZooKeeper —
        # including the registration schema check register_plus runs at
        # startup (reference lib/register.js:174-201), which load_config
        # alone does not cover.
        from registrar_tpu.registration import _validate_registration

        try:
            _validate_registration(cfg.registration)
        except ValueError as e:
            log.critical("invalid registration in %s", args.file,
                         exc_info=(type(e), e, e.__traceback__))
            sys.exit(EX_CONFIG)
        log.info("configuration OK", extra={"zdata": {"file": args.file}})
        sys.exit(0)
    log.info("configuration loaded from %s", args.file,
             extra={"zdata": {"file": args.file}})
    return cfg


def _client_from_config(cfg: Config) -> ZKClient:
    """The daemon's ZKClient settings, in ONE place: the cold-start path
    and the handoff-resume path must run with identical client tuning —
    a zookeeper key honored by one and silently dropped by the other
    would make a restarted daemon behave differently from a cold one."""
    return ZKClient(
        cfg.zookeeper.servers,
        timeout_ms=cfg.zookeeper.timeout_ms,
        connect_timeout_ms=cfg.zookeeper.connect_timeout_ms,
        chroot=cfg.zookeeper.chroot,
        request_timeout_ms=cfg.zookeeper.request_timeout_ms,
        survive_session_expiry=cfg.survive_session_expiry,
        max_session_rebirths=cfg.max_session_rebirths,
        can_be_read_only=cfg.zookeeper.can_be_read_only,
        connect_race_stagger_ms=cfg.zookeeper.connect_race_stagger_ms,
        ping_interval_ms=cfg.zookeeper.ping_interval_ms,
        dead_after_ms=cfg.zookeeper.dead_after_ms,
    )


async def _drain_unregister(zk: ZKClient, znodes, log) -> list:
    """Best-effort deregistration for the drain shutdown.

    Unlike the pipeline's strict ``unregister``, this walk NEVER aborts
    early: the whole point of a drain is that every record this host
    still serves leaves DNS before the process exits, so an
    already-absent node (health-down raced us, an operator deleted one
    out-of-band) is success, a still-shared service node is left in
    place as usual, and any other per-node error is logged while the
    remaining nodes are still processed.  Returns the nodes deleted.
    """
    deleted = []
    for node in znodes:
        try:
            # check: disable=await-in-lock-free-mutator -- shutdown-only
            # walk: ee.stop() has already run, so no recovery actor is
            # alive to contend, and the agent's lock died with it
            outcome = await unlink_tolerant(zk, node)
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001 - keep draining the rest
            log.error("restart: drain could not delete %s", node,
                      extra={"zdata": {"err": repr(err)}})
            continue
        if outcome == "deleted":
            deleted.append(node)
    return deleted


async def _attempt_resume(cfg: Config, restart_cfg: RestartConfig,
                          fingerprint: str, log):
    """Try to adopt the predecessor's session from the state file.

    Returns ``(client_or_None, manifest_or_None, attempted)``:

      * ``(client, manifest, True)`` — the session reattached; the agent
        should verify-not-recreate against ``manifest``;
      * ``(client, None, True)`` — a resume was staged but the server
        refused it (expired in the gap): the client holds a FRESH
        session, register normally;
      * ``(None, None, True)`` — the state file was unusable (stale
        stamp, config-hash mismatch, foreign/corrupt/short passwd):
        connect + register exactly as a cold start;
      * ``(None, None, False)`` — no state file at all (a normal cold
        start, nothing to report).
    """
    try:
        state = statefile.load(restart_cfg.state_file)
    except statefile.StateFileMissing:
        return None, None, False
    except statefile.StateFileError as e:
        log.warning(
            "restart: unusable state file (%s); starting fresh", e,
            extra={"zdata": {"reason": e.reason,
                             "file": restart_cfg.state_file}},
        )
        return None, None, True
    reason = statefile.check_resumable(state, fingerprint)
    if reason is not None:
        log.warning(
            "restart: state file not resumable (%s); starting fresh",
            reason,
            extra={"zdata": {"reason": reason,
                             "session": f"0x{state.session_id:x}",
                             "file": restart_cfg.state_file}},
        )
        return None, None, True
    zk = _client_from_config(cfg)
    zk.seed_session(
        state.session_id, state.passwd,
        negotiated_timeout_ms=state.negotiated_timeout_ms,
        last_zxid=state.last_zxid,
    )
    log.info(
        "restart: resuming predecessor session",
        extra={"zdata": {"session": f"0x{state.session_id:x}",
                         "predecessorPid": state.pid,
                         "znodes": list(state.znodes)}},
    )
    await connect_with_backoff(zk)
    if zk.session_id == state.session_id:
        log.info(
            "restart: session resumed; verifying registration in place",
            extra={"zdata": {"session": f"0x{zk.session_id:x}"}},
        )
        return zk, list(state.znodes), True
    # seed refused: the client already fell back to a fresh session
    # (zk.client resume_refused path) — register from scratch.
    log.warning(
        "restart: session resume refused (expired in the gap); "
        "registering fresh",
        extra={"zdata": {"stale": f"0x{state.session_id:x}",
                         "session": f"0x{zk.session_id:x}"}},
    )
    return zk, None, True


async def run(cfg: Config, *, _exit=sys.exit) -> None:
    """Connect, register, and serve events until stopped or expired."""
    log = logging.getLogger("registrar")

    # -- operation tracing (ISSUE 8, opt-in `observability` block) ----------
    # Installed FIRST so the initial connect/registration is traced too.
    # Absent block: the module default stays trace.DISABLED and not a
    # single span, log field, or metric series is added (parity pinned
    # by tests/test_trace.py).
    tracer = None
    trace_filter = None
    obs = cfg.observability
    if obs is not None:
        tracer = trace_mod.Tracer(
            sample_rate=obs.sample_rate,
            slow_span_ms=obs.slow_span_ms,
            max_spans=obs.flight_recorder_spans,
        )
        trace_mod.set_tracer(tracer)
        trace_filter = trace_mod.TraceContextFilter()
        for handler in logging.getLogger().handlers:
            handler.addFilter(trace_filter)
        log.info(
            "observability: tracing enabled",
            extra={"zdata": {"sampleRate": obs.sample_rate,
                             "slowSpanMs": obs.slow_span_ms,
                             "flightRecorderSpans":
                                 obs.flight_recorder_spans}},
        )
    try:
        await _run_traced(cfg, log, tracer, _exit=_exit)
    finally:
        if obs is not None:
            trace_mod.set_tracer(None)
            for handler in logging.getLogger().handlers:
                handler.removeFilter(trace_filter)


async def _run_traced(cfg: Config, log, tracer, *, _exit=sys.exit) -> None:
    restart_cfg = cfg.restart
    fingerprint = (
        statefile.config_fingerprint(
            cfg.registration, cfg.admin_ip, cfg.zookeeper.chroot
        )
        if restart_cfg is not None
        else None
    )

    zk = None
    resume_manifest = None
    resume_attempted = False
    if restart_cfg is not None:
        zk, resume_manifest, resume_attempted = await _attempt_resume(
            cfg, restart_cfg, fingerprint, log
        )
    if zk is None:
        # Same construction + infinite-backoff envelope create_zk_client
        # wraps (reference lib/zk.js:62-127) — shared with the resume
        # path above via _client_from_config/connect_with_backoff.
        zk = await connect_with_backoff(_client_from_config(cfg))

    zk.on("close", lambda *a: log.warning("zookeeper: disconnected"))
    # The initial connect already happened; later connects are reconnects
    # (the reference ignores the first 'connect' for the same reason,
    # main.js:135-139).
    zk.on("connect", lambda *a: log.info("zookeeper: reconnected"))

    stopping = asyncio.Event()
    exit_code = 0

    def _die(msg: str) -> None:
        # Route fatal conditions through the orderly shutdown below rather
        # than raising SystemExit inside the emitting task: zk.close()
        # then completes (deleting any half-registered ephemerals
        # immediately) before the process exits nonzero.
        nonlocal exit_code
        log.critical(msg)
        exit_code = 1
        stopping.set()

    # With surviveSessionExpiry, expiry is absorbed in-process and
    # announced as session_reborn; session_expired then only fires
    # terminally (feature off, or the rebirth circuit breaker tripped) —
    # either way the reference's crash-restart path below still applies.
    zk.on("session_expired",
          lambda *_a: _die("ZooKeeper session_expired event; exiting"))
    zk.on("session_reborn", lambda sid: log.warning(
        "zookeeper: session expired; fresh session established in-process",
        extra={"zdata": {"session": f"0x{sid:x}"}}))
    zk.on("rebirth_breaker_tripped", lambda n: log.error(
        "zookeeper: session rebirth circuit breaker tripped",
        extra={"zdata": {"rebirths_in_window": n}}))

    ee = register_plus(
        zk,
        cfg.registration,
        admin_ip=cfg.admin_ip,
        health_check=cfg.health_check,
        heartbeat_interval=cfg.heartbeat_interval_s,
        heartbeat_retry=cfg.heartbeat_retry,
        repair_heartbeat_miss=cfg.repair_heartbeat_miss,
        reconcile=(
            {
                "interval_seconds": cfg.reconcile.interval_s,
                "repair": cfg.reconcile.repair,
            }
            if cfg.reconcile is not None
            else None
        ),
        resume_manifest=resume_manifest,
    )

    ee.on("fail", lambda err: log.error(
        "registrar: healthcheck failed", extra={"zdata": {"err": err}}))
    ee.on("ok", lambda: log.info("registrar: healthcheck ok (was down)"))

    def on_error(err) -> None:
        log.error("registrar: unexpected error", extra={"zdata": {"err": err}})
        if not ee.znodes:
            # Initial registration failed: nothing will retry it (the
            # reference just logs and idles broken, lib/index.js:46-50).
            # Exit so the supervisor restarts us — the same crash-restart
            # policy as session expiry.
            _die("registrar: initial registration failed; exiting")

    ee.on("error", on_error)
    ee.on("register", lambda nodes: log.info(
        "registrar: registered", extra={"zdata": {"znodes": nodes}}))
    ee.on("unregister", lambda err, nodes: log.warning(
        "registrar: unregistered",
        extra={"zdata": {"err": err, "znodes": nodes}}))
    ee.on("drift", lambda d: log.warning(
        "registrar: drift detected",
        extra={"zdata": {"path": d.path, "reason": d.reason,
                         "detail": d.detail}}))
    ee.on("driftRepaired", lambda d: log.info(
        "registrar: drift repaired",
        extra={"zdata": {"path": d.path, "reason": d.reason}}))

    # Edge-triggered heartbeat logging (reference main.js:149,187-198).
    is_down = False

    def on_heartbeat_failure(err) -> None:
        nonlocal is_down
        if not is_down:
            log.error("zookeeper: heartbeat failed",
                      extra={"zdata": {"err": err}})
        is_down = True

    def on_heartbeat(_nodes) -> None:
        nonlocal is_down
        if is_down:
            log.info("zookeeper heartbeat ok")
        is_down = False

    ee.on("heartbeatFailure", on_heartbeat_failure)
    ee.on("heartbeat", on_heartbeat)

    # -- handoff state keeper (ISSUE 5) -------------------------------------
    # The state file tracks the LIVE session: rewritten on every session
    # establish/reattach/rebirth and registration refresh, stamped once
    # more at SIGTERM-handoff time, and fenced (deleted) the moment the
    # session is known dead (terminal expiry) or deliberately closed.
    state_note = {"hash": fingerprint}
    state_tasks: set = set()
    state_write_lock = asyncio.Lock()

    def _snapshot_state():
        return statefile.SessionState(
            session_id=zk.session_id,
            passwd=zk.session_passwd,
            negotiated_timeout_ms=zk.negotiated_timeout_ms,
            last_zxid=zk.last_zxid,
            chroot=zk.chroot,
            config_hash=state_note["hash"],
            znodes=list(ee.znodes),
            pid=os.getpid(),
            stamp=time.time(),
        )

    def _log_statefile_error(err: OSError) -> None:
        # A broken state file costs the NEXT restart its handoff (it
        # degrades to a fresh registration); it must never cost THIS
        # process its registration.
        log.error(
            "restart: cannot write state file %s",
            restart_cfg.state_file, extra={"zdata": {"err": repr(err)}},
        )

    def write_statefile(*_a) -> None:
        """Synchronous save — ONLY for the SIGTERM-handoff stamp, where
        the process is about to exit and the write must land first."""
        if restart_cfg is None or zk.closed or zk.session_id == 0:
            return
        try:
            statefile.save(restart_cfg.state_file, _snapshot_state())
        except OSError as err:
            _log_statefile_error(err)

    def write_statefile_bg(*_a) -> None:
        """Event-listener save: the state is snapshotted NOW (on the
        loop, a consistent view) but the two fsyncs run in a worker
        thread — register/connect/rebirth fire exactly when the session
        machinery is busiest, and a slow disk must not stall the loop.
        The lock serializes writers so snapshots land in event order."""
        if restart_cfg is None or zk.closed or zk.session_id == 0:
            return
        state = _snapshot_state()

        async def _save() -> None:
            async with state_write_lock:
                try:
                    await asyncio.to_thread(
                        statefile.save, restart_cfg.state_file, state
                    )
                except OSError as err:
                    _log_statefile_error(err)

        spawn_owned(_save(), state_tasks)

    def clear_statefile(*_a) -> None:
        if restart_cfg is not None:
            statefile.clear(restart_cfg.state_file)

    if restart_cfg is not None:
        ee.on("register", write_statefile_bg)
        zk.on("connect", write_statefile_bg)
        zk.on("session_reborn", write_statefile_bg)
        # Fencing: a terminally expired session must never be offered to
        # a successor (the reattach would be refused, but a dead-session
        # state file also misleads operators and `zkcli state`).
        zk.on("session_expired", clear_statefile)

    # -- SIGHUP config hot-reload (ISSUE 5) ---------------------------------
    reload_lock = asyncio.Lock()

    async def do_reload() -> None:
        async with reload_lock:
            result = "failed"
            path = cfg.source_path
            if path is None:
                log.error("SIGHUP: no config file to reload from")
            else:
                log.info("SIGHUP: reloading configuration from %s", path)
                try:
                    new_cfg = load_config(path)
                    from registrar_tpu.registration import (
                        _validate_registration,
                    )

                    _validate_registration(new_cfg.registration)
                except (ConfigError, ValueError) as err:
                    log.error(
                        "SIGHUP: invalid configuration; keeping the "
                        "running config",
                        exc_info=(type(err), err, err.__traceback__),
                    )
                else:
                    if new_cfg.log_level and new_cfg.log_level != cfg.log_level:
                        level = jlog.LEVELS.get(new_cfg.log_level.lower())
                        if level is not None:
                            logging.getLogger().setLevel(level)
                            cfg.log_level = new_cfg.log_level
                            log.info("SIGHUP: logLevel -> %s",
                                     new_cfg.log_level)
                    cold = _cold_reload_changes(cfg, new_cfg)
                    if cold:
                        log.warning(
                            "SIGHUP: changes to %s cannot hot-apply; "
                            "restart to pick them up", ", ".join(cold),
                            extra={"zdata": {"keys": cold}},
                        )
                    try:
                        result = await ee.reload(
                            new_cfg.registration, new_cfg.admin_ip
                        )
                    except asyncio.CancelledError:
                        raise
                    except RuntimeError as err:
                        log.error("SIGHUP: %s", err)
                    except Exception as err:  # noqa: BLE001
                        # The agent's desired state already switched to
                        # the new records (reload mutates before it
                        # writes), so heartbeat/reconciler converge on
                        # them; adopt the new config here too.
                        log.error(
                            "SIGHUP: reload delta failed mid-apply (%r); "
                            "recovery layers will converge on the new "
                            "records", err,
                        )
                        cfg.registration = dict(new_cfg.registration)
                        cfg.admin_ip = new_cfg.admin_ip
                    else:
                        cfg.registration = dict(new_cfg.registration)
                        cfg.admin_ip = new_cfg.admin_ip
                        log.info(
                            "SIGHUP: configuration reload %s", result,
                            extra={"zdata": {"result": result}},
                        )
                    if restart_cfg is not None:
                        state_note["hash"] = statefile.config_fingerprint(
                            cfg.registration, cfg.admin_ip,
                            cfg.zookeeper.chroot,
                        )
                        write_statefile_bg()
            ee.emit("configReload", result)

    # -- /status snapshot state (ISSUE 8) -----------------------------------
    # The introspection endpoint's last-known view of the slow-moving
    # bits: the client's state string and the reconciler's last summary
    # are events, so a snapshot must remember them.
    status_note = {"zk_state": "connected" if zk.connected else "disconnected",
                   "last_reconcile": None, "started": time.time(),
                   "transitions": {}}

    # Last-transition stamps (ISSUE 9 satellite): the wall-clock moment
    # each slow-moving state last CHANGED — session, health, and
    # registration — so an operator (or the SLO harness's live-daemon
    # mode) can compute MTTR from /status alone: recovery stamp minus
    # fault stamp, no log archaeology.
    def _note_transition(kind: str, state: str) -> None:
        status_note["transitions"][kind] = {
            "state": state, "at": round(time.time(), 3),
        }

    def _on_zk_state(s) -> None:
        status_note["zk_state"] = s
        _note_transition("session", s)

    zk.on("state", _on_zk_state)
    ee.on("register",
          lambda *_a: _note_transition("registration", "registered"))
    ee.on("unregister",
          lambda *_a: _note_transition("registration", "unregistered"))
    ee.on("fail", lambda *_a: _note_transition("health", "down"))
    ee.on("ok", lambda *_a: _note_transition("health", "up"))
    ee.on(
        "reconcile",
        lambda summary: status_note.__setitem__(
            "last_reconcile",
            {"at": time.time(), **{k: summary.get(k)
                                   for k in ("duration", "drift", "repaired")}},
        ),
    )

    metrics_server = None
    if cfg.metrics is not None:
        from registrar_tpu.metrics import (
            MetricsRegistry,
            MetricsServer,
            instrument,
            instrument_tracing,
        )

        registry = MetricsRegistry()
        if tracer is not None:
            # BEFORE instrument(): the tracing histograms own the
            # registrar_reconcile_sweep_seconds family when enabled.
            instrument_tracing(tracer, registry)
        instrument(ee, zk, registry)
        async def _trace_tree(trace_id: str):
            # GET /debug/trace?id= (ISSUE 13): the daemon is one
            # process, so "assembly" is just its own recorder — but
            # the payload shape (and the orphan convention) is the
            # same one the sharded tier's cross-process fan-out
            # serves, so dashboards and zkcli trace --id read both.
            from registrar_tpu import traceview

            return traceview.assemble(
                trace_mod.get_tracer().dump(trace_id=trace_id).get(
                    "entries", []
                ),
                trace_id,
            )

        try:
            metrics_server = await MetricsServer(
                registry,
                host=cfg.metrics.host,
                port=cfg.metrics.port,
                status_provider=lambda: _status_snapshot(
                    cfg, zk, ee, status_note
                ),
                trace_provider=lambda n: trace_mod.get_tracer().dump(n),
                trace_tree_provider=_trace_tree,
            ).start()
        except OSError as err:
            # A busy/forbidden port must not take down registration —
            # metrics are an observability add-on, not the product.
            log.error("metrics: cannot listen on %s:%d",
                      cfg.metrics.host, cfg.metrics.port,
                      extra={"zdata": {"err": err}})
        else:
            log.info("metrics: listening",
                     extra={"zdata": {"host": cfg.metrics.host,
                                      "port": metrics_server.port}})

    if resume_attempted and resume_manifest is None:
        # The agent reports "reattached"/"repaired" itself; the shapes
        # where no session came back (unusable file, refused reattach)
        # are only known here.  Emitted after the metrics wiring above
        # so the counter sees it.
        ee.emit("resume", "fresh")

    loop = asyncio.get_running_loop()

    def on_stop_signal() -> None:
        if stopping.is_set():
            # Second-signal escape hatch (ISSUE 5 satellite): the
            # graceful stop below is wedged (unreachable ensemble, long
            # drain grace) and the operator signalled again — leave NOW,
            # with a distinct line and code, so nobody reaches for
            # SIGKILL.  os._exit skips cleanup by design: cleanup is
            # exactly what is stuck.
            log.critical(
                "second termination signal during graceful stop; "
                "forcing immediate exit (code %d)", EX_FORCED,
            )
            try:
                sys.stdout.flush()
            except Exception:  # noqa: BLE001
                pass
            # check: disable=unguarded-private-attr -- os._exit is the
            # documented immediate-exit API (skips atexit/finalizers by
            # design), which is exactly what a wedged shutdown needs
            os._exit(EX_FORCED)
        stopping.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, on_stop_signal)
        except NotImplementedError:  # non-unix test environments
            pass
    reload_tasks: set = set()
    try:
        loop.add_signal_handler(
            signal.SIGHUP, lambda: spawn_owned(do_reload(), reload_tasks)
        )
    except (NotImplementedError, AttributeError):  # non-unix
        pass

    # -- SIGUSR2: dump the flight recorder (ISSUE 8) ------------------------
    dump_tasks: set = set()

    def dump_flight_recorder() -> None:
        tr = trace_mod.get_tracer()
        if not tr.enabled:
            log.warning(
                "SIGUSR2: tracing is disabled (no `observability` config "
                "block); nothing to dump"
            )
            return
        # Snapshot on-loop (a bounded list copy + render, ms-scale);
        # write in a worker thread.  SIGUSR2 arrives mid-incident, when
        # a wedged filesystem at dumpPath is most likely — blocking the
        # loop on that write could stall heartbeats past the session
        # timeout and let the diagnostic itself take the host out of
        # DNS (the statefile writer learned this in PR 5).
        payload = tr.dump()
        spans, events = tr.spans_recorded, tr.events_recorded
        dump_path = (
            cfg.observability.dump_path
            if cfg.observability is not None
            else None
        )

        async def _write() -> None:
            try:
                path = await asyncio.to_thread(
                    trace_mod.write_dump, payload, dump_path
                )
            except OSError as err:
                log.error("SIGUSR2: cannot write flight-recorder dump",
                          extra={"zdata": {"err": repr(err)}})
            else:
                log.info(
                    "SIGUSR2: flight recorder dumped",
                    extra={"zdata": {"file": path,
                                     "spans": spans,
                                     "events": events}},
                )

        spawn_owned(_write(), dump_tasks)

    try:
        loop.add_signal_handler(signal.SIGUSR2, dump_flight_recorder)
    except (NotImplementedError, AttributeError):  # non-unix
        pass

    await stopping.wait()
    mode = restart_cfg.mode if restart_cfg is not None else None
    log.info(
        "registrar: shutting down",
        extra={"zdata": {"mode": mode or "close"}},
    )
    ee.stop()  # health checker first: no transition may race the exit
    if (
        exit_code == 0
        and mode == "handoff"
        and not zk.closed
        and zk.session_id != 0
    ):
        # Persist with a FRESH stamp — the successor's staleness window
        # opens here — then sever the TCP connection with the session
        # (and every ephemeral) left alive for it.  Any in-flight
        # background save must land FIRST: a worker thread finishing
        # after us would clobber this stamp with an older snapshot and
        # silently shrink (or void) the successor's resume window.
        if state_tasks:
            await asyncio.gather(*state_tasks, return_exceptions=True)
        async with state_write_lock:
            write_statefile()
        log.info(
            "restart: session handed off; ephemerals remain live for "
            "the successor",
            extra={"zdata": {"session": f"0x{zk.session_id:x}",
                             "stateFile": restart_cfg.state_file,
                             "znodes": list(ee.znodes)}},
        )
        ee.emit("handoff", restart_cfg.state_file)
        await zk.detach()
    elif exit_code == 0 and mode == "drain":
        deleted = await _drain_unregister(zk, ee.znodes, log)
        log.info("restart: drained",
                 extra={"zdata": {"znodes": deleted}})
        ee.emit("drain", deleted)
        if restart_cfg.drain_grace_s > 0:
            log.info(
                "restart: waiting drainGraceSeconds before exit",
                extra={"zdata": {"seconds": restart_cfg.drain_grace_s}},
            )
            await asyncio.sleep(restart_cfg.drain_grace_s)
        clear_statefile()
        await zk.close()
    else:
        await zk.close()  # deletes our ephemerals immediately (docstring)
        clear_statefile()  # a closed session is nothing to hand off
    if metrics_server is not None:
        # Stopped LAST so the handoff/drain counters increment while the
        # endpoint still answers (a drain's grace period is scrapeable).
        await metrics_server.stop()
    if dump_tasks:
        # An in-flight SIGUSR2 dump finishes writing (it already holds
        # its snapshot; losing it at exit is losing the evidence).
        await asyncio.gather(*dump_tasks, return_exceptions=True)
    if exit_code:
        _exit(exit_code)


async def _status_snapshot(cfg: Config, zk, ee, note: dict) -> dict:
    """One ``GET /status`` introspection snapshot (ISSUE 8).

    The runbook's first stop (docs/OPERATIONS.md "The first 5 minutes
    of an incident"): session identity and state, registration epoch,
    the owned znodes with their live mzxids, health/drift posture, and
    the config fingerprint — enough to answer "is THIS instance the
    problem" without reading a single log line.

    The mzxid read-back is best-effort with a short deadline: /status
    must keep answering while the ensemble is down (that is precisely
    when operators hit it), so a failed sweep reports ``readError``
    instead of hanging or erroring the endpoint.
    """
    znodes = list(ee.znodes)
    mzxids: dict = {p: None for p in znodes}
    read_error = None
    if znodes and zk.connected:
        try:
            results = await asyncio.wait_for(zk.get_many(znodes), timeout=2.0)
            for path, res in zip(znodes, results):
                mzxids[path] = res[1].mzxid if res is not None else None
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001 - status must still answer
            read_error = repr(err)
    elif znodes:
        read_error = "session not connected"
    tr = trace_mod.get_tracer()
    health = getattr(ee, "_health", None)
    return {
        "name": "registrar",
        "pid": os.getpid(),
        "version": __version__,
        # uptime_s + last_transition (ISSUE 9 satellite): the MTTR-
        # computable view — each entry is the wall stamp of the LAST
        # session/health/registration state change (empty until the
        # first change after startup).
        "uptime_s": round(time.time() - note["started"], 1),
        "last_transition": dict(note.get("transitions", {})),
        "session": {
            "id": f"0x{zk.session_id:x}",
            "state": note["zk_state"],
            "connected": zk.connected,
            "closed": zk.closed,
            "server": (
                f"{zk.connected_server[0]}:{zk.connected_server[1]}"
                if zk.connected_server
                else None
            ),
            # True while attached to a read-only (minority) member:
            # resolves/heartbeats serve, writes refuse — the
            # OPERATIONS.md "read-only mode" alert's source of truth.
            "readOnly": getattr(zk, "read_only", False),
            "negotiatedTimeoutMs": zk.negotiated_timeout_ms,
            "rebirths": zk.rebirths,
            # Connect-race outcome + failover latency (ISSUE 20): the
            # runbook's first stop for "why was recovery slow" — which
            # member the last raced pass attached (None under the serial
            # reference path), how many candidates it dialed / aborted,
            # and how long the last unexpected-teardown -> reconnect
            # window took.
            "connectRace": {
                "wins": zk.race_stats["wins"],
                "lastWinner": zk.race_stats["last_winner"],
                "lastCandidates": zk.race_stats["last_candidates"],
                "lastAborted": zk.race_stats["last_aborted"],
            },
            "lastFailoverS": (
                round(zk.last_failover_s, 4)
                if zk.last_failover_s is not None
                else None
            ),
            "watchdogDrops": zk.watchdog_drops,
        },
        "registration": {
            "epoch": ee.epoch,
            "registered": bool(znodes),
            "znodes": [
                {"path": p, "mzxid": mzxids[p]} for p in znodes
            ],
            "readError": read_error,
        },
        "health": {
            "configured": health is not None,
            "down": ee.down,
            "checkerDown": bool(health.is_down) if health else False,
        },
        "reconcile": {
            "configured": ee.reconciler is not None,
            "lastSweep": note["last_reconcile"],
            "driftSeen": (
                ee.reconciler.drift_seen if ee.reconciler else None
            ),
            "ownerConflicts": (
                ee.reconciler.owner_conflicts if ee.reconciler else None
            ),
        },
        # The daemon never resolves; the cache block is for embedders
        # (zkcli serve-view exposes the same shape via its status line).
        "cache": None,
        "config": {
            "source": cfg.source_path,
            "fingerprint": statefile.config_fingerprint(
                cfg.registration, cfg.admin_ip, cfg.zookeeper.chroot
            ),
        },
        "observability": {
            "enabled": tr.enabled,
            "spansRecorded": getattr(tr, "spans_recorded", 0),
            "eventsRecorded": getattr(tr, "events_recorded", 0),
        },
    }


def _cold_reload_changes(old: Config, new: Config) -> list:
    """Config keys changed between ``old`` and ``new`` that can NOT
    hot-apply over SIGHUP — named in the reload warning so operators
    know those changes still need a restart.  Everything that shapes the
    znode records (registration, adminIp) hot-applies; logLevel
    hot-applies separately."""
    cold = []
    if old.zookeeper != new.zookeeper:
        cold.append("zookeeper")
    if old.health_check != new.health_check:
        cold.append("healthCheck")
    if old.metrics != new.metrics:
        cold.append("metrics")
    if old.reconcile != new.reconcile:
        cold.append("reconcile")
    if old.restart != new.restart:
        cold.append("restart")
    if old.survive_session_expiry != new.survive_session_expiry:
        cold.append("surviveSessionExpiry")
    if old.max_session_rebirths != new.max_session_rebirths:
        cold.append("maxSessionRebirths")
    if old.repair_heartbeat_miss != new.repair_heartbeat_miss:
        cold.append("repairHeartbeatMiss")
    if old.heartbeat_interval_s != new.heartbeat_interval_s:
        cold.append("registration.heartbeatInterval")
    if (
        old.heartbeat_retry.max_attempts != new.heartbeat_retry.max_attempts
    ):
        cold.append("maxAttempts")
    if old.cache != new.cache:
        cold.append("cache")
    if old.observability != new.observability:
        cold.append("observability")
    return cold


def install_event_loop(cfg: Config) -> str:
    """Apply ``zookeeper.eventLoop`` (ISSUE 11); returns the loop in
    effect (``"uvloop"`` or ``"asyncio"``).

    ``"uvloop"`` installs uvloop's event-loop policy when the package is
    importable; a missing/broken uvloop logs a warning and falls back to
    asyncio — the daemon never fails to start over an optional
    accelerator.  Default (absent key, or ``"asyncio"``): no policy
    change at all, byte-identical to every prior release.  The wire
    behavior is loop-independent either way (parity pinned by
    tests/test_main.py).
    """
    if cfg.zookeeper.event_loop != "uvloop":
        return "asyncio"
    try:
        import uvloop  # noqa: PLC0415 - optional, import-guarded
    except ImportError:
        logging.getLogger("registrar").warning(
            "config zookeeper.eventLoop is \"uvloop\" but uvloop is not "
            "installed; continuing on the stdlib asyncio loop"
        )
        return "asyncio"
    asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
    return "uvloop"


def main(argv=None) -> None:
    cfg = configure(argv)
    install_event_loop(cfg)
    try:
        asyncio.run(run(cfg))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
