"""The registrar daemon mainline (CLI).

Rebuild of reference main.js:102-200.  Usage::

    python -m registrar_tpu -f /opt/registrar/etc/config.json [-v ...]

Behavior parity:

  * ``-f`` config file (required), ``-v`` repeatable verbosity escalation,
    ``-h`` usage (reference main.js:29-46,107-121);
  * log level: LOG_LEVEL env < config ``logLevel`` < ``-v`` flags
    (reference main.js:24,66-76); bunyan-shaped JSON lines on stdout;
  * ZooKeeper connect retries forever with exponential 1-90 s backoff
    (reference lib/zk.js:97-101);
  * ``session_expired`` => log fatal + ``exit(1)`` so the supervisor
    (systemd/SMF) restarts the process with a fresh session — crash-restart
    is the load-bearing recovery design (reference main.js:141-144,
    SURVEY.md §3.4).  The opt-in ``surviveSessionExpiry`` config key
    (ISSUE 3) absorbs expiry in-process instead: the client builds a
    fresh session, the agent re-registers, and exit(1) only remains as
    the fallback when the rebirth circuit breaker trips;
  * every lifecycle event is logged, with heartbeat failures edge-triggered
    through an ``is_down`` latch so a long outage logs once
    (reference main.js:149,187-198).

Addition over the reference: SIGTERM/SIGINT run a graceful stop that
closes the ZK session, which deletes the ephemerals *immediately* instead
of waiting out the session timeout — an instance drained with
``systemctl stop registrar`` leaves DNS as fast as Binder's cache allows.
(The reference is stopped with SMF ``:kill`` and waits for expiry,
README.md:85-87.)
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys

from registrar_tpu import __version__
from registrar_tpu import jlog
from registrar_tpu.agent import register_plus
from registrar_tpu.config import (
    Config,
    ConfigError,
    ConfigUnreadableError,
    load_config,
)
from registrar_tpu.zk.client import create_zk_client


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="registrar",
        description="service-discovery sidecar: registers this host in "
        "ZooKeeper for Binder-served DNS",
    )
    parser.add_argument(
        "-f", "--file", metavar="FILE", required=True,
        help="configuration file to process",
    )
    parser.add_argument(
        "-v", "--verbose", action="count", default=0,
        help="verbose output; use multiple times for more verbosity",
    )
    parser.add_argument(
        "-n", "--check-config", action="store_true",
        help="validate the configuration file and exit (0 = valid); "
        "no ZooKeeper connection is made",
    )
    parser.add_argument(
        "--version", action="version", version=f"registrar {__version__}"
    )
    return parser.parse_args(argv)


#: exit status for configuration errors (BSD sysexits EX_CONFIG).
#: Distinct from the generic exit(1) used for runtime failures (session
#: expiry, failed initial registration) so the supervisor can crash-
#: restart on the latter but stop retrying a config that can never work
#: (systemd/registrar.service sets RestartPreventExitStatus=78).
EX_CONFIG = 78


def configure(argv=None) -> Config:
    """Parse args + config, set up logging (reference main.js:52-84)."""
    args = parse_args(argv)
    log = jlog.setup("registrar")
    try:
        cfg = load_config(args.file)
    except ConfigUnreadableError as e:
        # Read failures (file not provisioned yet, permissions) are often
        # transient — exit 1 so the supervisor's restart can cure them,
        # unlike the EX_CONFIG path below which it must not retry.
        log.critical("unable to read configuration %s", args.file,
                     exc_info=(type(e), e, e.__traceback__))
        sys.exit(1)
    except ConfigError as e:
        log.critical("invalid configuration %s", args.file,
                     exc_info=(type(e), e, e.__traceback__))
        sys.exit(EX_CONFIG)
    if cfg.unknown_keys:
        # Ignored like the reference ignores them — but a typo like
        # "healthcheck" silently disabling health checking is worth a
        # warning.  Emitted BEFORE the config's own logLevel applies, so
        # a {"logLevel": "error"} config cannot suppress it.
        log.warning(
            "configuration has unrecognized top-level keys (ignored): %s",
            ", ".join(cfg.unknown_keys),
            extra={"zdata": {"keys": list(cfg.unknown_keys)}},
        )
    if cfg.log_level:
        level = jlog.LEVELS.get(cfg.log_level.lower())
        if level is None:
            log.critical("invalid logLevel %r", cfg.log_level)
            sys.exit(EX_CONFIG)
        logging.getLogger().setLevel(level)
    if args.verbose:
        jlog.escalate(args.verbose)
    if args.check_config:
        # nginx -t style pre-flight for config-agent/CI pipelines: the same
        # validation the daemon would apply, without touching ZooKeeper —
        # including the registration schema check register_plus runs at
        # startup (reference lib/register.js:174-201), which load_config
        # alone does not cover.
        from registrar_tpu.registration import _validate_registration

        try:
            _validate_registration(cfg.registration)
        except ValueError as e:
            log.critical("invalid registration in %s", args.file,
                         exc_info=(type(e), e, e.__traceback__))
            sys.exit(EX_CONFIG)
        log.info("configuration OK", extra={"zdata": {"file": args.file}})
        sys.exit(0)
    log.info("configuration loaded from %s", args.file,
             extra={"zdata": {"file": args.file}})
    return cfg


async def run(cfg: Config, *, _exit=sys.exit) -> None:
    """Connect, register, and serve events until stopped or expired."""
    log = logging.getLogger("registrar")

    zk = await create_zk_client(
        cfg.zookeeper.servers,
        timeout_ms=cfg.zookeeper.timeout_ms,
        connect_timeout_ms=cfg.zookeeper.connect_timeout_ms,
        chroot=cfg.zookeeper.chroot,
        request_timeout_ms=cfg.zookeeper.request_timeout_ms,
        survive_session_expiry=cfg.survive_session_expiry,
        max_session_rebirths=cfg.max_session_rebirths,
    )

    zk.on("close", lambda *a: log.warning("zookeeper: disconnected"))
    # The initial connect already happened; later connects are reconnects
    # (the reference ignores the first 'connect' for the same reason,
    # main.js:135-139).
    zk.on("connect", lambda *a: log.info("zookeeper: reconnected"))

    stopping = asyncio.Event()
    exit_code = 0

    def _die(msg: str) -> None:
        # Route fatal conditions through the orderly shutdown below rather
        # than raising SystemExit inside the emitting task: zk.close()
        # then completes (deleting any half-registered ephemerals
        # immediately) before the process exits nonzero.
        nonlocal exit_code
        log.critical(msg)
        exit_code = 1
        stopping.set()

    # With surviveSessionExpiry, expiry is absorbed in-process and
    # announced as session_reborn; session_expired then only fires
    # terminally (feature off, or the rebirth circuit breaker tripped) —
    # either way the reference's crash-restart path below still applies.
    zk.on("session_expired",
          lambda *_a: _die("ZooKeeper session_expired event; exiting"))
    zk.on("session_reborn", lambda sid: log.warning(
        "zookeeper: session expired; fresh session established in-process",
        extra={"zdata": {"session": f"0x{sid:x}"}}))
    zk.on("rebirth_breaker_tripped", lambda n: log.error(
        "zookeeper: session rebirth circuit breaker tripped",
        extra={"zdata": {"rebirths_in_window": n}}))

    ee = register_plus(
        zk,
        cfg.registration,
        admin_ip=cfg.admin_ip,
        health_check=cfg.health_check,
        heartbeat_interval=cfg.heartbeat_interval_s,
        heartbeat_retry=cfg.heartbeat_retry,
        repair_heartbeat_miss=cfg.repair_heartbeat_miss,
        reconcile=(
            {
                "interval_seconds": cfg.reconcile.interval_s,
                "repair": cfg.reconcile.repair,
            }
            if cfg.reconcile is not None
            else None
        ),
    )

    ee.on("fail", lambda err: log.error(
        "registrar: healthcheck failed", extra={"zdata": {"err": err}}))
    ee.on("ok", lambda: log.info("registrar: healthcheck ok (was down)"))

    def on_error(err) -> None:
        log.error("registrar: unexpected error", extra={"zdata": {"err": err}})
        if not ee.znodes:
            # Initial registration failed: nothing will retry it (the
            # reference just logs and idles broken, lib/index.js:46-50).
            # Exit so the supervisor restarts us — the same crash-restart
            # policy as session expiry.
            _die("registrar: initial registration failed; exiting")

    ee.on("error", on_error)
    ee.on("register", lambda nodes: log.info(
        "registrar: registered", extra={"zdata": {"znodes": nodes}}))
    ee.on("unregister", lambda err, nodes: log.warning(
        "registrar: unregistered",
        extra={"zdata": {"err": err, "znodes": nodes}}))
    ee.on("drift", lambda d: log.warning(
        "registrar: drift detected",
        extra={"zdata": {"path": d.path, "reason": d.reason,
                         "detail": d.detail}}))
    ee.on("driftRepaired", lambda d: log.info(
        "registrar: drift repaired",
        extra={"zdata": {"path": d.path, "reason": d.reason}}))

    # Edge-triggered heartbeat logging (reference main.js:149,187-198).
    is_down = False

    def on_heartbeat_failure(err) -> None:
        nonlocal is_down
        if not is_down:
            log.error("zookeeper: heartbeat failed",
                      extra={"zdata": {"err": err}})
        is_down = True

    def on_heartbeat(_nodes) -> None:
        nonlocal is_down
        if is_down:
            log.info("zookeeper heartbeat ok")
        is_down = False

    ee.on("heartbeatFailure", on_heartbeat_failure)
    ee.on("heartbeat", on_heartbeat)

    metrics_server = None
    if cfg.metrics is not None:
        from registrar_tpu.metrics import MetricsServer, instrument

        try:
            metrics_server = await MetricsServer(
                instrument(ee, zk),
                host=cfg.metrics.host,
                port=cfg.metrics.port,
            ).start()
        except OSError as err:
            # A busy/forbidden port must not take down registration —
            # metrics are an observability add-on, not the product.
            log.error("metrics: cannot listen on %s:%d",
                      cfg.metrics.host, cfg.metrics.port,
                      extra={"zdata": {"err": err}})
        else:
            log.info("metrics: listening",
                     extra={"zdata": {"host": cfg.metrics.host,
                                      "port": metrics_server.port}})

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stopping.set)
        except NotImplementedError:  # non-unix test environments
            pass

    await stopping.wait()
    log.info("registrar: shutting down")
    ee.stop()
    if metrics_server is not None:
        await metrics_server.stop()
    await zk.close()  # deletes our ephemerals immediately (see docstring)
    if exit_code:
        _exit(exit_code)


def main(argv=None) -> None:
    cfg = configure(argv)
    try:
        asyncio.run(run(cfg))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
