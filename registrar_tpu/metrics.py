"""Opt-in Prometheus-text metrics endpoint.

The reference has **no** metrics surface — bunyan logs only; SURVEY.md §5
notes its Triton/Manta contemporaries exposed counters via node-artedi on
an HTTP port.  This module is that analog for the rebuild: a tiny
dependency-free registry rendering Prometheus text exposition format
0.0.4, served by an asyncio HTTP listener, fed from the
:func:`registrar_tpu.agent.register_plus` event surface and the ZK
client's connection state.

Everything is opt-in via the ``metrics`` config block (docs/CONFIG.md);
without it the daemon behaves exactly like the reference.

    GET /metrics   -> text/plain; version=0.0.4 exposition
    anything else  -> 404

Exported metrics (all prefixed ``registrar_``):

    registrar_registrations_total       registrations completed (incl.
                                        health recovery + heartbeat repair)
    registrar_unregistrations_total     health-driven deregistrations
    registrar_heartbeats_total{status}  znode probes, status="ok"|"failure"
    registrar_health_transitions_total{to}  threshold crossings, to="down"|"up"
    registrar_errors_total              'error' events from any subsystem
    registrar_health_down               1 while deregistered by health, else 0
    registrar_znodes_owned              znodes this instance maintains
    registrar_zk_connected              1 while the ZK session is connected
    registrar_uptime_seconds            seconds since instrumentation started
    registrar_session_rebirths_total    fresh in-process sessions after expiry
                                        (surviveSessionExpiry, ISSUE 3)
    registrar_rebirth_breaker_trips_total  rebirth circuit-breaker trips
                                        (fell back to terminal expiry)
    registrar_drift_total{reason}       reconciler drift detected, by reason
    registrar_drift_repaired_total{reason}  reconciler drift converged
    registrar_reconcile_sweeps_total    reconcile sweeps completed
    registrar_reconcile_sweep_seconds   duration of the last reconcile sweep
    registrar_handoffs_total            handoff shutdowns: session left
                                        alive for a successor (ISSUE 5)
    registrar_drains_total              drain shutdowns (clean unregister)
    registrar_session_resumes_total{outcome}  cross-process session
                                        resumes (reattached|repaired|fresh)
    registrar_config_reloads_total{result}  SIGHUP config reloads
                                        (applied|noop|failed)

:func:`instrument_cache` (ISSUE 4) additionally exposes the
watch-coherent resolve cache (:mod:`registrar_tpu.zkcache`):

    registrar_cache_hits_total / _misses_total / _invalidations_total
    registrar_cache_bypasses_total      lookups served live while degraded
    registrar_cache_degraded_total      transitions into degraded mode
    registrar_cache_evictions_total     maxEntries evictions
    registrar_cache_entries             entries currently cached (gauge)
    registrar_cache_authoritative       1 = coherence-guaranteed (gauge)
    registrar_cache_coherence_lag_seconds[_total|_count]
                                        write→cache-visible lag
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

from registrar_tpu import reconcile as reconcile_mod

log = logging.getLogger("registrar_tpu.metrics")

_LabelKey = Tuple[Tuple[str, str], ...]


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


class _Metric:
    """One metric family: name, help text, per-labelset values."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: Dict[_LabelKey, float] = {}

    def _key(self, labels: Optional[Dict[str, str]]) -> _LabelKey:
        return tuple(sorted((labels or {}).items()))

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(self._key(labels), 0.0)

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        # Deterministic output: label sets in sorted order.
        for key in sorted(self._values):
            value = self._values[key]
            if key:
                labels = ",".join(
                    f'{k}="{_escape_label(v)}"' for k, v in key
                )
                lines.append(f"{self.name}{{{labels}}} {_format(value)}")
            else:
                lines.append(f"{self.name} {_format(value)}")
        if len(lines) == 2:  # no samples yet: expose an explicit zero
            lines.append(f"{self.name} 0")
        return lines


def _format(value: float) -> str:
    return str(int(value)) if float(value).is_integer() else repr(value)


class Counter(_Metric):
    kind = "counter"

    def inc(
        self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None
    ) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        self._values[key] = self._values.get(key, 0.0) + amount

    #: counters backed by a live total are read at scrape time (the
    #: cache's hot path bumps a plain int; an event per lookup would put
    #:  an emitter dispatch inside every DNS answer).  The backing total
    #: must be monotonic — that is the exporter's contract to keep.
    fn: Optional[Callable[[], float]] = None

    def set_function(self, fn: Callable[[], float]) -> None:
        self.fn = fn

    def render(self) -> List[str]:
        if self.fn is not None:
            self._values[self._key(None)] = float(self.fn())
        return super().render()


class Gauge(_Metric):
    kind = "gauge"

    def set(
        self, value: float, labels: Optional[Dict[str, str]] = None
    ) -> None:
        self._values[self._key(labels)] = float(value)

    #: gauges with a callback are computed at scrape time
    fn: Optional[Callable[[], float]] = None

    def set_function(self, fn: Callable[[], float]) -> None:
        self.fn = fn

    def render(self) -> List[str]:
        if self.fn is not None:
            self.set(self.fn())
        return super().render()


class MetricsRegistry:
    """Ordered collection of metric families; renders the exposition."""

    def __init__(self) -> None:
        self._metrics: List[_Metric] = []
        self._by_name: Dict[str, _Metric] = {}

    def counter(self, name: str, help_text: str) -> Counter:
        return self._add(Counter(name, help_text))

    def gauge(self, name: str, help_text: str) -> Gauge:
        return self._add(Gauge(name, help_text))

    def _add(self, metric):
        if metric.name in self._by_name:
            raise ValueError(f"duplicate metric {metric.name}")
        self._metrics.append(metric)
        self._by_name[metric.name] = metric
        return metric

    def get(self, name: str) -> Optional[_Metric]:
        return self._by_name.get(name)

    def render(self) -> str:
        out: List[str] = []
        for metric in self._metrics:
            out.extend(metric.render())
        return "\n".join(out) + "\n"


class MetricsServer:
    """Minimal asyncio HTTP/1.0 server exposing ``GET /metrics``.

    Deliberately tiny: one request per connection, no keep-alive, no TLS —
    the same operational footprint as an artedi/kang listener, meant for a
    loopback or management network (bind 127.0.0.1 by default).
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.registry = registry
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "MetricsServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.debug("metrics listening on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader, writer) -> None:
        try:
            try:
                request = await asyncio.wait_for(
                    reader.readline(), timeout=5.0
                )
            except (asyncio.TimeoutError, ValueError):
                # ValueError: line exceeded the StreamReader limit (a
                # hostile/garbage request) — drop it, no response owed.
                return
            parts = request.decode("latin-1", "replace").split()
            # Drain headers (bounded) so well-behaved clients see a clean
            # close instead of a reset.
            for _ in range(100):
                try:
                    line = await asyncio.wait_for(
                        reader.readline(), timeout=5.0
                    )
                except ValueError:  # oversized header line
                    return
                if line in (b"\r\n", b"\n", b""):
                    break
            if len(parts) >= 2 and parts[0] == "GET" and (
                parts[1] == "/metrics" or parts[1].startswith("/metrics?")
            ):
                body = self.registry.render().encode()
                status = "200 OK"
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                body = b"try GET /metrics\n"
                status = "404 Not Found"
                ctype = "text/plain; charset=utf-8"
            writer.write(
                (
                    f"HTTP/1.0 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + body
            )
            await writer.drain()
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass


def instrument(ee, zk, registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Wire a :class:`MetricsRegistry` to the register_plus event surface.

    ``ee`` is the :class:`registrar_tpu.agent.RegistrarEvents` emitter,
    ``zk`` the :class:`registrar_tpu.zk.client.ZKClient`.  Returns the
    registry (creating one when not given).  Call once, before or after
    the initial 'register' event — gauges read live state at scrape time.
    """
    reg = registry if registry is not None else MetricsRegistry()

    registrations = reg.counter(
        "registrar_registrations_total",
        "Registrations completed (initial, health recovery, heartbeat repair)",
    )
    unregistrations = reg.counter(
        "registrar_unregistrations_total",
        "Health-driven deregistrations completed",
    )
    heartbeats = reg.counter(
        "registrar_heartbeats_total",
        "Znode liveness probes by status (ok|failure)",
    )
    transitions = reg.counter(
        "registrar_health_transitions_total",
        "Health-check threshold crossings (to=down|up)",
    )
    errors = reg.counter(
        "registrar_errors_total", "Unexpected errors from any subsystem"
    )
    down = reg.gauge(
        "registrar_health_down",
        "1 while the health checker holds this host deregistered",
    )
    znodes = reg.gauge(
        "registrar_znodes_owned", "Znodes this instance maintains"
    )
    connected = reg.gauge(
        "registrar_zk_connected", "1 while the ZooKeeper session is connected"
    )
    uptime = reg.gauge(
        "registrar_uptime_seconds", "Seconds since instrumentation started"
    )
    rebirths = reg.counter(
        "registrar_session_rebirths_total",
        "Fresh ZK sessions established in-process after an expiry "
        "(surviveSessionExpiry)",
    )
    breaker_trips = reg.counter(
        "registrar_rebirth_breaker_trips_total",
        "Session-rebirth circuit breaker trips (fell back to terminal "
        "session expiry)",
    )
    drift = reg.counter(
        "registrar_drift_total",
        "Registration drift detected by the reconciler, by reason",
    )
    drift_repaired = reg.counter(
        "registrar_drift_repaired_total",
        "Registration drift converged by the reconciler, by reason",
    )
    sweeps = reg.counter(
        "registrar_reconcile_sweeps_total", "Reconcile sweeps completed"
    )
    sweep_seconds = reg.gauge(
        "registrar_reconcile_sweep_seconds",
        "Duration of the last reconcile sweep (seconds)",
    )
    handoffs = reg.counter(
        "registrar_handoffs_total",
        "Handoff shutdowns: session state persisted, connection "
        "detached with the session (and ephemerals) left alive for a "
        "successor (restart.mode=handoff, ISSUE 5)",
    )
    drains = reg.counter(
        "registrar_drains_total",
        "Drain shutdowns: znodes unregistered cleanly before exit "
        "(restart.mode=drain)",
    )
    resumes = reg.counter(
        "registrar_session_resumes_total",
        "Cross-process session resume attempts by outcome: reattached "
        "(verified in place, zero NO_NODE), repaired (reattached but "
        "drifted; pipeline re-ran), fresh (state unusable or reattach "
        "refused; normal registration)",
    )
    reloads = reg.counter(
        "registrar_config_reloads_total",
        "SIGHUP config reloads by result (applied|noop|failed)",
    )
    watch_events = reg.counter(
        "registrar_watch_events_total",
        "ZooKeeper watch notifications delivered to this client "
        "(the firehose behind cache invalidation and watch re-arm)",
    )

    start = time.monotonic()
    uptime.set_function(lambda: time.monotonic() - start)
    down.set_function(lambda: 1.0 if ee.down else 0.0)
    znodes.set_function(lambda: float(len(ee.znodes)))
    connected.set_function(lambda: 1.0 if zk.connected else 0.0)

    # Pre-seed every documented label set at 0 so each series exists from
    # the first scrape — a counter appearing only on its first increment
    # breaks rate()/absent() queries, and the unlabeled zero placeholder
    # (render fallback) would otherwise vanish once a labeled sample lands.
    for status in ("ok", "failure"):
        heartbeats.inc(0, labels={"status": status})
    for to in ("down", "up"):
        transitions.inc(0, labels={"to": to})
    for reason in reconcile_mod.REASONS:
        drift.inc(0, labels={"reason": reason})
        drift_repaired.inc(0, labels={"reason": reason})
    for outcome in ("reattached", "repaired", "fresh"):
        resumes.inc(0, labels={"outcome": outcome})
    for result in ("applied", "noop", "failed"):
        reloads.inc(0, labels={"result": result})

    def on_sweep(summary) -> None:
        sweeps.inc()
        sweep_seconds.set(float(summary.get("duration", 0.0)))

    zk.on("session_reborn", lambda *_a: rebirths.inc())
    zk.on("rebirth_breaker_tripped", lambda *_a: breaker_trips.inc())
    zk.on("watch", lambda *_a: watch_events.inc())
    ee.on("handoff", lambda *_a: handoffs.inc())
    ee.on("drain", lambda *_a: drains.inc())
    ee.on("resume", lambda outcome: resumes.inc(labels={"outcome": outcome}))
    ee.on(
        "configReload",
        lambda result: reloads.inc(labels={"result": result}),
    )
    ee.on("drift", lambda d: drift.inc(labels={"reason": d.reason}))
    ee.on(
        "driftRepaired",
        lambda d: drift_repaired.inc(labels={"reason": d.reason}),
    )
    ee.on("reconcile", on_sweep)
    ee.on("register", lambda *_a: registrations.inc())
    ee.on("unregister", lambda *_a: unregistrations.inc())
    ee.on("heartbeat", lambda *_a: heartbeats.inc(labels={"status": "ok"}))
    ee.on(
        "heartbeatFailure",
        lambda *_a: heartbeats.inc(labels={"status": "failure"}),
    )
    ee.on("fail", lambda *_a: transitions.inc(labels={"to": "down"}))
    ee.on("ok", lambda *_a: transitions.inc(labels={"to": "up"}))
    ee.on("error", lambda *_a: errors.inc())
    return reg


def instrument_cache(cache, registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Expose a :class:`registrar_tpu.zkcache.ZKCache`'s counters.

    The cache's lookup hot path bumps plain ints in ``cache.stats``;
    the registry reads them at scrape time (``Counter.set_function``),
    so instrumentation adds zero cost to a cached DNS answer.  Every
    series exists from the first scrape (pre-seeded via the same
    scrape-time read — the backing stats start at 0).
    """
    reg = registry if registry is not None else MetricsRegistry()
    stats = cache.stats

    def from_stat(metric, key: str) -> None:
        metric.set_function(lambda: stats[key])

    from_stat(reg.counter(
        "registrar_cache_hits_total",
        "Resolve-cache lookups served from memory",
    ), "hits")
    from_stat(reg.counter(
        "registrar_cache_misses_total",
        "Resolve-cache lookups that needed a live ZooKeeper read",
    ), "misses")
    from_stat(reg.counter(
        "registrar_cache_invalidations_total",
        "Cache entries dropped by a fired one-shot watch",
    ), "invalidations")
    from_stat(reg.counter(
        "registrar_cache_bypasses_total",
        "Lookups served live because the cache was degraded "
        "(session down or watch re-arm failed)",
    ), "bypasses")
    from_stat(reg.counter(
        "registrar_cache_degraded_total",
        "Transitions into degraded (non-authoritative) mode",
    ), "degraded_total")
    from_stat(reg.counter(
        "registrar_cache_evictions_total",
        "Entries evicted by the maxEntries bound",
    ), "evictions")
    reg.counter(
        "registrar_cache_coherence_lag_seconds_total",
        "Sum of observed write-to-invalidation-processed lag (the "
        "window in which a cached answer could still be stale; "
        "divide by _count for the mean)",
    ).set_function(lambda: stats["coherence_lag_ms_total"] / 1000.0)
    from_stat(reg.counter(
        "registrar_cache_coherence_lag_count",
        "Number of coherence-lag observations",
    ), "coherence_lag_count")
    entries = reg.gauge(
        "registrar_cache_entries", "Entries currently cached"
    )
    entries.set_function(lambda: float(cache.entries))
    authoritative = reg.gauge(
        "registrar_cache_authoritative",
        "1 while cached answers are coherence-guaranteed, 0 in "
        "degraded (live-read) mode",
    )
    authoritative.set_function(lambda: 1.0 if cache.authoritative else 0.0)
    lag_last = reg.gauge(
        "registrar_cache_coherence_lag_seconds",
        "Last observed write-to-invalidation-processed lag (seconds)",
    )
    lag_last.set_function(lambda: stats["coherence_lag_ms_last"] / 1000.0)
    return reg
