"""Opt-in Prometheus-text metrics endpoint.

The reference has **no** metrics surface — bunyan logs only; SURVEY.md §5
notes its Triton/Manta contemporaries exposed counters via node-artedi on
an HTTP port.  This module is that analog for the rebuild: a tiny
dependency-free registry rendering Prometheus text exposition format
0.0.4, served by an asyncio HTTP listener, fed from the
:func:`registrar_tpu.agent.register_plus` event surface and the ZK
client's connection state.

Everything is opt-in via the ``metrics`` config block (docs/CONFIG.md);
without it the daemon behaves exactly like the reference.

    GET /metrics   -> text/plain; version=0.0.4 exposition
    anything else  -> 404

Exported metrics (all prefixed ``registrar_``):

    registrar_registrations_total       registrations completed (incl.
                                        health recovery + heartbeat repair)
    registrar_unregistrations_total     health-driven deregistrations
    registrar_heartbeats_total{status}  znode probes, status="ok"|"failure"
    registrar_health_transitions_total{to}  threshold crossings, to="down"|"up"
    registrar_errors_total              'error' events from any subsystem
    registrar_malformed_frames_total{surface}  malformed peer frames rejected
                                        at a decode boundary (jute, zk
                                        framing/handshake, shard wire)
    registrar_health_down               1 while deregistered by health, else 0
    registrar_znodes_owned              znodes this instance maintains
    registrar_zk_connected              1 while the ZK session is connected
    registrar_uptime_seconds            seconds since instrumentation started
    registrar_session_rebirths_total    fresh in-process sessions after expiry
                                        (surviveSessionExpiry, ISSUE 3)
    registrar_rebirth_breaker_trips_total  rebirth circuit-breaker trips
                                        (fell back to terminal expiry)
    registrar_drift_total{reason}       reconciler drift detected, by reason
    registrar_drift_repaired_total{reason}  reconciler drift converged
    registrar_reconcile_sweeps_total    reconcile sweeps completed
    registrar_reconcile_sweep_seconds   duration of the last reconcile sweep
    registrar_handoffs_total            handoff shutdowns: session left
                                        alive for a successor (ISSUE 5)
    registrar_drains_total              drain shutdowns (clean unregister)
    registrar_session_resumes_total{outcome}  cross-process session
                                        resumes (reattached|repaired|fresh)
    registrar_config_reloads_total{result}  SIGHUP config reloads
                                        (applied|noop|failed)

:func:`instrument_cache` (ISSUE 4) additionally exposes the
watch-coherent resolve cache (:mod:`registrar_tpu.zkcache`):

    registrar_cache_hits_total / _misses_total / _invalidations_total
    registrar_cache_bypasses_total      lookups served live while degraded
    registrar_cache_degraded_total      transitions into degraded mode
    registrar_cache_evictions_total     maxEntries evictions
    registrar_cache_entries             entries currently cached (gauge)
    registrar_cache_authoritative       1 = coherence-guaranteed (gauge)
    registrar_cache_coherence_lag_seconds[_total|_count]
                                        write→cache-visible lag

:func:`instrument_tracing` (ISSUE 8) feeds real latency **histograms**
(`_bucket`/`_sum`/`_count` series) from the span layer
(:mod:`registrar_tpu.trace`) — only wired when the ``observability``
config block enables tracing, so metric output stays byte-identical
without it:

    registrar_zk_op_seconds{op}         one observation per ZooKeeper
                                        request (queue + wire)
    registrar_resolve_seconds{source}   Binder-view resolves,
                                        source="cached"|"live"
    registrar_health_exec_seconds       health-check command executions
    registrar_reconcile_sweep_seconds   reconcile sweeps (replaces the
                                        last-value gauge of the same
                                        name while tracing is on)

:func:`instrument_slo` (ISSUE 9) exposes the availability-SLO
harness's probe surface (:mod:`registrar_tpu.testing.slo`):

    registrar_slo_probe_total{result}   availability probes, result="ok"|"fail"
    registrar_slo_outage_seconds_total{fault}  probe-observed outage
                                        seconds per owning fault class

The MetricsServer additionally serves (ISSUE 8):

    GET /status        one JSON snapshot: session id/state, registration
                       epoch + owned znodes with mzxids, health state,
                       cache stats, last drift summary, config
                       fingerprint — the runbook's first stop
                       (docs/OPERATIONS.md "first 5 minutes")
    GET /debug/trace?n=  the flight recorder's most recent n entries
    non-GET on a known path -> 405 with ``Allow: GET``
"""

from __future__ import annotations

import asyncio
import bisect
import json
import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

from registrar_tpu import malformed as malformed_mod
from registrar_tpu import reconcile as reconcile_mod
from registrar_tpu import trace as trace_mod

log = logging.getLogger("registrar_tpu.metrics")

_LabelKey = Tuple[Tuple[str, str], ...]


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


class _Metric:
    """One metric family: name, help text, per-labelset values."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: Dict[_LabelKey, float] = {}

    def _key(self, labels: Optional[Dict[str, str]]) -> _LabelKey:
        return tuple(sorted((labels or {}).items()))

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(self._key(labels), 0.0)

    def remove(self, labels: Optional[Dict[str, str]] = None) -> None:
        """Drop one label set's series entirely (topology change: a
        resharded-away shard id must stop rendering, not freeze at its
        last value — a phantom ``up 1`` defeats the health signal)."""
        self._values.pop(self._key(labels), None)

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        # Deterministic output: label sets in sorted order.
        for key in sorted(self._values):
            value = self._values[key]
            if key:
                labels = ",".join(
                    f'{k}="{_escape_label(v)}"' for k, v in key
                )
                lines.append(f"{self.name}{{{labels}}} {_format(value)}")
            else:
                lines.append(f"{self.name} {_format(value)}")
        if len(lines) == 2:  # no samples yet: expose an explicit zero
            lines.append(f"{self.name} 0")
        return lines


def _format(value: float) -> str:
    return str(int(value)) if float(value).is_integer() else repr(value)


class Counter(_Metric):
    kind = "counter"

    def inc(
        self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None
    ) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        self._values[key] = self._values.get(key, 0.0) + amount

    #: counters backed by a live total are read at scrape time (the
    #: cache's hot path bumps a plain int; an event per lookup would put
    #:  an emitter dispatch inside every DNS answer).  The backing total
    #: must be monotonic — that is the exporter's contract to keep.
    fn: Optional[Callable[[], float]] = None

    def set_function(self, fn: Callable[[], float]) -> None:
        self.fn = fn

    def set_total(
        self, value: float, labels: Optional[Dict[str, str]] = None
    ) -> None:
        """Install a polled cumulative total for one label set — the
        labeled counterpart of :meth:`set_function`, for counters whose
        truth lives in another process (the shard router polls each
        worker's resolves_total).  The caller owns monotonicity (the
        router banks a crashed worker's count before its successor
        restarts from zero); a stale lower value is ignored rather than
        rendered as a counter going backwards."""
        key = self._key(labels)
        if value >= self._values.get(key, 0.0):
            self._values[key] = float(value)

    def render(self) -> List[str]:
        if self.fn is not None:
            self._values[self._key(None)] = float(self.fn())
        return super().render()


class Gauge(_Metric):
    kind = "gauge"

    def set(
        self, value: float, labels: Optional[Dict[str, str]] = None
    ) -> None:
        self._values[self._key(labels)] = float(value)

    #: gauges with a callback are computed at scrape time
    fn: Optional[Callable[[], float]] = None

    def set_function(self, fn: Callable[[], float]) -> None:
        self.fn = fn

    def render(self) -> List[str]:
        if self.fn is not None:
            self.set(self.fn())
        return super().render()


#: default histogram buckets (seconds): spans range from tens of µs
#: (a warm cached resolve) to whole seconds (the settle-delayed
#: registration pipeline), so the ladder covers 100 µs – 10 s.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Histogram(_Metric):
    """Prometheus histogram: cumulative ``_bucket{le=}``, ``_sum``,
    ``_count`` per label set.  The family *name* is the bare metric
    name; only the suffixed series are rendered (standard exposition),
    which is why a histogram can replace a same-named gauge without the
    two ever colliding on a rendered series."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_text: str,
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_text)
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        #: per-label-set per-bucket counts (non-cumulative internally;
        #: rendered cumulative), plus the +Inf overflow slot at the end
        self._counts: Dict[_LabelKey, List[int]] = {}
        self._sums: Dict[_LabelKey, float] = {}

    def _slot(self, key: _LabelKey) -> List[int]:
        counts = self._counts.get(key)
        if counts is None:
            counts = self._counts[key] = [0] * (len(self.buckets) + 1)
            self._sums.setdefault(key, 0.0)
        return counts

    def preseed(self, labels: Optional[Dict[str, str]] = None) -> None:
        """Create the label set's zero series so alerts built on
        ``rate(..._count)`` see it from the first scrape (the registry's
        pre-seeding convention, same as Counter.inc(0))."""
        self._slot(self._key(labels))

    def observe(
        self, value: float, labels: Optional[Dict[str, str]] = None
    ) -> None:
        key = self._key(labels)
        counts = self._slot(key)
        counts[bisect.bisect_left(self.buckets, value)] += 1
        self._sums[key] = self._sums.get(key, 0.0) + value

    def set_totals(
        self,
        counts: List[int],
        total_sum: float,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        """Install polled cumulative per-bucket counts + sum for one
        label set — the histogram counterpart of Counter.set_total, for
        ladders whose truth lives in other processes (the shard router
        polls each worker's DNS latency counts and banks a crashed
        incarnation's).  ``counts`` is the non-cumulative per-bucket
        list incl. the +Inf slot (short lists are zero-padded); same
        monotonic guard — a stale lower snapshot is ignored rather than
        rendered as a histogram going backwards."""
        key = self._key(labels)
        fresh = [int(c) for c in counts]
        if len(fresh) > len(self.buckets) + 1:
            raise ValueError("more bucket counts than bounds")
        fresh.extend([0] * (len(self.buckets) + 1 - len(fresh)))
        if sum(fresh) < sum(self._counts.get(key, ())):
            return
        self._counts[key] = fresh
        self._sums[key] = max(float(total_sum), self._sums.get(key, 0.0))

    def count(self, labels: Optional[Dict[str, str]] = None) -> int:
        return sum(self._counts.get(self._key(labels), ()))

    def quantile(
        self, q: float, labels: Optional[Dict[str, str]] = None
    ) -> Optional[float]:
        """Bucket-interpolated quantile, the histogram_quantile()
        estimate (bench.py records p50/p95/p99 from exactly this).
        None when the label set has no observations."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be within [0, 1]")
        counts = self._counts.get(self._key(labels))
        total = sum(counts) if counts else 0
        if not total:
            return None
        rank = q * total
        seen = 0
        for i, bucket_count in enumerate(counts):
            seen += bucket_count
            if seen >= rank:
                if i >= len(self.buckets):
                    return self.buckets[-1]  # +Inf bucket: clamp
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                within = rank - (seen - bucket_count)
                return lo + (hi - lo) * (
                    within / bucket_count if bucket_count else 0.0
                )
        return self.buckets[-1]

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for key in sorted(self._counts):
            counts = self._counts[key]
            base = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
            sep = "," if base else ""
            cumulative = 0
            for bound, bucket_count in zip(self.buckets, counts):
                cumulative += bucket_count
                lines.append(
                    f'{self.name}_bucket{{{base}{sep}le="{_format(bound)}"}}'
                    f" {cumulative}"
                )
            cumulative += counts[-1]
            lines.append(
                f'{self.name}_bucket{{{base}{sep}le="+Inf"}} {cumulative}'
            )
            suffix = f"{{{base}}}" if base else ""
            lines.append(
                f"{self.name}_sum{suffix} {_format(self._sums.get(key, 0.0))}"
            )
            lines.append(f"{self.name}_count{suffix} {cumulative}")
        return lines


class MetricsRegistry:
    """Ordered collection of metric families; renders the exposition."""

    def __init__(self) -> None:
        self._metrics: List[_Metric] = []
        self._by_name: Dict[str, _Metric] = {}

    def counter(self, name: str, help_text: str) -> Counter:
        return self._add(Counter(name, help_text))

    def gauge(self, name: str, help_text: str) -> Gauge:
        return self._add(Gauge(name, help_text))

    def histogram(
        self,
        name: str,
        help_text: str,
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._add(Histogram(name, help_text, buckets))

    def _add(self, metric):
        if metric.name in self._by_name:
            raise ValueError(f"duplicate metric {metric.name}")
        self._metrics.append(metric)
        self._by_name[metric.name] = metric
        return metric

    def get(self, name: str) -> Optional[_Metric]:
        return self._by_name.get(name)

    def render(self) -> str:
        out: List[str] = []
        for metric in self._metrics:
            out.extend(metric.render())
        return "\n".join(out) + "\n"


#: total header bytes drained per request before the connection is
#: dropped: 100 lines of up-to-64KiB each (the StreamReader limit) would
#: otherwise let one hostile request make the daemon read ~6 MiB of
#: garbage per connection (ISSUE 8 hardening).
MAX_HEADER_BYTES = 16 * 1024


class MetricsServer:
    """Minimal asyncio HTTP/1.0 server exposing ``GET /metrics`` — plus,
    when providers are wired, ``GET /status`` (one introspection JSON
    snapshot) and ``GET /debug/trace?n=`` (the flight recorder).

    Deliberately tiny: one request per connection, no keep-alive, no TLS —
    the same operational footprint as an artedi/kang listener, meant for a
    loopback or management network (bind 127.0.0.1 by default).

    ``status_provider`` is an async callable returning the /status dict;
    ``trace_provider`` a sync callable ``(n: Optional[int]) -> dict``
    returning the /debug/trace payload; ``trace_tree_provider`` an
    async callable ``(trace_id: str) -> dict`` returning the ASSEMBLED
    cross-process tree for ``GET /debug/trace?id=<trace_id>`` (ISSUE
    13 — the shard router's OP_TRACE fan-out, or the daemon's own
    single-recorder assembly).  An unwired endpoint answers 404,
    exactly like any unknown path.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        host: str = "127.0.0.1",
        port: int = 0,
        status_provider=None,
        trace_provider=None,
        trace_tree_provider=None,
    ):
        self.registry = registry
        self.host = host
        self.status_provider = status_provider
        self.trace_provider = trace_provider
        self.trace_tree_provider = trace_tree_provider
        self._requested_port = port
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "MetricsServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.debug("metrics listening on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader, writer) -> None:
        try:
            try:
                request = await asyncio.wait_for(
                    reader.readline(), timeout=5.0
                )
            except (asyncio.TimeoutError, ValueError):
                # ValueError: line exceeded the StreamReader limit (a
                # hostile/garbage request) — drop it, no response owed.
                return
            parts = request.decode("latin-1", "replace").split()
            # Drain headers (bounded in LINES and total BYTES) so
            # well-behaved clients see a clean close instead of a reset,
            # while a hostile request cannot make us read megabytes of
            # headers one near-limit line at a time.
            drained = len(request)
            for _ in range(100):
                try:
                    line = await asyncio.wait_for(
                        reader.readline(), timeout=5.0
                    )
                except ValueError:  # oversized header line
                    return
                if line in (b"\r\n", b"\n", b""):
                    break
                drained += len(line)
                if drained > MAX_HEADER_BYTES:
                    return  # hostile header volume: drop, no response owed
            status, ctype, body, extra = await self._respond(parts)
            writer.write(
                (
                    f"HTTP/1.0 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    f"{extra}"
                    "Connection: close\r\n\r\n"
                ).encode()
                + body
            )
            await writer.drain()
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _respond(self, parts: List[str]):
        """Route one request: ``(status, content_type, body, extra_headers)``."""
        method = parts[0] if parts else ""
        target = parts[1] if len(parts) >= 2 else ""
        path, _, query = target.partition("?")
        known = path == "/metrics" or (
            path == "/status" and self.status_provider is not None
        ) or (
            path == "/debug/trace"
            and (
                self.trace_provider is not None
                or self.trace_tree_provider is not None
            )
        )
        if known and method != "GET":
            # The path exists; the method is wrong.  405 with Allow is
            # the contract clients (and security scanners) expect —
            # a 404 here would claim the endpoint doesn't exist.
            return (
                "405 Method Not Allowed",
                "text/plain; charset=utf-8",
                b"method not allowed; try GET\n",
                "Allow: GET\r\n",
            )
        if known and path == "/metrics":
            return (
                "200 OK",
                "text/plain; version=0.0.4; charset=utf-8",
                self.registry.render().encode(),
                "",
            )
        if known and path == "/status":
            try:
                snapshot = await self.status_provider()
                body = json.dumps(snapshot, indent=2, default=str).encode()
                body += b"\n"
            except Exception as err:  # noqa: BLE001 - introspection must answer
                log.exception("status provider raised")
                body = json.dumps({"error": repr(err)}).encode() + b"\n"
            return ("200 OK", "application/json; charset=utf-8", body, "")
        if known and path == "/debug/trace":
            n = None
            trace_id = None
            for kv in query.split("&"):
                key, _, value = kv.partition("=")
                if key == "n":
                    try:
                        n = int(value)
                    except ValueError:
                        pass
                elif key == "id" and value:
                    trace_id = value
            if trace_id is not None:
                # One ASSEMBLED tree (ISSUE 13) instead of the raw ring
                # — cross-process when the provider is the shard
                # router's OP_TRACE fan-out.  No provider = an explicit
                # error, never a silent fallback to the ring dump (the
                # shapes differ; zkcli trace --id would choke on it).
                if self.trace_tree_provider is None:
                    payload = {
                        "error": "trace assembly (?id=) is not wired "
                        "on this listener",
                        "trace_id": trace_id,
                    }
                else:
                    try:
                        payload = await self.trace_tree_provider(trace_id)
                    except Exception as err:  # noqa: BLE001 - introspection must answer
                        log.exception("trace tree provider raised")
                        payload = {"error": repr(err), "trace_id": trace_id}
            elif self.trace_provider is not None:
                payload = self.trace_provider(n)
            else:
                payload = {"error": "no flight recorder wired"}
            body = json.dumps(
                payload, indent=2, default=str
            ).encode() + b"\n"
            return ("200 OK", "application/json; charset=utf-8", body, "")
        return (
            "404 Not Found",
            "text/plain; charset=utf-8",
            b"try GET /metrics\n",
            "",
        )


def instrument(ee, zk, registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Wire a :class:`MetricsRegistry` to the register_plus event surface.

    ``ee`` is the :class:`registrar_tpu.agent.RegistrarEvents` emitter,
    ``zk`` the :class:`registrar_tpu.zk.client.ZKClient`.  Returns the
    registry (creating one when not given).  Call once, before or after
    the initial 'register' event — gauges read live state at scrape time.
    """
    reg = registry if registry is not None else MetricsRegistry()

    registrations = reg.counter(
        "registrar_registrations_total",
        "Registrations completed (initial, health recovery, heartbeat repair)",
    )
    unregistrations = reg.counter(
        "registrar_unregistrations_total",
        "Health-driven deregistrations completed",
    )
    heartbeats = reg.counter(
        "registrar_heartbeats_total",
        "Znode liveness probes by status (ok|failure)",
    )
    transitions = reg.counter(
        "registrar_health_transitions_total",
        "Health-check threshold crossings (to=down|up)",
    )
    errors = reg.counter(
        "registrar_errors_total", "Unexpected errors from any subsystem"
    )
    malformed_frames = reg.counter(
        "registrar_malformed_frames_total",
        "Malformed peer frames rejected at a decode boundary, by surface",
    )
    for surface in malformed_mod.SURFACES:
        # Pre-seed every surface's zero series (the registry convention:
        # alert rate()s must see the series from the first scrape).
        malformed_frames.inc(0, labels={"surface": surface})
    malformed_mod.subscribe(
        lambda surface: malformed_frames.inc(labels={"surface": surface})
    )
    down = reg.gauge(
        "registrar_health_down",
        "1 while the health checker holds this host deregistered",
    )
    znodes = reg.gauge(
        "registrar_znodes_owned", "Znodes this instance maintains"
    )
    connected = reg.gauge(
        "registrar_zk_connected", "1 while the ZooKeeper session is connected"
    )
    uptime = reg.gauge(
        "registrar_uptime_seconds", "Seconds since instrumentation started"
    )
    rebirths = reg.counter(
        "registrar_session_rebirths_total",
        "Fresh ZK sessions established in-process after an expiry "
        "(surviveSessionExpiry)",
    )
    breaker_trips = reg.counter(
        "registrar_rebirth_breaker_trips_total",
        "Session-rebirth circuit breaker trips (fell back to terminal "
        "session expiry)",
    )
    drift = reg.counter(
        "registrar_drift_total",
        "Registration drift detected by the reconciler, by reason",
    )
    drift_repaired = reg.counter(
        "registrar_drift_repaired_total",
        "Registration drift converged by the reconciler, by reason",
    )
    sweeps = reg.counter(
        "registrar_reconcile_sweeps_total", "Reconcile sweeps completed"
    )
    # With tracing on, instrument_tracing (wired FIRST) already owns this
    # family as a real histogram fed from reconcile.sweep spans; the
    # last-value gauge then stands down — including its event handler
    # (a Histogram has no set(), and the span sink is already the data
    # path).  Without it (the default), the gauge renders and updates
    # exactly as before — parity.
    sweep_seconds = reg.get("registrar_reconcile_sweep_seconds")
    if sweep_seconds is None:
        sweep_seconds = reg.gauge(
            "registrar_reconcile_sweep_seconds",
            "Duration of the last reconcile sweep (seconds)",
        )
    sweep_gauge = sweep_seconds if isinstance(sweep_seconds, Gauge) else None
    handoffs = reg.counter(
        "registrar_handoffs_total",
        "Handoff shutdowns: session state persisted, connection "
        "detached with the session (and ephemerals) left alive for a "
        "successor (restart.mode=handoff, ISSUE 5)",
    )
    drains = reg.counter(
        "registrar_drains_total",
        "Drain shutdowns: znodes unregistered cleanly before exit "
        "(restart.mode=drain)",
    )
    resumes = reg.counter(
        "registrar_session_resumes_total",
        "Cross-process session resume attempts by outcome: reattached "
        "(verified in place, zero NO_NODE), repaired (reattached but "
        "drifted; pipeline re-ran), fresh (state unusable or reattach "
        "refused; normal registration)",
    )
    reloads = reg.counter(
        "registrar_config_reloads_total",
        "SIGHUP config reloads by result (applied|noop|failed)",
    )
    watch_events = reg.counter(
        "registrar_watch_events_total",
        "ZooKeeper watch notifications delivered to this client "
        "(the firehose behind cache invalidation and watch re-arm)",
    )
    write_refusals = reg.counter(
        "registrar_write_refusals_total",
        "ZooKeeper writes refused by reason (read_only = the request "
        "reached a read-only minority/quorum-loss member; retried once "
        "the client fails over — ISSUE 10)",
    )
    member_role = reg.gauge(
        "registrar_zk_member_role",
        "Info gauge: 1 for the kind of ensemble member the session is "
        "attached to (role=read_write|read_only|disconnected)",
    )

    start = time.monotonic()
    uptime.set_function(lambda: time.monotonic() - start)
    down.set_function(lambda: 1.0 if ee.down else 0.0)
    znodes.set_function(lambda: float(len(ee.znodes)))
    connected.set_function(lambda: 1.0 if zk.connected else 0.0)

    # Pre-seed every documented label set at 0 so each series exists from
    # the first scrape — a counter appearing only on its first increment
    # breaks rate()/absent() queries, and the unlabeled zero placeholder
    # (render fallback) would otherwise vanish once a labeled sample lands.
    for status in ("ok", "failure"):
        heartbeats.inc(0, labels={"status": status})
    for to in ("down", "up"):
        transitions.inc(0, labels={"to": to})
    for reason in reconcile_mod.REASONS:
        drift.inc(0, labels={"reason": reason})
        drift_repaired.inc(0, labels={"reason": reason})
    for outcome in ("reattached", "repaired", "fresh"):
        resumes.inc(0, labels={"outcome": outcome})
    for result in ("applied", "noop", "failed"):
        reloads.inc(0, labels={"result": result})
    for reason in ("read_only",):
        write_refusals.inc(0, labels={"reason": reason})

    member_roles = ("read_write", "read_only", "disconnected")

    def set_member_role(*_a) -> None:
        if zk.connected:
            role = (
                "read_only"
                if getattr(zk, "read_only", False)
                else "read_write"
            )
        else:
            role = "disconnected"
        for r in member_roles:
            member_role.set(1.0 if r == role else 0.0, labels={"role": r})

    set_member_role()

    def on_sweep(summary) -> None:
        sweeps.inc()
        if sweep_gauge is not None:
            sweep_gauge.set(float(summary.get("duration", 0.0)))

    zk.on("session_reborn", lambda *_a: rebirths.inc())
    zk.on("rebirth_breaker_tripped", lambda *_a: breaker_trips.inc())
    zk.on("watch", lambda *_a: watch_events.inc())
    zk.on(
        "write_refused",
        lambda reason: write_refusals.inc(labels={"reason": reason}),
    )
    zk.on("state", set_member_role)
    ee.on("handoff", lambda *_a: handoffs.inc())
    ee.on("drain", lambda *_a: drains.inc())
    ee.on("resume", lambda outcome: resumes.inc(labels={"outcome": outcome}))
    ee.on(
        "configReload",
        lambda result: reloads.inc(labels={"result": result}),
    )
    ee.on("drift", lambda d: drift.inc(labels={"reason": d.reason}))
    ee.on(
        "driftRepaired",
        lambda d: drift_repaired.inc(labels={"reason": d.reason}),
    )
    ee.on("reconcile", on_sweep)
    ee.on("register", lambda *_a: registrations.inc())
    ee.on("unregister", lambda *_a: unregistrations.inc())
    ee.on("heartbeat", lambda *_a: heartbeats.inc(labels={"status": "ok"}))
    ee.on(
        "heartbeatFailure",
        lambda *_a: heartbeats.inc(labels={"status": "failure"}),
    )
    ee.on("fail", lambda *_a: transitions.inc(labels={"to": "down"}))
    ee.on("ok", lambda *_a: transitions.inc(labels={"to": "up"}))
    ee.on("error", lambda *_a: errors.inc())
    return reg


def instrument_cache(cache, registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Expose a :class:`registrar_tpu.zkcache.ZKCache`'s counters.

    The cache's lookup hot path bumps plain ints in ``cache.stats``;
    the registry reads them at scrape time (``Counter.set_function``),
    so instrumentation adds zero cost to a cached DNS answer.  Every
    series exists from the first scrape (pre-seeded via the same
    scrape-time read — the backing stats start at 0).
    """
    reg = registry if registry is not None else MetricsRegistry()
    stats = cache.stats

    def from_stat(metric, key: str) -> None:
        metric.set_function(lambda: stats[key])

    from_stat(reg.counter(
        "registrar_cache_hits_total",
        "Resolve-cache lookups served from memory",
    ), "hits")
    from_stat(reg.counter(
        "registrar_cache_misses_total",
        "Resolve-cache lookups that needed a live ZooKeeper read",
    ), "misses")
    from_stat(reg.counter(
        "registrar_cache_invalidations_total",
        "Cache entries dropped by a fired one-shot watch",
    ), "invalidations")
    from_stat(reg.counter(
        "registrar_cache_bypasses_total",
        "Lookups served live because the cache was degraded "
        "(session down or watch re-arm failed)",
    ), "bypasses")
    from_stat(reg.counter(
        "registrar_cache_degraded_total",
        "Transitions into degraded (non-authoritative) mode",
    ), "degraded_total")
    from_stat(reg.counter(
        "registrar_cache_stale_serves_total",
        "Degraded-mode lookups answered from bounded-age last-known-good "
        "entries (serve-stale, cache.staleMaxAgeS)",
    ), "stale_serves")
    from_stat(reg.counter(
        "registrar_cache_stale_refusals_total",
        "Degraded-mode lookups that crossed the stale-age bound and "
        "flushed the stale world instead of answering from it",
    ), "stale_refusals")
    from_stat(reg.counter(
        "registrar_cache_evictions_total",
        "Entries evicted by the maxEntries bound",
    ), "evictions")
    reg.counter(
        "registrar_cache_coherence_lag_seconds_total",
        "Sum of observed write-to-invalidation-processed lag (the "
        "window in which a cached answer could still be stale; "
        "divide by _count for the mean)",
    ).set_function(lambda: stats["coherence_lag_ms_total"] / 1000.0)
    from_stat(reg.counter(
        "registrar_cache_coherence_lag_count",
        "Number of coherence-lag observations",
    ), "coherence_lag_count")
    entries = reg.gauge(
        "registrar_cache_entries", "Entries currently cached"
    )
    entries.set_function(lambda: float(cache.entries))
    authoritative = reg.gauge(
        "registrar_cache_authoritative",
        "1 while cached answers are coherence-guaranteed, 0 in "
        "degraded (live-read) mode",
    )
    authoritative.set_function(lambda: 1.0 if cache.authoritative else 0.0)
    lag_last = reg.gauge(
        "registrar_cache_coherence_lag_seconds",
        "Last observed write-to-invalidation-processed lag (seconds)",
    )
    lag_last.set_function(lambda: stats["coherence_lag_ms_last"] / 1000.0)
    return reg


def instrument_slo(harness, registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Expose the availability-SLO harness's probe counters (ISSUE 9).

    ``harness`` is a :class:`registrar_tpu.testing.slo.SLOHarness` (or
    anything with its event surface): ``probe(result)`` fires once per
    availability sample, ``outage(fault, seconds)`` once per attributed
    merged outage window at report time.  Both label sets are
    pre-seeded — results from the two probe verdicts, fault classes
    from the harness's docs/FAULTS.md catalog ids — so every series
    exists from the first scrape (the registry's convention).
    """
    reg = registry if registry is not None else MetricsRegistry()
    probes = reg.counter(
        "registrar_slo_probe_total",
        "Availability probes by result (ok = the live Binder answer "
        "carried the full fleet)",
    )
    for result in ("ok", "fail"):
        probes.inc(0, labels={"result": result})
    outage = reg.counter(
        "registrar_slo_outage_seconds_total",
        "Probe-observed outage seconds by the fault class owning the "
        "merged window (overlapping faults never double-count)",
    )
    for fault in getattr(harness, "fault_ids", ()):
        outage.inc(0, labels={"fault": fault})
    harness.on("probe", lambda result: probes.inc(labels={"result": result}))
    harness.on(
        "outage",
        lambda fault, seconds: outage.inc(seconds, labels={"fault": fault}),
    )
    return reg


def instrument_shards(
    router, registry: Optional[MetricsRegistry] = None
) -> MetricsRegistry:
    """Expose the sharded serve tier's rollup (ISSUE 12).

    ``router`` is a :class:`registrar_tpu.shard.ShardRouter`: its
    ``poll`` event carries each worker's polled status (resolves, cache
    entries), ``respawn`` fires when a crashed worker is detected, and
    ``reshard`` when the ring changes shape.  Per-shard label sets are
    pre-seeded for the router's current shard ids; counters stay
    monotonic across worker crashes because the router banks a dead
    incarnation's totals (``Counter.set_total``).

    The overload-armor families (ISSUE 17) ride the same events:
    ``registrar_shed_total{reason}`` and ``registrar_queue_depth{shard}``
    from the status polls, ``registrar_admitted_resolve_seconds`` from
    the router's ``admitted`` event (one observation per successfully
    relayed resolve).
    """
    reg = registry if registry is not None else MetricsRegistry()
    resolves = reg.counter(
        "registrar_shard_resolves_total",
        "Resolves served, by shard (rolled up from worker status polls; "
        "monotonic across worker respawns)",
    )
    entries = reg.gauge(
        "registrar_shard_entries",
        "Watch-coherent cache entries currently held, by shard",
    )
    up = reg.gauge(
        "registrar_shard_up",
        "1 while the shard's worker process is serving, 0 while it is "
        "dead or respawning",
    )
    respawns = reg.counter(
        "registrar_shard_respawns_total",
        "Worker crashes detected (each is followed by a respawn while "
        "sibling shards keep serving), by shard",
    )
    reshards = reg.counter(
        "registrar_shard_reshards_total",
        "Ring shape changes (SIGHUP shard-count change with warm "
        "handoff)",
    )
    reshards.inc(0)
    relay = reg.histogram(
        "registrar_shard_relay_seconds",
        "Router relay latency by shard (ISSUE 13): one observation per "
        "shard.relay span, client frame in to worker reply out; the "
        "span's forwarded/worker marks split it into router-queue, "
        "socket, and worker time",
    )
    # Overload armor rollup (ISSUE 17).  All three families exist (pre-
    # seeded) whether or not any armor is configured — an un-armored
    # tier legitimately reports zero sheds, and the alert rate() needs
    # the zero series either way.
    from registrar_tpu.shard import SHED_REASONS

    sheds = reg.counter(
        "registrar_shed_total",
        "Requests deliberately rejected by the overload armor, by shed "
        "reason (queue_full = worker admission bound, rate_limited = "
        "the router's per-client token bucket, cold_fill_shed = the "
        "cache's cold-fill concurrency bound, slow_client = a reply "
        "write deadline disconnected a stalled reader); monotonic "
        "across worker respawns",
    )
    for reason in SHED_REASONS:
        sheds.inc(0, labels={"reason": reason})
    queue_depth = reg.gauge(
        "registrar_queue_depth",
        "Resolve requests dispatched and unanswered in the worker, by "
        "shard (the bounded dispatch backlog; at maxQueueDepth new "
        "resolves shed queue_full)",
    )
    admitted = reg.histogram(
        "registrar_admitted_resolve_seconds",
        "Latency of ADMITTED resolves relayed through the router "
        "(shed requests are excluded — this prices exactly the work "
        "the armor let through)",
    )
    admitted.preseed(None)
    # DNS frontend rollup (ISSUE 19).  Families exist (pre-seeded)
    # whether or not serve.dns is configured — an un-DNS'd tier
    # legitimately reports zero queries, and alert rate()s need the
    # zero series either way (the registry's parity stance).
    from registrar_tpu.dnsfront import QTYPE_NAMES, SERVED_QTYPES

    dns_queries = reg.counter(
        "registrar_dns_queries_total",
        "DNS queries answered at the SO_REUSEPORT frontend, by qtype "
        "and rcode (rolled up from worker status polls; monotonic "
        "across worker respawns)",
    )
    for qt in SERVED_QTYPES:
        for rc in ("NOERROR", "NXDOMAIN", "REFUSED", "SERVFAIL"):
            dns_queries.inc(
                0, labels={"qtype": QTYPE_NAMES[qt], "rcode": rc}
            )
    dns_udp = reg.histogram(
        "registrar_dns_udp_seconds",
        "UDP DNS answer latency at the frontend (packet in to sendto), "
        "aggregated across shard workers (Histogram.set_totals from "
        "the polled per-worker ladders; monotonic across respawns)",
    )
    dns_udp.preseed(None)
    dns_hits = reg.counter(
        "registrar_dns_encode_cache_hits_total",
        "Warm answer-encode-cache template hits (the memcpy-path "
        "answers), tier-wide",
    )
    dns_hits.inc(0)
    dns_misses = reg.counter(
        "registrar_dns_encode_cache_misses_total",
        "Answer-encode-cache misses (full resolve + RR render), "
        "tier-wide",
    )
    dns_misses.inc(0)
    dns_invalidations = reg.counter(
        "registrar_dns_encode_cache_invalidations_total",
        "Pre-rendered answer templates dropped by ZKCache watch "
        "events (the coherence mechanism), tier-wide",
    )
    dns_invalidations.inc(0)
    dns_entries = reg.gauge(
        "registrar_dns_encode_cache_entries",
        "Pre-rendered answer templates currently held, tier-wide",
    )
    seeded: set = set()

    def seed(sid) -> None:
        labels = {"shard": str(sid)}
        resolves.inc(0, labels=labels)
        entries.set(0.0, labels=labels)
        up.set(0.0, labels=labels)
        respawns.inc(0, labels=labels)
        relay.preseed(labels)
        queue_depth.set(0.0, labels=labels)
        seeded.add(sid)

    for sid in getattr(router.ring, "shard_ids", ()):
        seed(sid)

    def resync_shards(*_args) -> None:
        # A reshard changes the label-set topology: new shards get
        # pre-seeded series, and a resharded-away shard's GAUGES are
        # dropped (a phantom up/entries frozen at its last value would
        # misreport a nonexistent shard as healthy forever).  Its
        # counters stay — they are history, not health.
        current = set(router.ring.shard_ids)
        for sid in current - seeded:
            seed(sid)
        for sid in seeded - current:
            entries.remove({"shard": str(sid)})
            up.remove({"shard": str(sid)})
            queue_depth.remove({"shard": str(sid)})
            seeded.discard(sid)

    def on_poll(statuses) -> None:
        down = set(router.shards_down())
        for sid in router.ring.shard_ids:
            up.set(0.0 if sid in down else 1.0,
                   labels={"shard": str(sid)})
        for sid, status in statuses:
            labels = {"shard": str(sid)}
            resolves.set_total(
                router.shard_resolves_total(sid), labels=labels
            )
            entries.set(float(status.get("entries", 0)), labels=labels)
            queue_depth.set(
                float(
                    (status.get("overload") or {}).get("queue_depth", 0)
                ),
                labels=labels,
            )
        # Tier-wide shed rollup: router-side rejects plus every slot's
        # banked + live worker counts (set_total keeps it monotonic
        # across respawns, same contract as resolves).
        for reason, count in router.sheds_total().items():
            sheds.set_total(count, labels={"reason": reason})
        # DNS surface rollup (ISSUE 19): the router folds every slot's
        # banked + live front stats; the same monotonic contract.
        rollup = (
            router.dns_rollup() if hasattr(router, "dns_rollup") else None
        )
        if rollup:
            for key, count in (rollup.get("queries") or {}).items():
                qt, _, rc = key.partition(" ")
                dns_queries.set_total(
                    count, labels={"qtype": qt, "rcode": rc}
                )
            udp = rollup.get("udp") or {}
            if udp.get("counts"):
                dns_udp.set_totals(udp["counts"], udp.get("sum", 0.0))
            cache_stats = rollup.get("encode_cache") or {}
            dns_hits.set_total(cache_stats.get("hits", 0))
            dns_misses.set_total(cache_stats.get("misses", 0))
            dns_invalidations.set_total(
                cache_stats.get("invalidations", 0)
            )
            dns_entries.set(float(cache_stats.get("entries", 0)))

    router.on("poll", on_poll)
    router.on("admitted", lambda seconds: admitted.observe(seconds))
    router.on(
        "respawn",
        lambda sid: (
            respawns.inc(labels={"shard": str(sid)}),
            up.set(0.0, labels={"shard": str(sid)}),
        ),
    )
    def on_reshard(_old, _new, _moved) -> None:
        reshards.inc()
        resync_shards()

    router.on("reshard", on_reshard)

    # Feed the relay histogram from the router's shard.relay spans
    # (ISSUE 13).  Resolved once, at instrument time: with tracing off
    # the family still exists pre-seeded (alerts see zero series), it
    # just never observes — the registry's parity stance.
    tracer = trace_mod.tracer_for(router)
    if tracer.enabled:
        shard_labels: Dict[str, Dict[str, str]] = {}

        def on_relay_span(span) -> None:
            if span.name != "shard.relay" or span.duration_s is None:
                return
            sid = str(span.attrs.get("shard"))
            labels = shard_labels.get(sid)
            if labels is None:
                labels = shard_labels[sid] = {"shard": sid}
            relay.observe(span.duration_s, labels=labels)

        tracer.on_span(on_relay_span)
    return reg


#: ZooKeeper op label values pre-seeded for registrar_zk_op_seconds —
#: the requests the daemon's own loops issue, so each series exists from
#: the first scrape (the registry's pre-seeding convention).
ZK_OPS_PRESEEDED = (
    "create", "delete", "exists", "getData", "setData", "getChildren2",
    "sync", "multi",
)


def instrument_tracing(
    tracer, registry: Optional[MetricsRegistry] = None
) -> MetricsRegistry:
    """Feed latency histograms from the span layer (ISSUE 8).

    Subscribes to ``tracer``'s span sink and routes the cataloged span
    names (docs/OBSERVABILITY.md) into Prometheus histograms.  Call
    BEFORE :func:`instrument` on a shared registry: this owns the
    ``registrar_reconcile_sweep_seconds`` family (as a histogram), and
    instrument() then skips its last-value gauge of the same name.

    Only wired when tracing is enabled (the ``observability`` config
    block) — without it, none of these families exist and the metric
    output is byte-identical to pre-tracing builds.
    """
    reg = registry if registry is not None else MetricsRegistry()

    zk_op = reg.histogram(
        "registrar_zk_op_seconds",
        "ZooKeeper request latency (submit to reply dispatched), by op",
    )
    for op in ZK_OPS_PRESEEDED:
        zk_op.preseed({"op": op})
    resolve = reg.histogram(
        "registrar_resolve_seconds",
        "Binder-view resolve latency by source (cached|live)",
    )
    for source in ("cached", "live"):
        resolve.preseed({"source": source})
    health_exec = reg.histogram(
        "registrar_health_exec_seconds",
        "Health-check command execution time",
    )
    health_exec.preseed()
    sweep = reg.histogram(
        "registrar_reconcile_sweep_seconds",
        "Reconcile sweep duration distribution",
    )
    sweep.preseed()

    # Label dicts are interned per distinct value: the sink runs once
    # per finished span on traced hot paths (a cached resolve is ~100µs
    # end to end), and a fresh one-key dict per observation is
    # measurable there.
    op_labels: Dict[str, Dict[str, str]] = {}
    source_labels = {s: {"source": s} for s in ("cached", "live")}

    def on_span(span) -> None:
        if span.duration_s is None:
            return
        name = span.name
        if name == "zk.op":
            op = str(span.attrs.get("op"))
            labels = op_labels.get(op)
            if labels is None:
                labels = op_labels[op] = {"op": op}
            zk_op.observe(span.duration_s, labels=labels)
        elif name == "resolve.query":
            source = str(span.attrs.get("source"))
            labels = source_labels.get(source)
            if labels is None:
                labels = source_labels[source] = {"source": source}
            resolve.observe(span.duration_s, labels=labels)
        elif name == "health.exec":
            health_exec.observe(span.duration_s)
        elif name == "reconcile.sweep":
            sweep.observe(span.duration_s)

    tracer.on_span(on_span)
    return reg
