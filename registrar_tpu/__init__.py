"""registrar_tpu — a from-scratch, idiomatic Python rebuild of
TritonDataCenter/registrar (reference at /root/reference).

Registrar is a service-discovery sidecar: it writes this host's IP/ports
into ZooKeeper ephemeral nodes (consumed by Binder to answer DNS A/SRV
queries), keeps them alive with a heartbeat loop, and optionally runs a
periodic command-based health check that deregisters the host while the
check reports it down.

The reference (~800 LoC of callback-style Node.js; see SURVEY.md) has no
compute path of any kind, so this rebuild targets *capability* parity: the
ZooKeeper data contract is preserved byte-for-byte (reference
lib/register.js:141-159 and README.md "ZooKeeper data format"), the
operational timing constants are identical (BASELINE.md), and the known
reference bugs that do not affect the wire contract are fixed.

Layer map (mirrors SURVEY.md §1):

    main.py      CLI/daemon mainline                  (ref main.js)
    agent.py     register_plus orchestrator           (ref lib/index.js)
    registration.py  znode registration pipeline      (ref lib/register.js)
    health.py    periodic command health checker      (ref lib/health.js)
    zk/          ZooKeeper client, written from scratch against the
                 public ZooKeeper 3.4 wire protocol   (ref lib/zk.js + zkplus)
    testing/     in-process ZooKeeper server for hermetic tests
                 (the reference's tests need a live ZK at 127.0.0.1:2181;
                 see SURVEY.md §4 — this is the rebuild's main test upgrade)
"""

import importlib

__version__ = "1.0.0"

# Flat re-export surface mirroring the reference's lib/index.js:184-186,
# which re-exports every symbol from health/register/zk alongside the
# default register_plus export.  Lazy so that subsets of the package can be
# imported without pulling in the whole stack.
_EXPORTS = {
    "register_plus": "registrar_tpu.agent",
    "RegistrarEvents": "registrar_tpu.agent",
    "create_health_check": "registrar_tpu.health",
    "HealthCheck": "registrar_tpu.health",
    "domain_to_path": "registrar_tpu.records",
    "host_record": "registrar_tpu.records",
    "service_record": "registrar_tpu.records",
    "default_address": "registrar_tpu.records",
    "HOST_RECORD_TYPES": "registrar_tpu.records",
    "register": "registrar_tpu.registration",
    "unregister": "registrar_tpu.registration",
    "ZKClient": "registrar_tpu.zk.client",
    "create_zk_client": "registrar_tpu.zk.client",
    "Op": "registrar_tpu.zk.client",
    "MultiError": "registrar_tpu.zk.client",
    # extensions beyond the reference surface
    "MetricsRegistry": "registrar_tpu.metrics",
    "MetricsServer": "registrar_tpu.metrics",
    "instrument": "registrar_tpu.metrics",
    "instrument_cache": "registrar_tpu.metrics",
    "resolve": "registrar_tpu.binderview",
    "ZKCache": "registrar_tpu.zkcache",
}


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module 'registrar_tpu' has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)

__all__ = [
    "register_plus",
    "RegistrarEvents",
    "create_health_check",
    "HealthCheck",
    "domain_to_path",
    "host_record",
    "service_record",
    "default_address",
    "HOST_RECORD_TYPES",
    "register",
    "unregister",
    "ZKClient",
    "create_zk_client",
    "Op",
    "MultiError",
    "MetricsRegistry",
    "MetricsServer",
    "instrument",
    "instrument_cache",
    "resolve",
    "ZKCache",
    "__version__",
]
