"""Periodic command-based health checking.

Rebuild of reference lib/health.js:22-148: every ``interval`` seconds run a
shell command with a ``timeout`` (SIGTERM to the command's whole process
group on expiry — the shell runs in its own session so grandchildren
can't outlive the kill — 1 MiB output cap); a
check fails on non-zero exit (unless ``ignore_exit_status``) or when stdout
fails an optional regex match.  Failures accumulate; at ``threshold``
failures within the sliding ``period`` window the service is declared down.

Event surface (mirrors the reference's object-mode stream records,
lib/health.js:77-84,117-120): listeners on ``data`` receive dicts::

    {"type": "ok",   "command": ...}
    {"type": "fail", "command": ..., "err": <Exception>, "failures": <int>,
     "isDown": <bool>, "threshold": <int>}

plus ``end`` when stopped.  Defaults are the reference's exactly
(BASELINE.md): interval 60 s, exec timeout 1 s, threshold 5, period 300 s.

Deliberate fixes over the reference (its window logic is acknowledged
broken — reference README.md:99-102, HEAD-2282/HEAD-2283; SURVEY.md §7):

  * the failure window really slides: failures older than ``period`` are
    pruned on every check, instead of one timer wiping the list at odd
    times (reference lib/health.js:60-64,130);
  * a successful check while down clears the down state and the window, so
    one later blip cannot instantly re-trigger isDown (the reference's
    ``down`` latch never resets, lib/health.js:66-68);
  * ``stdout_match.invert`` is implemented (the reference validates it at
    lib/health.js:32-33 but never applies it).
"""

from __future__ import annotations

import asyncio
import logging
import os
import re
import signal
import time
from typing import Any, Dict, List, Mapping, Optional

from registrar_tpu import trace
from registrar_tpu.events import EventEmitter

log = logging.getLogger("registrar_tpu.health")

#: Reference defaults, lib/health.js:43,51,56,58.
DEFAULT_INTERVAL_S = 60.0
DEFAULT_TIMEOUT_S = 1.0
DEFAULT_THRESHOLD = 5
DEFAULT_PERIOD_S = 300.0
MAX_OUTPUT_BYTES = 1024 * 1024  # reference lib/health.js:50 maxBuffer


class HealthCheckError(Exception):
    """A single failed check (non-zero exit, timeout, or stdout mismatch)."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


class DownError(Exception):
    """Threshold failures within the window — the MultiError analog
    (reference lib/health.js:73)."""

    def __init__(self, errors: List[Exception]):
        self.errors = list(errors)
        super().__init__(
            f"{len(self.errors)} consecutive health check failures: "
            + "; ".join(str(e) for e in self.errors)
        )


def _compile_stdout_match(stdout_match: Optional[Mapping[str, Any]]):
    """Compile the reference's ``stdoutMatch{pattern,flags,invert}`` config
    (JS RegExp flags mapped to Python re flags)."""
    if not stdout_match or not stdout_match.get("pattern"):
        return None, False
    flags = 0
    for ch in stdout_match.get("flags") or "":
        if ch == "i":
            flags |= re.IGNORECASE
        elif ch == "m":
            flags |= re.MULTILINE
        elif ch == "s":
            flags |= re.DOTALL
        elif ch in ("g", "u", "y"):
            pass  # stateful/unicode JS flags: no Python equivalent needed
        else:
            raise ValueError(f"unsupported stdoutMatch flag: {ch!r}")
    return re.compile(stdout_match["pattern"], flags), bool(
        stdout_match.get("invert")
    )


class HealthCheck(EventEmitter):
    """Periodic checker; see module docstring for the event surface."""

    def __init__(
        self,
        command: str,
        interval: float = DEFAULT_INTERVAL_S,
        timeout: float = DEFAULT_TIMEOUT_S,
        threshold: int = DEFAULT_THRESHOLD,
        period: float = DEFAULT_PERIOD_S,
        ignore_exit_status: bool = False,
        stdout_match: Optional[Mapping[str, Any]] = None,
    ):
        super().__init__()
        if not isinstance(command, str) or not command:
            raise ValueError("command must be a non-empty string")
        for name, val in (
            ("interval", interval), ("timeout", timeout), ("period", period),
        ):
            if not isinstance(val, (int, float)) or val <= 0:
                raise ValueError(f"{name} must be a positive number")
        if not isinstance(threshold, int) or threshold < 1:
            raise ValueError("threshold must be a positive integer")
        self.command = command
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.threshold = threshold
        self.period = float(period)
        self.ignore_exit_status = bool(ignore_exit_status)
        self._regex, self._invert = _compile_stdout_match(stdout_match)

        self._fails: List[tuple] = []  # (monotonic_ts, HealthCheckError)
        self._down = False
        self._task: Optional[asyncio.Task] = None
        self._running = False
        #: per-instance tracer override (ISSUE 8); None = process default
        self.tracer = None

    @property
    def is_down(self) -> bool:
        return self._down

    @property
    def running(self) -> bool:
        return self._running

    def start(self) -> "HealthCheck":
        if not self._running:
            self._running = True
            self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.emit("end")

    #: Crash-restart backoff bounds for the check loop (below).
    CRASH_BACKOFF_INITIAL_S = 1.0
    CRASH_BACKOFF_MAX_S = 60.0

    async def _loop(self) -> None:
        # An unexpected exception must never silently end health checking
        # while the host stays registered — that would disable the exact
        # protection the checker exists to provide (round-4 verdict).  A
        # crash is surfaced on ``error``, *counted as a failed check* (so
        # repeated crashes cross the threshold and deregister the host
        # through the normal fail path), and the loop restarts with
        # exponential backoff.
        backoff = self.CRASH_BACKOFF_INITIAL_S
        while self._running:
            try:
                await self.check_once()
                backoff = self.CRASH_BACKOFF_INITIAL_S
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001
                log.exception("health check crashed; restarting in %gs", backoff)
                self.emit("error", err)
                record = self._mark_down(
                    HealthCheckError(f"health check crashed: {err!r}")
                )
                self.emit("data", record)
                if not self._running:
                    return
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self.CRASH_BACKOFF_MAX_S)
                continue
            if not self._running:
                return
            await asyncio.sleep(self.interval)

    async def check_once(self) -> Dict[str, Any]:
        """Run one check and emit its ``data`` record (also returned)."""
        with trace.tracer_for(self).span(
            "health.exec", command=self.command
        ) as sp:
            env = None
            if sp.trace_id is not None:
                # Stamp the subprocess with the active trace (ISSUE 13):
                # a check command that logs $REGISTRAR_TRACE_ID makes
                # its own shell output joinable to the health.exec span
                # — the same ids the shard wire extension carries, so a
                # health-driven deregistration's whole causal chain
                # greps by one token.  With tracing off, env is None
                # and the child inherits the parent environment
                # untouched (parity).
                env = dict(os.environ)
                env["REGISTRAR_TRACE_ID"] = sp.trace_id
                env["REGISTRAR_SPAN_ID"] = sp.span_id
            err = await self._run_command(env)
            if err is not None:
                sp.set_attr("failed", str(err))
        if err is None:
            record = self._mark_ok()
        else:
            record = self._mark_down(err)
        self.emit("data", record)
        return record

    async def _run_command(
        self, env: Optional[Dict[str, str]] = None
    ) -> Optional[HealthCheckError]:
        log.debug("check: running %s", self.command)
        try:
            proc = await asyncio.create_subprocess_shell(
                self.command,
                env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
                # Own process group: the shell routinely spawns
                # grandchildren (pipelines, `curl | grep`, & chains) that
                # inherit it, so the timeout kill below can take out the
                # WHOLE group — killing only the shell leaks any child
                # that outlives it (and a signal-ignoring child used to
                # survive every escalation while holding our pipes open).
                start_new_session=hasattr(os, "killpg"),
            )
        except OSError as e:
            return HealthCheckError(f"{self.command} failed to spawn: {e}")
        try:
            stdout, exceeded = await asyncio.wait_for(
                self._drain_capped(proc), timeout=self.timeout
            )
        except asyncio.CancelledError:
            # stop() mid-check: don't orphan the child process.  The
            # bounded reap also closes the pipe transports explicitly —
            # their fds would otherwise stay registered until a pipe EOF
            # that never comes while a signal-ignoring grandchild holds
            # the inherited write ends (the wait itself is NOT the
            # hazard: asyncio resolves wait() when the child watcher
            # reaps the shell, independent of the pipes).
            await self._force_reap(proc)
            raise
        except asyncio.TimeoutError:
            # SIGTERM to the whole process group, matching the
            # reference's killSignal (lib/health.js:48); escalate if it
            # lingers.  Drain the pipes so their transports are closed
            # and the child isn't wedged on a full pipe; after the grace
            # period escalate to the bounded group-SIGKILL reap (the
            # pipes may be held open by a signal-ignoring grandchild —
            # which the group KILL now reaps too).
            self._kill_group(proc, signal.SIGTERM)
            try:
                await asyncio.wait_for(self._drain(proc), timeout=1.0)
            except asyncio.TimeoutError:
                await self._force_reap(proc)
            return HealthCheckError(
                f"{self.command} timed out after {self.timeout}s"
            )

        if exceeded:
            return HealthCheckError(f"{self.command} exceeded output limit")
        if proc.returncode != 0 and not self.ignore_exit_status:
            return HealthCheckError(
                f"{self.command} exited {proc.returncode}", code=proc.returncode
            )
        if self._regex is not None:
            text = stdout.decode("utf-8", errors="replace")
            matched = self._regex.search(text) is not None
            if matched == self._invert:  # invert=False: fail when no match
                return HealthCheckError(
                    f"stdout match ({self._regex.pattern}) failed", code=-1
                )
        return None

    @staticmethod
    def _kill_group(proc, sig) -> None:
        """Signal the child's whole process group, shell included.

        The shell is spawned with ``start_new_session=True``, so its pid
        doubles as the group id and every grandchild it forked (that did
        not setsid itself) is in the group — ``os.killpg`` reaches the
        processes a shell-only ``terminate()``/``kill()`` leaks.  Falls
        back to signalling the shell alone when the group is already
        gone, or on platforms without process groups."""
        if hasattr(os, "killpg"):
            try:
                os.killpg(proc.pid, sig)
                return
            except ProcessLookupError:
                return  # whole group already exited
            except (PermissionError, OSError):
                pass  # e.g. pid is not a group leader: fall through
        try:
            if sig == getattr(signal, "SIGKILL", None):
                proc.kill()
            else:
                proc.terminate()
        except ProcessLookupError:
            pass

    async def _force_reap(self, proc) -> None:
        """Group SIGKILL, reap (bounded), and close the pipe transports.

        The ONE copy of the reap escalation (both the timeout and
        cancellation paths end here).  ``wait()`` resolves when the
        child watcher reaps the killed shell — asyncio sets the exit
        waiters in ``_process_exited``, with pipe EOF playing no part —
        so the 1 s bound only guards against a wedged/absent watcher.
        The explicit transport close matters separately: the pipe
        read-transports stay registered until EOF, which never comes
        while a signal-ignoring grandchild holds the inherited write
        ends — without it their open fds linger for the garbage
        collector.  ``_transport`` is asyncio private API, so its
        absence (a future internals change) degrades to skipping the
        close rather than crashing the reap path."""
        self._kill_group(proc, getattr(signal, "SIGKILL", signal.SIGTERM))
        transport = getattr(proc, "_transport", None)
        try:
            await asyncio.wait_for(proc.wait(), timeout=1.0)
        except asyncio.TimeoutError:
            # The watcher did not reap within the bound — wedged watcher
            # or dead watcher thread.  Close the pipe transports (when
            # the private API still exposes them) and give the reap one
            # more BOUNDED chance: transport.close() frees fds but only
            # _process_exited resolves the exit waiters, so an unbounded
            # second wait() could hang stop() forever in exactly the
            # wedged-watcher case this timeout exists for.  The child is
            # already SIGKILLed; abandoning leaves at worst a zombie.
            if transport is not None:
                transport.close()
            try:
                await asyncio.wait_for(proc.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                log.warning(
                    "health check child not reaped after SIGKILL; abandoning"
                )
        else:
            if transport is not None:
                transport.close()  # idempotent

    async def _drain_capped(self, proc) -> "tuple[bytes, bool]":
        """Read the child's output to EOF with the reference's *streaming*
        output cap (exec maxBuffer, lib/health.js:45-52): the child is
        SIGTERMed the moment stdout or stderr crosses MAX_OUTPUT_BYTES,
        and at most the cap is ever retained in memory — a fast-writing
        runaway command cannot balloon the daemon's RSS while the timeout
        window runs.  Returns (stdout up to the cap, exceeded?)."""
        exceeded = False

        async def read(stream, keep: bool) -> bytes:
            nonlocal exceeded
            chunks: List[bytes] = []
            total = 0
            while True:
                chunk = await stream.read(65536)
                if not chunk:
                    return b"".join(chunks)
                before, total = total, total + len(chunk)
                if total > MAX_OUTPUT_BYTES:
                    if not exceeded:
                        exceeded = True
                        self._kill_group(proc, signal.SIGTERM)
                    # Keep only up to the cap; drain (and discard) the
                    # rest so the pipe reaches EOF and the child can die.
                    if keep and before < MAX_OUTPUT_BYTES:
                        chunks.append(chunk[: MAX_OUTPUT_BYTES - before])
                    continue
                if keep:
                    chunks.append(chunk)

        stdout, _ = await asyncio.gather(
            read(proc.stdout, True), read(proc.stderr, False)
        )
        await proc.wait()
        return stdout, exceeded

    @staticmethod
    async def _drain(proc) -> None:
        """Discard remaining pipe output and reap the child."""

        async def sink(stream) -> None:
            while await stream.read(65536):
                pass

        await asyncio.gather(sink(proc.stdout), sink(proc.stderr))
        await proc.wait()

    def _mark_ok(self) -> Dict[str, Any]:
        log.debug("healthCheck: %s ok", self.command)
        if self._down or self._fails:
            # Recovery clears the window (fix over the reference's
            # never-resetting down latch, see module docstring).
            self._down = False
            self._fails.clear()
        return {"type": "ok", "command": self.command}

    def _mark_down(self, err: HealthCheckError) -> Dict[str, Any]:
        log.debug("check: %s failed: %s", self.command, err)
        now = time.monotonic()
        cutoff = now - self.period
        self._fails = [(ts, e) for ts, e in self._fails if ts >= cutoff]
        self._fails.append((now, err))
        out_err: Exception = err
        if not self._down and len(self._fails) >= self.threshold:
            self._down = True
            out_err = DownError([e for _, e in self._fails])
        return {
            "type": "fail",
            "command": self.command,
            "err": out_err,
            "failures": len(self._fails),
            "isDown": self._down,
            "threshold": self.threshold,
        }


def create_health_check(
    command: Optional[str] = None, **options: Any
) -> HealthCheck:
    """Factory mirroring the reference's createHealthCheck(options)
    (lib/health.js:22).  Accepts either snake_case kwargs or a config-shaped
    mapping with the reference's camelCase keys::

        create_health_check(command="...", interval=5, threshold=3)
        create_health_check(**{"command": "...", "ignoreExitStatus": True,
                               "stdoutMatch": {"pattern": "ok"}})
    """
    rename = {
        "ignoreExitStatus": "ignore_exit_status",
        "stdoutMatch": "stdout_match",
    }
    kwargs = {rename.get(k, k): v for k, v in options.items()}
    # The reference's interval/timeout/period are milliseconds; the Python
    # surface is seconds.  Config-file translation happens in config.py.
    return HealthCheck(command=command, **kwargs)
