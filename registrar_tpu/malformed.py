"""Malformed-peer-frame accounting — the decode boundary's tally.

Every decode surface rejects hostile or corrupt input by raising its
contract error (``JuteError``, ``ConnectionError``, ``ShardError`` —
docs/FAULTS.md), and generation 5 of the checker proves the bound
checks behind those rejections.  What the contract errors do NOT give
an operator is a rate: a peer spraying garbage at the shard socket
shows up only as connection churn in the logs.  This module is the
zero-dependency tally the decode modules can afford to import (they
sit below metrics.py in the layering):

  * :func:`note` is called at each decode-REJECT site — the exact
    statements that raise on a bad length/count/frame;
  * ``instrument()`` (metrics.py) subscribes a
    ``registrar_malformed_frames_total{surface}`` counter, pre-seeded
    per surface so the alert rate() sees a zero series from the first
    scrape (docs/OPERATIONS.md).

An "unknown op" on a well-formed shard frame is deliberately NOT noted
— the frame decoded fine; version skew is not an attack signal.
"""

from __future__ import annotations

from typing import Callable, Dict, List

#: One label value per decode surface: jute deserialization, the ZK
#: client/server frame buffer, the ZK client handshake, the shard
#: router/worker wire protocol, the DNS frontend's packet codec.
SURFACES = ("jute", "zk_framing", "zk_client", "shard", "dns")

_counts: Dict[str, int] = {surface: 0 for surface in SURFACES}
_subscribers: List[Callable[[str], None]] = []


def note(surface: str) -> None:
    """Record one rejected frame/field on ``surface``.  Total: an
    unknown surface is ignored rather than raised — this sits on error
    paths that must stay on their contract-exception rails, and a raise
    here would turn a counting typo into a dead handler task (the tests
    pin the SURFACES vocabulary instead)."""
    if surface in _counts:
        _counts[surface] += 1
        for fn in list(_subscribers):
            fn(surface)


def counts() -> Dict[str, int]:
    """Snapshot of per-surface reject counts (process lifetime)."""
    return dict(_counts)


def subscribe(fn: Callable[[str], None]) -> Callable[[], None]:
    """Call ``fn(surface)`` on every future :func:`note`; returns the
    unsubscribe callable (tests pair them to stay isolated)."""
    _subscribers.append(fn)

    def unsubscribe() -> None:
        if fn in _subscribers:
            _subscribers.remove(fn)

    return unsubscribe
