"""Watch-coherent in-memory cache of znode data + children (ISSUE 4).

The whole point of registrar is feeding Binder, the DNS server — and a
DNS answer that costs 2–3 live ZooKeeper round trips is capped at wire
latency no matter how fast the wire stack gets (docs/PERF.md).  The real
Binder fronts ZooKeeper with a zkplus watch-backed cache for exactly
this reason; :class:`ZKCache` is that layer for the rebuild.

It duck-types the two read calls the Binder-view resolver uses —
:meth:`read_node` and :meth:`get_many` — so ``binderview.resolve``
works identically over a :class:`~registrar_tpu.zk.client.ZKClient`
(live reads) or a :class:`ZKCache` (memory), and a warm cached resolve
touches the server zero times.

Coherence model (docs/DESIGN.md "Watch-coherent resolve cache"):

  * every fill arms one-shot data/child watches with the read itself
    (``read_node(watch=True)`` / ``get_many(watch=True)``), so there is
    no arm-then-read window in which a write can slip through unseen;
  * a fired watch **drops** the entry before the next lookup can see it
    (events dispatch synchronously from the client's read loop); the
    next lookup is a live read that re-fills and re-arms.  Staleness is
    therefore bounded by watch delivery latency — the same bound the
    real Binder rides;
  * NO_NODE is cached negatively **with an exists-watch armed**, so an
    absent domain is answered from memory (no stampede on the server)
    and its creation invalidates the negative entry;
  * per-entry **generation counters**: a fill snapshots the entry's
    generation before its first RPC and stores only if the generation
    is unchanged after the replies arrive — an invalidation that races
    a refill can never be overwritten by the stale in-flight answer;
  * **degraded mode**: whenever the session is down, terminally
    expired, or a reconnect's watch re-arm failed (the client's
    ``watch_rearm_failed`` event), the cache flushes and turns
    non-authoritative — every lookup falls through to a live read until
    the next clean connect.  A reconnect (including a
    ``surviveSessionExpiry`` rebirth) resumes *cold but authoritative*:
    entries were flushed, and each refill arms fresh watches on the new
    connection, so nothing cached can predate the session boundary;
  * **stale-while-revalidate** (ISSUE 20, opt-in ``stale_max_age_s``,
    config ``cache.staleMaxAgeS``): the RFC 8767 serve-stale stance the
    DNS frontend and shard tier already take, promoted into the core
    cache.  Instead of flushing on a session drop, last-known-good
    entries keep answering for a bounded window — a backend blip or
    election is not a resolve outage for names whose data never changed
    — while the client's reconnect machinery IS the revalidation.  Past
    the bound the whole stale world is flushed and lookups fail
    truthfully; restoring authority flushes too (the invalidations
    missed while dark make every retained entry unprovable), and a
    terminal session expiry always flushes, so a rebirth can never
    resurrect a stale answer.  Default None: flush-on-degrade,
    reference-exact.

Single-flight fills: concurrent misses for one path share one in-flight
read, so a cold hot domain costs one RPC burst, not one per waiter.

Used by ``zkcli resolve --cached`` and the long-running ``zkcli
serve-view`` watch loop; benchmarked by bench.py (cached resolve
latency/QPS and the write→cache-visible coherence-lag metric);
instrumented by :func:`registrar_tpu.metrics.instrument_cache`.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Iterable, List, Optional, Tuple

from registrar_tpu import trace
from registrar_tpu.events import EventEmitter
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import Err, EventType, Stat, ZKError

log = logging.getLogger("registrar_tpu.zkcache")

#: invalidation event types whose triggering write stamps the node's own
#: mtime — the only ones a refill can compute a coherence lag from
_DATA_EVENTS = (EventType.NODE_DATA_CHANGED, EventType.NODE_CREATED)

#: default bound on cached entries (docs/CONFIG.md ``cache.maxEntries``);
#: eviction is oldest-inserted-first — a resolve re-fills an evicted
#: entry transparently, so the bound trades memory for hit rate only.
DEFAULT_MAX_ENTRIES = 4096


class CacheOverloadError(Exception):
    """A cold fill was load-shed: ``fill_concurrency`` distinct-path
    fills were already in flight (ISSUE 17).  Deliberate and immediate —
    never a timeout — so the serve tier above can degrade (serve a
    bounded-age stale answer, or fail fast with an explicit shed
    reason) instead of queueing into collapse.  Joiners of an
    ALREADY-in-flight fill are never shed: single-flight sharing is the
    cheap case the bound exists to protect."""


class _Entry:
    """One cached node.  ``data is None`` ⇒ negative (node absent, an
    exists-watch is armed); ``children is None`` ⇒ children unknown (the
    entry was filled by a data-only ``get_many`` burst)."""

    __slots__ = ("data", "stat", "children")

    def __init__(
        self,
        data: Optional[bytes],
        stat: Optional[Stat],
        children: Optional[Tuple[str, ...]],
    ):
        self.data = data
        self.stat = stat
        self.children = children

    @property
    def negative(self) -> bool:
        return self.data is None


class ZKCache(EventEmitter):
    """Watch-invalidated read-through cache over one :class:`ZKClient`.

    Events: ``invalidated`` (path, watch event) after an entry is
    dropped by a fired watch — the ``serve-view`` loop's refresh signal;
    ``degraded`` (reason) / ``restored`` () on authority transitions.

    Not thread-safe (asyncio single-loop, like the client itself).
    """

    def __init__(
        self,
        zk: ZKClient,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        fill_concurrency: Optional[int] = None,
        stale_max_age_s: Optional[float] = None,
    ):
        super().__init__()
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        if fill_concurrency is not None and fill_concurrency < 0:
            raise ValueError("fill_concurrency must be >= 0")
        if stale_max_age_s is not None and stale_max_age_s < 0:
            raise ValueError("stale_max_age_s must be >= 0")
        self._zk = zk
        self.max_entries = max_entries
        #: serve-stale bound in seconds (module docstring).  None =
        #: reference-exact flush-on-degrade; 0 = fail closed (entries
        #: drop the moment authority is lost, like ``staleTtl: 0``).
        self.stale_max_age_s = stale_max_age_s
        #: monotonic stamp of the moment authority was lost with entries
        #: retained (the serve-stale window's start); None while
        #: authoritative or when SWR is off
        self._stale_since: Optional[float] = None
        #: cold-fill stampede bound (ISSUE 17): at most this many
        #: DISTINCT-path read_node fills in flight at once; the next
        #: would-be fill LEADER raises :class:`CacheOverloadError`
        #: instead of queueing (joiners always share).  None = unbounded,
        #: the pre-armor behavior.
        self.fill_concurrency = fill_concurrency
        #: insertion-ordered entry map (dict order drives eviction)
        self._entries: Dict[str, _Entry] = {}
        #: per-path invalidation generation, reset by clear() via _epoch
        self._gens: Dict[str, int] = {}
        #: global epoch folded into every generation snapshot: clear()
        #: bumps it, killing every in-flight store at once
        self._epoch = 0
        #: single-flight read_node fills: path -> future of the result
        self._inflight: Dict[str, asyncio.Future] = {}
        #: paths with a get_many fill in flight (count per path) — kept
        #: so _prune never drops a generation a bulk store still checks
        self._bulk: Dict[str, int] = {}
        #: paths with a registered client watch listener
        self._watched: set = set()
        #: path -> wall time its LAST data-change/creation invalidation
        #: was processed.  Only those refills can compute a coherence
        #: lag (a children-changed/deleted invalidation refills a node
        #: whose data mtime is unrelated to the triggering write), and
        #: the lag is measured to the INVALIDATION, not to the refill —
        #: once the entry is dropped every lookup is live, so the
        #: coherence window closed at the drop, however much later a
        #: query happens to refill the entry.
        self._lag_candidates: Dict[str, float] = {}
        self._session_up = zk.connected
        self._rearm_failed = False
        self._terminal = False
        #: per-instance tracer override (ISSUE 8); None = process default
        self.tracer = None
        self.stats: Dict[str, float] = {
            "hits": 0,
            "misses": 0,
            "fills": 0,
            "invalidations": 0,
            "bypasses": 0,
            "degraded_total": 0,
            "clears": 0,
            "evictions": 0,
            "coherence_lag_ms_last": 0.0,
            "coherence_lag_ms_total": 0.0,
            "coherence_lag_count": 0,
            "fill_sheds": 0,
            "stale_serves": 0,
            "stale_refusals": 0,
        }
        self._was_authoritative = self.authoritative
        zk.on("close", self._on_close)
        zk.on("connect", self._on_connect)
        zk.on("session_expired", self._on_session_expired)
        zk.on("watch_rearm_failed", self._on_rearm_failed)

    # -- authority ----------------------------------------------------------

    @property
    def authoritative(self) -> bool:
        """True while cached answers are coherence-guaranteed.  False ⇒
        every lookup falls through to a live read (module docstring)."""
        return (
            self._session_up and not self._rearm_failed and not self._terminal
        )

    @property
    def entries(self) -> int:
        return len(self._entries)

    def hit_rate(self) -> float:
        total = self.stats["hits"] + self.stats["misses"]
        return self.stats["hits"] / total if total else 0.0

    def _authority_changed(self, reason: str) -> None:
        now = self.authoritative
        if self._was_authoritative and not now:
            self.stats["degraded_total"] += 1
            log.warning("cache degraded (%s): serving live reads", reason)
            self.emit("degraded", reason)
        elif now and not self._was_authoritative:
            log.info("cache authoritative again (%s): cold start", reason)
            self.emit("restored")
        self._was_authoritative = now

    def _on_close(self, *_a) -> None:
        self._session_up = False
        # A fresh connection re-arms per-fill; the previous connection's
        # re-arm verdict is moot once it is gone.
        self._rearm_failed = False
        self._lose_authority()
        self._authority_changed("disconnected")

    def _on_connect(self, *_a) -> None:
        self._session_up = True
        # Cold but authoritative: everything cached before the drop was
        # flushed, and every refill arms fresh watches on THIS
        # connection — unless this connect's batch re-arm failed
        # (watch_rearm_failed fires before the connect event).  With
        # serve-stale this is the revalidation landing: the retained
        # entries are unprovable (their invalidations may have fired
        # while we were dark) and flush here too.
        self.clear()
        self._authority_changed("connected")

    def _on_session_expired(self, *_a) -> None:
        # Terminal expiry (surviveSessionExpiry off, or its breaker
        # tripped): the client is permanently closed; so is authority.
        # ALWAYS flushes — serve-stale never outlives the session's
        # death, so a later rebirth cannot resurrect a stale answer.
        self._terminal = True
        self.clear()
        self._authority_changed("session_expired")

    def _on_rearm_failed(self, *_a) -> None:
        self._rearm_failed = True
        self._lose_authority()
        self._authority_changed("watch_rearm_failed")

    def _lose_authority(self) -> None:
        """Authority lost on a non-terminal path: flush (reference), or
        — with ``stale_max_age_s`` set — open the serve-stale window and
        keep the last-known-good entries for its bounded duration."""
        if self.stale_max_age_s is None or self._terminal:
            self.clear()
            return
        if self._stale_since is None:
            self._stale_since = time.monotonic()

    def _stale_entry(self, path: str) -> Optional[_Entry]:
        """A bounded-age last-known-good entry servable while degraded,
        or None.  Crossing the age bound refuses and flushes the whole
        stale world: past it nothing retained is provable, and lookups
        must fail truthfully instead of answering from history."""
        if self._stale_since is None:
            return None
        entry = self._entries.get(path)
        if entry is None:
            return None
        if time.monotonic() - self._stale_since > self.stale_max_age_s:
            self.stats["stale_refusals"] += 1
            self.clear()
            return None
        return entry

    def clear(self) -> None:
        """Flush every entry and kill every in-flight store (epoch bump)."""
        self._entries.clear()
        self._gens.clear()
        self._lag_candidates.clear()
        self._epoch += 1
        self._stale_since = None
        self.stats["clears"] += 1

    def close(self) -> None:
        """Unhook from the client (listeners + watch bookkeeping)."""
        self._zk.off("close", self._on_close)
        self._zk.off("connect", self._on_connect)
        self._zk.off("session_expired", self._on_session_expired)
        self._zk.off("watch_rearm_failed", self._on_rearm_failed)
        for path in self._watched:
            self._zk.unwatch(path, self._on_event)
        self._watched.clear()
        self.clear()

    # -- invalidation -------------------------------------------------------

    def _gen(self, path: str) -> Tuple[int, int]:
        return (self._epoch, self._gens.get(path, 0))

    def _on_event(self, event) -> None:
        """A one-shot watch fired: drop the entry *now* (this runs
        synchronously from the client's frame dispatch, so no lookup can
        be scheduled between the event and the drop)."""
        path = event.path
        self._gens[path] = self._gens.get(path, 0) + 1
        dropped = self._entries.pop(path, None)
        if dropped is not None:
            self.stats["invalidations"] += 1
            trace.tracer_for(self).event(
                "cache.invalidated", path=path, type=event.type
            )
        if event.type in _DATA_EVENTS:
            self._lag_candidates[path] = time.time()
            # bound the candidate map: a path churned away before any
            # refill consumes its stamp must not leak it forever
            while len(self._lag_candidates) > self.max_entries:
                self._lag_candidates.pop(next(iter(self._lag_candidates)))
        else:
            self._lag_candidates.pop(path, None)
        self.emit("invalidated", path, event)
        self._prune(path)

    def _prune(self, path: str) -> None:
        """Drop per-path bookkeeping once nothing references it: no
        entry, no in-flight fill.  The generation entry must outlive any
        fill that snapshotted it (else a later snapshot would compare
        equal to a pre-bump one and resurrect stale data)."""
        if (
            path not in self._entries
            and path not in self._inflight
            and path not in self._bulk
        ):
            if path in self._watched:
                self._watched.discard(path)
                self._zk.unwatch(path, self._on_event)
            # With no fill in flight, no snapshot of this generation
            # can still be live — a later fill re-reads it (back at 0)
            # only after re-registering the listener, so an
            # invalidation after that bumps to 1 and still wins.
            # Popping here keeps a weeks-long serve-view from leaking
            # one generation per churned-away unique path.  Lag
            # candidates are NOT popped: they must outlive the drop to
            # be consumed by the next refill (bounded in _on_event).
            self._gens.pop(path, None)

    def _ensure_listener(self, path: str) -> None:
        if path not in self._watched:
            self._watched.add(path)
            self._zk.watch(path, self._on_event)

    def _store(
        self, path: str, entry: _Entry, gen: Tuple[int, int]
    ) -> None:
        """Install a filled entry unless its snapshot went stale."""
        if not self.authoritative or gen != self._gen(path):
            return
        # Coherence-lag observation: a refill that follows a DATA
        # invalidation (dataChanged/created — the only events whose
        # triggering write stamps this node's mtime) measures the
        # write→invalidation-processed window off that mtime (same
        # host in the hermetic/bench setup; in production this is an
        # approximation subject to clock skew).  The refill's own
        # timing is deliberately excluded: the stale window closed
        # when the entry was dropped, and a consumer that next queries
        # ten minutes later must not read as ten minutes of lag.
        inval_at = self._lag_candidates.pop(path, None)
        if inval_at is not None and entry.stat is not None:
            lag_ms = max(0.0, inval_at * 1000.0 - entry.stat.mtime)
            self.stats["coherence_lag_ms_last"] = lag_ms
            self.stats["coherence_lag_ms_total"] += lag_ms
            self.stats["coherence_lag_count"] += 1
        self._entries[path] = entry
        self.stats["fills"] += 1
        while len(self._entries) > self.max_entries:
            victim = next(iter(self._entries))
            del self._entries[victim]
            self._gens[victim] = self._gens.get(victim, 0) + 1
            self._zk.forget_watches(victim)
            self.stats["evictions"] += 1
            self._prune(victim)

    # -- the resolver's read surface ----------------------------------------

    async def read_node(
        self, path: str
    ) -> Optional[Tuple[bytes, Stat, List[str]]]:
        """Cached :meth:`ZKClient.read_node`: ``(data, stat, children)``
        or None when absent (served from the negative cache)."""
        if not self.authoritative:
            stale = self._stale_entry(path)
            if stale is not None and (
                stale.negative or stale.children is not None
            ):
                # Serve-stale (ISSUE 20): a bounded-age last-known-good
                # answer through the blip, RFC 8767 style.
                self.stats["stale_serves"] += 1
                if stale.negative:
                    return None
                return (stale.data, stale.stat, list(stale.children))
            self.stats["bypasses"] += 1
            return await self._zk.read_node(path)
        entry = self._entries.get(path)
        if entry is not None and (entry.negative or entry.children is not None):
            self.stats["hits"] += 1
            if entry.negative:
                return None
            return (entry.data, entry.stat, list(entry.children))
        self.stats["misses"] += 1
        return await self._fill_node(path)

    async def get_many(
        self, paths: Iterable[str]
    ) -> List[Optional[Tuple[bytes, Stat]]]:
        """Cached :meth:`ZKClient.get_many`; misses are refilled in one
        pipelined watch-arming burst."""
        paths = list(paths)
        if not self.authoritative:
            if paths and self._stale_since is not None:
                # All-or-nothing: a batch mixing stale entries with live
                # reads would compose an answer no single point in time
                # ever looked like — serve stale only when EVERY path is
                # covered, else fall through whole (and fail truthfully
                # if the backend is dark).
                stale = [self._stale_entry(p) for p in paths]
                if all(e is not None for e in stale):
                    self.stats["stale_serves"] += len(stale)
                    return [
                        None if e.negative else (e.data, e.stat)
                        for e in stale
                    ]
            self.stats["bypasses"] += 1
            return await self._zk.get_many(paths)
        out: List[Optional[Tuple[bytes, Stat]]] = [None] * len(paths)
        misses: List[Tuple[int, str]] = []
        for i, path in enumerate(paths):
            entry = self._entries.get(path)
            if entry is None:
                misses.append((i, path))
            elif entry.negative:
                self.stats["hits"] += 1
            else:
                self.stats["hits"] += 1
                out[i] = (entry.data, entry.stat)
        if not misses:
            return out
        self.stats["misses"] += len(misses)
        gens = []
        for _i, path in misses:
            self._ensure_listener(path)
            gens.append(self._gen(path))
            self._bulk[path] = self._bulk.get(path, 0) + 1
        try:
            with trace.tracer_for(self).span(
                "cache.fill", kind="bulk", count=len(misses)
            ):
                results = await self._zk.get_many(
                    (path for _i, path in misses), watch=True
                )
            for (i, path), gen, res in zip(misses, gens, results):
                out[i] = res
                if res is not None:
                    # A None (NO_NODE) result is returned uncached:
                    # getData leaves no watch on an absent node, and the
                    # parent's child watch already covers the churn that
                    # produced it.
                    self._store(path, _Entry(res[0], res[1], None), gen)
        finally:
            # AFTER the stores: _prune unregisters the invalidation
            # listener for paths that ended up with no entry — pruning
            # before storing would strip every freshly filled entry of
            # its coherence signal.
            for _i, path in misses:
                left = self._bulk.get(path, 0) - 1
                if left <= 0:
                    self._bulk.pop(path, None)
                    self._prune(path)
                else:
                    self._bulk[path] = left
        return out

    # -- fills --------------------------------------------------------------

    async def _fill_node(self, path: str):
        """Single-flight read_node fill: concurrent misses share one
        in-flight load; a cancelled leader hands leadership to the next
        waiter instead of failing the whole queue."""
        while True:
            fut = self._inflight.get(path)
            if fut is None:
                break
            try:
                return await asyncio.shield(fut)
            except asyncio.CancelledError:
                if fut.cancelled():
                    continue  # leader died; take over
                raise
        if (
            self.fill_concurrency is not None
            and len(self._inflight) >= self.fill_concurrency
        ):
            # Cold-fill stampede shed (class CacheOverloadError): this
            # would be a NEW fill leader beyond the bound.  Checked
            # after the join loop on purpose — a request for a path
            # already being filled rides the existing future for free.
            self.stats["fill_sheds"] += 1
            raise CacheOverloadError(
                f"cold-fill concurrency bound reached "
                f"({len(self._inflight)} >= {self.fill_concurrency})"
            )
        fut = asyncio.get_running_loop().create_future()
        self._inflight[path] = fut
        try:
            result = await self._load_node(path)
        except BaseException as err:
            if isinstance(err, asyncio.CancelledError):
                fut.cancel()
            else:
                fut.set_exception(err)
                fut.exception()  # mark retrieved: no waiter is guaranteed
            raise
        else:
            fut.set_result(result)
            return result
        finally:
            self._inflight.pop(path, None)
            self._prune(path)

    async def _load_node(self, path: str):
        with trace.tracer_for(self).span("cache.fill", path=path):
            return await self._load_node_inner(path)

    async def _load_node_inner(self, path: str):
        gen = self._gen(path)
        self._ensure_listener(path)
        node = await self._zk.read_node(path, watch=True)
        while node is None:
            # Negative caching: getData leaves no watch on NO_NODE, so
            # arm an exists-watch — the node's creation then invalidates
            # the negative entry.  A creation racing in between makes
            # the stat succeed; loop back to a real watched read.
            try:
                await self._zk.stat(path, watch=True)
            except ZKError as err:
                if err.code != Err.NO_NODE:
                    raise
                self._store(path, _Entry(None, None, ()), gen)
                return None
            node = await self._zk.read_node(path, watch=True)
        data, stat, children = node
        self._store(path, _Entry(data, stat, tuple(children)), gen)
        return (data, stat, children)
