"""Availability-SLO fleet simulator: measure the nines, not just convergence.

Every robustness layer in this tree (netem toxics, session rebirth,
handoff restarts, reconcile repair) is proven by *eventual convergence*
tests; an operator's question is "how many nines, and how fast do we
recover per fault class?"  This module (ISSUE 9 tentpole, ROADMAP item
5) turns the accumulated fault machinery into a measured availability
envelope:

  * **Fleet** — N in-process registrars (the tests/test_soak.py fleet
    shape: one :class:`~registrar_tpu.zk.client.ZKClient` per member
    against one :class:`~registrar_tpu.testing.server.ZKServer`, or —
    ``ensemble=`` > 1, ISSUE 10 — a quorum
    :class:`~registrar_tpu.testing.server.ZKEnsemble` with real leader
    elections), each member connected through its own per-backend
    :class:`~registrar_tpu.testing.netem.ChaosProxy` so member network
    faults and ensemble faults compose.
  * **Prober** — a continuously-polling resolver samples the Binder
    answer at a fixed cadence over BOTH read paths: live
    (:func:`registrar_tpu.binderview.resolve` against a direct client)
    and cached (through :class:`~registrar_tpu.zkcache.ZKCache`).  A
    probe is **ok** iff the live answer carries every member of the
    fleet; the cached answer is additionally compared against the live
    one to count **stale** serves.  Each probe runs inside an
    ``slo.probe`` span carrying the active scenario/fault marks
    (:func:`registrar_tpu.trace.annotate`), so a failing probe's trace
    id points straight into the flight recorder.
  * **Scenarios** — seeded, named churn traces keyed to the
    docs/FAULTS.md fault-class catalog (``id:`` rows; checklib's
    ``fault-id-drift`` rule diffs the two): deploy waves (drain +
    re-register), crash loops (session force-expired with a
    SIGKILL-shaped stale handoff state — the successor's seeded resume
    is refused and it registers fresh), health-check flaps, expiry
    storms, and per-member netem blackhole episodes long enough to
    expire the session.
  * **SLO math** — pure functions over the probe timeline (no fleet
    needed; unit-tested in tests/test_slo.py): availability and nines,
    outage-window extraction and merging (overlapping faults never
    double-count downtime), and per-fault MTTD/MTTR attribution keyed
    to the injection timestamps.

The runner (``tools/slo.py``, ``make slo`` / ``make slo-quick``) drives
a trace, writes ``slo-report.json``, and gates the quick trace against
``SLO_BASELINE.json`` exactly the way bench.py gates perf — floors
pinned from the append-only ``SLO_HISTORY.json``, regressions fail.

Metrics: :func:`registrar_tpu.metrics.instrument_slo` exposes
``registrar_slo_probe_total{result}`` and
``registrar_slo_outage_seconds_total{fault}`` from the harness's event
surface (``probe`` per sample, ``outage`` per attributed window).
"""

from __future__ import annotations

import asyncio
import logging
import math
import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

from registrar_tpu import binderview, traceview
from registrar_tpu import metrics as metrics_mod
from registrar_tpu import trace as trace_mod
from registrar_tpu.events import EventEmitter, spawn_owned
from registrar_tpu.registration import register, unregister
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.testing.netem import DOWN, UP, Blackhole, ChaosProxy, proxy_fleet
from registrar_tpu.testing.server import ZKEnsemble, ZKServer
from registrar_tpu.zk.client import SessionExpiredError, ZKClient
from registrar_tpu.zkcache import ZKCache

log = logging.getLogger("registrar_tpu.testing.slo")

#: The fault-class catalog (docs/FAULTS.md "Fault classes", the ``id:``
#: column).  Every scenario injects through :meth:`SLOHarness.inject`
#: with one of these literals; checklib's ``fault-id-drift`` rule diffs
#: the injection sites against the doc table in both directions.
FAULT_IDS = (
    "deploy-wave",
    "crash-loop",
    "health-flap",
    "expiry-storm",
    "netem-episode",
    # ensemble fault classes (ISSUE 10; need ensemble= > 1)
    "leader-kill",
    "quorum-loss",
    "rolling-upgrade",
    "partition-minority",
    # sharded serve tier fault classes (ISSUE 12; need shards= > 0)
    "shard-kill",
    "reshard-wave",
    # overload armor (ISSUE 17; need shards= > 0)
    "overload-storm",
)

#: nines(1.0) would be infinite; the cap keeps a flawless short trace
#: reportable (and honest: a 5 s trace cannot demonstrate nine nines).
MAX_NINES = 9.0


# ---------------------------------------------------------------------------
# SLO math: pure functions over probe timelines (unit-tested, no fleet)
# ---------------------------------------------------------------------------


class Probe:
    """One availability sample: ``t`` (harness clock, seconds), ``ok``
    (the live answer carried the full fleet), ``missing`` (how many
    members the answer lacked), and the probe span's ``trace_id`` (the
    flight-recorder pointer for a failing sample)."""

    __slots__ = ("t", "ok", "missing", "trace_id")

    def __init__(
        self,
        t: float,
        ok: bool,
        missing: int = 0,
        trace_id: Optional[str] = None,
    ):
        self.t = t
        self.ok = ok
        self.missing = missing
        self.trace_id = trace_id

    def __repr__(self) -> str:
        state = "ok" if self.ok else f"fail(-{self.missing})"
        return f"Probe({self.t:.3f}, {state})"


class FaultEvent:
    """One injected fault: identity (catalog ``fault`` id + member),
    its injection/clear stamps, and the probe-derived verdicts filled in
    by :func:`attribute` — ``detected_at`` (first failing probe at or
    after injection) and ``recovered_at`` (first ok probe after
    detection).  MTTD/MTTR are both measured **from injection**, the
    operator's clock."""

    __slots__ = (
        "fault", "member", "injected_at", "cleared_at",
        "detected_at", "recovered_at",
    )

    def __init__(self, fault: str, member: Optional[int], injected_at: float):
        self.fault = fault
        self.member = member
        self.injected_at = injected_at
        self.cleared_at: Optional[float] = None
        self.detected_at: Optional[float] = None
        self.recovered_at: Optional[float] = None

    @property
    def mttd_s(self) -> Optional[float]:
        if self.detected_at is None:
            return None
        return self.detected_at - self.injected_at

    @property
    def mttr_s(self) -> Optional[float]:
        if self.recovered_at is None:
            return None
        return self.recovered_at - self.injected_at

    def __repr__(self) -> str:
        return (
            f"FaultEvent({self.fault!r}, member={self.member}, "
            f"injected_at={self.injected_at:.3f})"
        )


def availability(probes: Sequence[Probe]) -> float:
    """Fraction of ok probes.  Raises on an empty timeline — a prober
    that never sampled must read as a broken run, not as 100%."""
    if not probes:
        raise ValueError("no probes: availability is unmeasured, not 1.0")
    ok = sum(1 for p in probes if p.ok)
    return ok / len(probes)


def nines(avail: float) -> float:
    """Availability as "nines": 0.999 -> 3.0, capped at MAX_NINES."""
    if not 0.0 <= avail <= 1.0:
        raise ValueError("availability must be within [0, 1]")
    if avail >= 1.0:
        return MAX_NINES
    return min(max(0.0, round(-math.log10(1.0 - avail), 3)), MAX_NINES)


def outage_windows(
    probes: Sequence[Probe], end: Optional[float] = None
) -> List[Tuple[float, float]]:
    """Half-open ``(start, end)`` windows where the probe stream saw
    failure: a window opens at the first failing probe and closes at
    the next ok probe.  A trailing failure closes at ``end`` (default:
    the last probe's stamp) — an unrecovered outage still has a
    measurable duration."""
    windows: List[Tuple[float, float]] = []
    start = None
    for p in probes:
        if not p.ok and start is None:
            start = p.t
        elif p.ok and start is not None:
            windows.append((start, p.t))
            start = None
    if start is not None:
        close = end if end is not None else probes[-1].t
        windows.append((start, max(close, start)))
    return windows


def merge_windows(
    windows: Sequence[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """Sort and coalesce overlapping/adjacent windows, so downtime from
    overlapping fault classes is counted once."""
    merged: List[Tuple[float, float]] = []
    for start, end in sorted(windows):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def total_outage_s(windows: Sequence[Tuple[float, float]]) -> float:
    return sum(end - start for start, end in merge_windows(windows))


def attribute(
    faults: Sequence[FaultEvent], probes: Sequence[Probe]
) -> None:
    """Fill ``detected_at``/``recovered_at`` on every fault event from
    the probe timeline.

    Detection is the first failing probe at or after the injection —
    bounded at ``cleared_at`` when the fault was cleared: a fault whose
    whole outage fell between two probe ticks is reported *undetected*
    (shorter than the cadence can observe), never credited with a later
    unrelated scenario's failure.  Recovery is the first ok probe after
    detection.  When two faults overlap, each still gets its own
    MTTD/MTTR measured from its own injection stamp — the later fault
    "detects" immediately (the outage is already observable) and both
    recover at the same ok probe; only the merged-window *downtime* is
    deduplicated (see :func:`window_owner`)."""
    for fault in faults:
        horizon = (
            fault.cleared_at if fault.cleared_at is not None else math.inf
        )
        fault.detected_at = next(
            (
                p.t
                for p in probes
                if fault.injected_at <= p.t <= horizon and not p.ok
            ),
            None,
        )
        if fault.detected_at is not None:
            fault.recovered_at = next(
                (p.t for p in probes if p.t > fault.detected_at and p.ok),
                None,
            )


def window_owner(
    window: Tuple[float, float], faults: Sequence[FaultEvent]
) -> Optional[FaultEvent]:
    """The fault that OWNS a merged outage window: the earliest-injected
    fault whose detection..recovery interval overlaps it.  One window,
    one owner — overlapping fault classes never double-count downtime
    (``registrar_slo_outage_seconds_total`` sums to the merged total)."""
    start, end = window
    owner = None
    for fault in faults:
        if fault.detected_at is None:
            continue
        recovered = (
            fault.recovered_at if fault.recovered_at is not None else end
        )
        if fault.detected_at < end and recovered > start:
            if owner is None or fault.injected_at < owner.injected_at:
                owner = fault
    return owner


def _round_stats(values: List[float]) -> Dict[str, Optional[float]]:
    if not values:
        return {"mean": None, "max": None}
    return {
        "mean": round(sum(values) / len(values), 4),
        "max": round(max(values), 4),
    }


def fault_summary(
    faults: Sequence[FaultEvent],
    probes: Sequence[Probe],
    end: Optional[float] = None,
) -> Tuple[Dict[str, Dict[str, Any]], List[Tuple[float, float]]]:
    """Per-fault-class rollup + the merged outage windows.

    Each class reports its injected/detected counts, MTTD/MTTR mean and
    max (seconds, from injection), and the downtime attributed to the
    windows it owns.  Calls :func:`attribute` on the way."""
    attribute(faults, probes)
    windows = merge_windows(outage_windows(probes, end))
    per: Dict[str, Dict[str, Any]] = {}
    mttds: Dict[str, List[float]] = {}
    mttrs: Dict[str, List[float]] = {}
    for fault in faults:
        entry = per.setdefault(
            fault.fault,
            {"injected": 0, "detected": 0, "outage_s": 0.0},
        )
        entry["injected"] += 1
        if fault.detected_at is not None:
            entry["detected"] += 1
            mttds.setdefault(fault.fault, []).append(fault.mttd_s)
            if fault.mttr_s is not None:
                mttrs.setdefault(fault.fault, []).append(fault.mttr_s)
    for window in windows:
        owner = window_owner(window, faults)
        if owner is not None:
            per[owner.fault]["outage_s"] = round(
                per[owner.fault]["outage_s"] + (window[1] - window[0]), 4
            )
    for fid, entry in per.items():
        stats = _round_stats(mttds.get(fid, []))
        entry["mttd_s_mean"], entry["mttd_s_max"] = stats["mean"], stats["max"]
        stats = _round_stats(mttrs.get(fid, []))
        entry["mttr_s_mean"], entry["mttr_s_max"] = stats["mean"], stats["max"]
    return per, windows


# ---------------------------------------------------------------------------
# The fleet harness
# ---------------------------------------------------------------------------

#: members reconnect fast: the harness measures recovery, and the
#: production-shaped 1-90 s envelope would make every scenario read as
#: its backoff, not its detection bound
_MEMBER_RECONNECT = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.05, max_delay=0.4,
    jitter="decorrelated",
)

#: ISSUE 20 recovery tuning (``levers=True``): with raced connects and
#: the tightened ping schedule doing the detection, the residual
#: reconnect backoff IS the recovery latency — a 10-60 ms decorrelated
#: envelope keeps retry pressure bounded without letting the backoff
#: dominate any MTTR row.
_LEVER_RECONNECT = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.01, max_delay=0.06,
    jitter="decorrelated",
)

#: registration retry for members (re)registering under harness faults:
#: transient failures (CONNECTION_LOSS through a healing proxy, a
#: NOT_READONLY from a minority member, an election-window drop) are the
#: thing being measured — the member must keep trying through them, at
#: harness cadence rather than the production 1-90 s envelope
_REGISTER_RETRY = RetryPolicy(
    max_attempts=80, initial_delay=0.05, max_delay=0.3,
    jitter="decorrelated",
)

#: ISSUE 20: the levered registration retry.  A (re)registration that
#: collides with an election window fails once and then sleeps the
#: backoff — with a 40 ms election, a 50-300 ms draw IS the leader-kill
#: MTTR row, so the levers pull the envelope down to election scale.
_LEVER_REGISTER_RETRY = RetryPolicy(
    max_attempts=200, initial_delay=0.01, max_delay=0.05,
    jitter="decorrelated",
)


class _Member:
    """One fleet member: its proxies (one per ensemble member — a single
    list entry against a standalone server), client, and registration."""

    __slots__ = ("idx", "hostname", "admin_ip", "proxies", "client", "znodes")

    def __init__(self, idx: int, hostname: str, admin_ip: str):
        self.idx = idx
        self.hostname = hostname
        self.admin_ip = admin_ip
        self.proxies: List[ChaosProxy] = []
        self.client: Optional[ZKClient] = None
        self.znodes: List[str] = []

    @property
    def proxy(self) -> Optional[ChaosProxy]:
        return self.proxies[0] if self.proxies else None


class SLOHarness(EventEmitter):
    """Seeded fleet + prober + fault injection (module docstring).

    Events: ``probe(result)`` per sample (``"ok"``/``"fail"``) and
    ``outage(fault, seconds)`` per attributed window at report time —
    :func:`registrar_tpu.metrics.instrument_slo` turns these into the
    ``registrar_slo_*`` counters.

    ``repair=False`` injects every fault but withholds the recovery
    actions (no member ever restarts or re-registers) — the
    deliberately broken run tools/slo.py uses to prove the probe
    actually detects outages (a measurable nines drop).
    """

    def __init__(
        self,
        members: int = 5,
        seed: int = 0,
        probe_interval: float = 0.02,
        session_timeout_ms: int = 800,
        repair: bool = True,
        domain: str = "slo.fleet.us",
        tracer: Optional[trace_mod.Tracer] = None,
        ensemble: int = 1,
        election_ms: float = 150.0,
        shards: int = 0,
        levers: bool = False,
    ):
        """``ensemble`` (ISSUE 10): > 1 runs the fleet against an
        N-member :class:`ZKEnsemble` with a real leader/quorum protocol
        — each fleet member fronts every ensemble member with its own
        ChaosProxy, clients are ``can_be_read_only`` with seeded connect
        order, and the ensemble fault classes (leader-kill, quorum-loss,
        rolling-upgrade, partition-minority) become injectable.
        ``election_ms`` sizes the leader-election window the failover
        MTTR must ride through.

        ``shards`` (ISSUE 12): > 0 additionally runs a sharded serve
        tier (:class:`registrar_tpu.shard.ShardRouter` + worker
        processes) against the same backends and gives the prober a
        third leg: a set of static **slice-probe domains** chosen to
        cover every shard's slice is polled through the tier each
        sample, so a shard's outage (and only that shard's) shows up in
        the availability math.  The shard fault classes (shard-kill,
        reshard-wave) become injectable; with ``repair=False`` the
        router's crash→respawn supervision is withheld (the recovery
        action under test).

        ``levers`` (ISSUE 20): turn on the availability levers this PR
        engineers — raced connects (no serial dead-host scan on
        failover), the tightened ping/dead-after schedule (link death
        detected in ~0.1 s rather than negotiated-timeout fractions),
        stale-while-revalidate in the probe-side :class:`ZKCache`, a
        harness-scale reconnect floor, and spread watch attach across
        the ensemble.  ``False`` (the default) is reference-exact: the
        r19 client/cache behavior, bit for bit — ``tools/slo.py
        --prove-levers`` runs both under one seed and fails unless the
        levers measurably beat the reference."""
        super().__init__()
        if members < 2:
            raise ValueError("a fleet needs at least 2 members")
        self.n_members = members
        self.seed = seed
        self.rng = random.Random(seed)
        self.probe_interval = probe_interval
        self.session_timeout_ms = session_timeout_ms
        self.repair = repair
        self.domain = domain
        self.n_ensemble = ensemble
        self.election_ms = election_ms
        self.levers = levers
        self.fault_ids = FAULT_IDS
        self.tracer = (
            tracer
            if tracer is not None
            else trace_mod.Tracer(sample_rate=1.0, max_spans=8192)
        )
        #: latency histograms fed from the probe spans (the PR-8
        #: machinery: registrar_resolve_seconds by source) plus the
        #: registrar_slo_* counters
        self.registry = metrics_mod.instrument_tracing(self.tracer)
        metrics_mod.instrument_slo(self, self.registry)

        self.server: Optional[ZKServer] = None
        self.ensemble: Optional[ZKEnsemble] = None
        self.members: List[_Member] = []
        self.live_client: Optional[ZKClient] = None
        self.cache_client: Optional[ZKClient] = None
        self.cache: Optional[ZKCache] = None
        #: sharded serve tier (ISSUE 12; shards > 0)
        self.n_shards = shards
        self.router = None
        self.shard_client = None
        self._slice_client: Optional[ZKClient] = None
        self._shard_dir: Optional[str] = None
        #: slice-probe domain -> its single host's admin ip (the
        #: expected A answer; static, never touched by fleet scenarios)
        self.slice_expected: Dict[str, str] = {}
        #: per-slice-domain shard-leg probe failures (the sibling-
        #: never-blips assertions diff snapshots of this)
        self.slice_errors: Dict[str, int] = {}
        self.shard_probes = 0
        #: the DNS frontend leg (ISSUE 19; rides the shard tier): real
        #: UDP A queries against the workers' SO_REUSEPORT socket, one
        #: slice domain per sample round-robin, fresh source port each
        #: time so samples hash across the whole worker group
        self.dns_probes = 0
        self.dns_errors = 0
        #: the serve tier's overload armor (ISSUE 17) — installed by
        #: _start_shard_tier iff repair is on; None IS the detection
        #: proof's lever (repair=False runs the same storm unarmored)
        self.shard_overload: Optional[Dict[str, Any]] = None

        self.probes: List[Probe] = []
        self.faults: List[FaultEvent] = []
        #: (fault_id, segment_start, segment_end) per scenario run
        self.segments: List[Tuple[str, float, float]] = []
        self.scenario: Optional[str] = None
        self.stale_probes = 0
        self.cached_probes = 0
        self._tasks: set = set()
        self._stop_probing = asyncio.Event()
        self._started_at: Optional[float] = None
        self._finished_at: Optional[float] = None

    # -- clock --------------------------------------------------------------

    def now(self) -> float:
        return asyncio.get_running_loop().time()

    # -- lifecycle ----------------------------------------------------------

    def _registration(self) -> Dict[str, Any]:
        return {
            "domain": self.domain,
            "type": "load_balancer",
            "service": {
                "type": "service",
                "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
            },
        }

    def _zk_addresses(self) -> List[Tuple[str, int]]:
        """Every live-or-restartable backend address (the stable
        ensemble servers list, or the standalone server's)."""
        if self.ensemble is not None:
            return list(self.ensemble.addresses)
        return [self.server.address]

    def _any_server(self) -> ZKServer:
        """A live server to drive test controls (force-expiry) through —
        shared session table, so any ensemble member works."""
        if self.ensemble is not None:
            live = self.ensemble.live
            if not live:
                raise RuntimeError("no live ensemble member")
            return live[0]
        return self.server

    def _lever_kwargs(self, member: Optional[_Member] = None) -> Dict[str, Any]:
        """The ISSUE-20 client levers, or ``{}`` when ``levers`` is off
        (reference-exact: the ZKClient keys stay absent, so the connect
        path and ping schedule are bit-identical to r19).

        Members get the full set: raced connects with a 40 ms stagger,
        a 40 ms ping / 100 ms dead-after watchdog (their 200 ms lever
        sessions make the reference ~67/133 ms schedule the bound), and
        — in ensemble mode — a spread attach slot so watch load never
        piles onto one member.  Probe clients get raced connects only:
        their 8 s sessions die by TCP reset (they are unproxied), so
        the watchdog lever has nothing to detect there."""
        if not self.levers:
            return {}
        kwargs: Dict[str, Any] = {"connect_race_stagger_ms": 40}
        if member is not None:
            kwargs["ping_interval_ms"] = 40
            kwargs["dead_after_ms"] = 100
            if self.n_ensemble > 1:
                kwargs["attach_preference"] = (
                    f"spread:{member.idx % self.n_ensemble}"
                    f"-of-{self.n_ensemble}"
                )
        return kwargs

    def _reconnect_policy(self) -> RetryPolicy:
        return _LEVER_RECONNECT if self.levers else _MEMBER_RECONNECT

    def _register_retry(self) -> RetryPolicy:
        return _LEVER_REGISTER_RETRY if self.levers else _REGISTER_RETRY

    def _make_client(self, member: _Member) -> ZKClient:
        return ZKClient(
            [p.address for p in member.proxies],
            timeout_ms=self.session_timeout_ms,
            connect_timeout_ms=300,
            connect_pass_timeout_ms=self.session_timeout_ms,
            reconnect_policy=self._reconnect_policy(),
            # Ensemble mode: attach read-only during quorum loss (reads
            # keep serving; writes retry through NOT_READONLY), fail
            # over fast when a read-write member returns, and keep the
            # connect-order shuffle seed-deterministic per fleet member.
            can_be_read_only=self.ensemble is not None,
            rng=random.Random(self.rng.randrange(2**32)),
            **self._lever_kwargs(member),
        )

    def _probe_client(self) -> ZKClient:
        client = ZKClient(
            self._zk_addresses(),
            timeout_ms=8000,
            connect_timeout_ms=300,
            connect_pass_timeout_ms=2000,
            reconnect_policy=self._reconnect_policy(),
            can_be_read_only=self.ensemble is not None,
            rng=random.Random(self.rng.randrange(2**32)),
            **self._lever_kwargs(),
        )
        client.rw_probe_interval_s = 0.1
        return client

    async def start(self) -> "SLOHarness":
        if self.n_ensemble > 1:
            self.ensemble = await ZKEnsemble(
                self.n_ensemble, election_ms=self.election_ms
            ).start()
        else:
            self.server = await ZKServer().start()
        backends = self._zk_addresses()
        for i in range(self.n_members):
            member = _Member(i, f"slo{i}", f"10.9.{i // 256}.{i % 256}")
            member.proxies = await proxy_fleet(backends, rng=self.rng)
            member.client = await self._make_client(member).connect()
            member.znodes = await register(
                member.client, self._registration(),
                admin_ip=member.admin_ip, hostname=member.hostname,
                settle_delay=0,
            )
            self.members.append(member)
        self.live_client = await self._probe_client().connect()
        self.cache_client = await self._probe_client().connect()
        self.live_client.tracer = self.tracer
        # The SWR lever (ISSUE 20): through a blip the cached leg keeps
        # answering bounded-age last-known-good instead of falling to
        # live reads against the same dead link — observable in the
        # report's staleness/levers stats, deliberately NOT in
        # availability (the probe's ok verdict rides the live leg).
        self.cache = ZKCache(
            self.cache_client,
            stale_max_age_s=30.0 if self.levers else None,
        )
        self.cache.tracer = self.tracer
        if self.n_shards > 0:
            await self._start_shard_tier()
        self._started_at = self.now()
        spawn_owned(self._probe_loop(), self._tasks)
        return self

    async def _start_shard_tier(self) -> None:
        """Stand up the ISSUE-12 serve tier: a router + worker
        processes against the (unproxied) backends, plus one static
        single-host slice-probe domain per shard slice — chosen off the
        deterministic ring, so every shard's slice is observable and a
        killed shard's outage is attributable to exactly its slice."""
        import os
        import tempfile

        from registrar_tpu.shard import (
            HashRing, ShardClient, ShardRouter,
        )

        # Deterministic slice coverage: walk candidate names until every
        # shard owns at least one (the ring is a pure function of the
        # shard ids, so this converges the same way in every run).
        ring = HashRing(range(self.n_shards))
        chosen: List[str] = []
        covered: set = set()
        for i in range(256):
            name = f"slice{i}.shard.slo.us"
            owner = ring.owner(name)
            if owner not in covered or len(chosen) < self.n_shards + 1:
                chosen.append(name)
                covered.add(owner)
            if len(covered) == self.n_shards and len(chosen) >= (
                self.n_shards + 1
            ):
                break
        # This client OWNS the slice hosts' ephemerals, so it must
        # outlive the whole run (closing it would delete them).
        self._slice_client = await self._probe_client().connect()
        for i, name in enumerate(chosen):
            ip = f"10.8.0.{i}"
            await register(
                self._slice_client,
                {
                    "domain": name,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp",
                            "port": 80,
                        },
                    },
                },
                admin_ip=ip, hostname=f"slice{i}", settle_delay=0,
            )
            self.slice_expected[name] = ip
            self.slice_errors[name] = 0
        self._shard_dir = tempfile.mkdtemp(prefix="sloshard")
        # Overload armor (ISSUE 17), repair-gated like the respawn
        # below: the armored run degrades under the storm scenario
        # (bounded queues, explicit sheds, stale answers); the
        # repair=False run faces the SAME seeded storm with every
        # defense withheld — the collapse the detection proof measures.
        # Sizing: per-conn inflight does the shedding (6 per storm
        # connection) and the global depth is the backstop, sized so
        # the router's own relay channel (the probes' path) never hits
        # it; the rate limit is far above probe cadence on purpose —
        # the storm drives workers directly, and a probe must never be
        # the client that gets limited.
        if self.repair:
            self.shard_overload = {
                # Lever mode sizes the backstop so the storm's backlog
                # sheds at the STORM's connections (per-conn inflight)
                # before the global bound starts refusing the probes'
                # own relay channel — the reference depth stays 96.
                "maxQueueDepth": 160 if self.levers else 96,
                "maxInflightPerConn": 6,
                "clientRateLimit": 1000.0,
                "coldFillConcurrency": 4,
                "writeDeadlineS": 0.4,
            }
        self.router = ShardRouter(
            self._zk_addresses(),
            self.n_shards,
            os.path.join(self._shard_dir, "resolve.sock"),
            attach_spread="spread" if self.ensemble is not None else "any",
            timeout_ms=self.session_timeout_ms,
            poll_interval_s=0.5,
            # Lever mode (ISSUE 20): crash detect + readiness poll at
            # 10 ms — the respawn MTTR's fixed overhead — instead of
            # the reference 50 ms cadence.
            supervise_interval_s=0.01 if self.levers else 0.05,
            # The DNS frontend (ISSUE 19) rides the same workers: every
            # probe sample sends a REAL A query over UDP, so "the tier
            # is up" means the packet path answers, not just the unix
            # relay.  Port 0: the harness must never collide with a
            # developer's own 5300.
            dns={"host": "127.0.0.1", "port": 0},
            # Worker disconnect/degrade warnings are the simulator
            # working as intended, same stance as tools/slo.py takes
            # for the fleet's own clients (SLO_VERBOSE restores them).
            worker_log_level=(
                None if os.environ.get("SLO_VERBOSE") == "1" else "ERROR"
            ),
            # Cross-process tracing (ISSUE 13): workers record at 100%
            # so a failing slice probe's trace id resolves to a FULL
            # tree — probe span → shard.relay → the owning worker's
            # resolve subtree — in the worst-outage report.
            worker_trace={"sampleRate": 1.0, "maxSpans": 4096},
            overload=self.shard_overload,
        )
        self.router.tracer = self.tracer
        # With repair withheld, a crashed worker stays dead — the
        # respawn IS the recovery action the detection proof disables.
        self.router.respawn_enabled = self.repair
        await self.router.start()
        self.shard_client = await ShardClient(
            self.router.socket_path
        ).connect()
        for name in self.slice_expected:
            await self.shard_client.resolve(name, "A")

    async def stop(self) -> None:
        self._stop_probing.set()
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self.cache is not None:
            self.cache.close()
        if self.shard_client is not None:
            await self.shard_client.close()
        if self.router is not None:
            await self.router.stop()
        if self._shard_dir is not None:
            import shutil

            shutil.rmtree(self._shard_dir, ignore_errors=True)
        for client in (
            self.live_client, self.cache_client, self._slice_client
        ):
            if client is not None and not client.closed:
                await client.close()
        for member in self.members:
            if member.client is not None and not member.client.closed:
                await member.client.close()
            for proxy in member.proxies:
                await proxy.stop()
        if self.ensemble is not None:
            await self.ensemble.stop()
        if self.server is not None:
            await self.server.stop()

    async def __aenter__(self) -> "SLOHarness":
        return await self.start()

    async def __aexit__(self, *_exc) -> None:
        await self.stop()

    # -- fault bookkeeping --------------------------------------------------

    @property
    def expected(self) -> set:
        return {m.admin_ip for m in self.members}

    def inject(self, fault: str, member: Optional[int] = None) -> FaultEvent:
        """Record (and trace) a fault-class injection.  Every scenario
        routes through here with a docs/FAULTS.md catalog literal, which
        is what the ``fault-id-drift`` rule machine-checks."""
        if fault not in self.fault_ids:
            raise ValueError(f"unknown fault class {fault!r} (FAULT_IDS)")
        event = FaultEvent(fault, member, self.now())
        self.faults.append(event)
        self.tracer.event(
            "slo.fault", fault=fault, member=member,
            scenario=self.scenario,
        )
        log.debug("inject %s member=%s at %.3f", fault, member,
                  event.injected_at)
        return event

    def clear(self, event: FaultEvent) -> None:
        event.cleared_at = self.now()

    def _active_faults(self) -> str:
        return ",".join(
            f.fault for f in self.faults if f.cleared_at is None
        )

    # -- the prober ---------------------------------------------------------

    async def _probe_loop(self) -> None:
        while not self._stop_probing.is_set():
            try:
                await self._probe_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - the prober must outlive faults
                log.exception("probe iteration failed")
            try:
                await asyncio.wait_for(
                    self._stop_probing.wait(), timeout=self.probe_interval
                )
            except asyncio.TimeoutError:
                pass

    async def _probe_once(self) -> None:
        expected = self.expected
        with trace_mod.annotate(
            scenario=self.scenario, faults=self._active_faults()
        ):
            with self.tracer.span("slo.probe") as span:
                t = self.now()
                live_set: set = set()
                try:
                    res = await binderview.resolve(
                        self.live_client, self.domain, "A"
                    )
                    live_set = {a.data for a in res.answers}
                except asyncio.CancelledError:
                    raise
                except Exception as err:  # noqa: BLE001 - a failed read IS a failed probe
                    span.set_attr("err", repr(err))
                ok = live_set == expected
                span.set_attr("result", "ok" if ok else "fail")
                try:
                    cres = await binderview.resolve(
                        self.cache, self.domain, "A"
                    )
                    self.cached_probes += 1
                    if {a.data for a in cres.answers} != live_set:
                        self.stale_probes += 1
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - cached failure counts as stale
                    self.cached_probes += 1
                    self.stale_probes += 1
                if self.shard_client is not None:
                    # The two tier legs run concurrently: they share no
                    # state, and adding the DNS exchange's latency on
                    # top of the slice sweep's would quantize every
                    # outage window up by the serial sum.
                    shard_ok, dns_ok = await asyncio.gather(
                        self._probe_shards(), self._probe_dns(),
                    )
                    span.set_attr("shard_ok", shard_ok)
                    span.set_attr("dns_ok", dns_ok)
                    ok = ok and shard_ok and dns_ok
        self.probes.append(
            Probe(t, ok, len(expected - live_set), span.trace_id)
        )
        self.emit("probe", "ok" if ok else "fail")

    async def _probe_shards(self) -> bool:
        """The sharded-tier probe leg: every slice-probe domain must
        answer its static host through the tier.  Per-domain failures
        are counted (the sibling-never-blips assertions), and any
        failure fails the sample — a shard's slice being down IS fleet
        downtime once real DNS fronts this tier."""
        async def one(name: str, expected_ip: str) -> bool:
            try:
                res = await self.shard_client.resolve(name, "A")
                return {a.data for a in res.answers} == {expected_ip}
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - a failed read IS a failed probe
                return False

        # Concurrently — the slices are independent by construction, and
        # a sequential sweep would quantize every outage window up by
        # the whole sweep's latency.
        names = list(self.slice_expected)
        results = await asyncio.gather(
            *(one(n, self.slice_expected[n]) for n in names)
        )
        shard_ok = True
        for name, good in zip(names, results):
            if not good:
                self.slice_errors[name] += 1
                shard_ok = False
        self.shard_probes += 1
        return shard_ok

    async def _probe_dns(self) -> bool:
        """The DNS frontend probe leg (ISSUE 19): one real UDP A query
        per sample against the workers' shared SO_REUSEPORT socket,
        round-robin over the slice domains.  A fresh source port each
        sample means the kernel's 4-tuple hash spreads samples across
        the whole worker group over time, so no single worker's DNS
        path can rot unobserved.  The answer must be NOERROR with the
        slice's static host — a REFUSED shed, a timeout, or a wrong
        answer IS fleet downtime: this leg is what "real DNS fronts
        this tier" changes about the availability math."""
        from registrar_tpu import dnsfront

        names = list(self.slice_expected)
        if not names:
            return True
        name = names[self.dns_probes % len(names)]
        expected_ip = self.slice_expected[name]
        self.dns_probes += 1
        packet = dnsfront.build_query(
            (self.dns_probes & 0xFFFF) or 1, name,
            dnsfront.QTYPE_A, rd=True, edns_size=1232,
        )
        # Two attempts, matching what any real resolver does with a
        # dropped UDP exchange — and each retry is a fresh source port,
        # so the kernel rehashes it to a (likely) different worker.
        # 0.2 s per attempt: orders of magnitude above a healthy
        # exchange (sub-ms on loopback) but short enough that a
        # dead-air sample doesn't stall the probe cadence and quantize
        # the measured outage windows up by its own timeout.
        good = False
        for _attempt in range(2):
            try:
                data = await dnsfront.query_udp(
                    self.router.dns["host"], self.router.dns["port"],
                    packet, timeout=0.2,
                )
                resp = dnsfront.decode_response(data)
                good = (
                    (data[3] & 0x0F) == dnsfront.RCODE_NOERROR
                    and any(
                        rtype == "A" and text == expected_ip
                        for _n, rtype, _ttl, text in resp.answers
                    )
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - a failed exchange IS a failed probe
                good = False
            if good:
                break
        if not good:
            self.dns_errors += 1
        return good

    async def wait_healthy(self, timeout: float = 8.0) -> None:
        """Block until the prober sees a full fleet again (scenario
        barrier: the next scenario starts from health, so its fault
        class owns its own windows)."""
        deadline = self.now() + timeout
        while True:
            if self.probes and self.probes[-1].ok:
                return
            if self.now() >= deadline:
                raise RuntimeError(
                    f"fleet never reconverged after {self.scenario!r} "
                    f"(last probe: {self.probes[-1] if self.probes else None})"
                )
            await asyncio.sleep(self.probe_interval)

    # -- member recovery actions --------------------------------------------

    async def _connect_fresh(self, client: ZKClient) -> ZKClient:
        try:
            return await client.connect()
        except SessionExpiredError:
            # A seeded resume the server refused (the session died with
            # the "crashed" predecessor): the client has already reset
            # to a fresh-session handshake — connect again, exactly the
            # successor-daemon fallback (main._attempt_resume).
            return await client.connect()

    async def _restart_member(
        self, member: _Member, resume: Optional[Tuple[int, bytes]] = None
    ) -> None:
        """Bring a member back with a fresh process's client.

        ``resume`` is the SIGKILL-shaped stale-statefile path: the
        "successor" offers the dead session's (id, passwd) the way a
        leftover handoff state file would; the server refuses it and
        the member falls back to a fresh registration."""
        if member.client is not None and not member.client.closed:
            await member.client.close()
        client = self._make_client(member)
        if resume is not None:
            client.seed_session(
                resume[0], resume[1],
                negotiated_timeout_ms=self.session_timeout_ms,
            )
        member.client = await self._connect_fresh(client)
        member.znodes = await register(
            member.client, self._registration(),
            admin_ip=member.admin_ip, hostname=member.hostname,
            settle_delay=0, retry_policy=self._register_retry(),
        )

    def _live_members(self) -> List[_Member]:
        return [
            m
            for m in self.members
            if m.client is not None and m.client.connected
        ]

    def _pick_member(self) -> Optional[_Member]:
        """A member whose client is still alive — with repair disabled,
        earlier scenarios leave corpses behind, and injecting into a
        corpse would be a no-op the attribution then mis-reads."""
        candidates = self._live_members()
        if not candidates:
            return None
        return candidates[self.rng.randrange(len(candidates))]

    # -- scenarios (one per docs/FAULTS.md fault class) ---------------------

    async def run_scenario(self, fault_id: str, **kwargs) -> None:
        """Run one named scenario, bracket its probe segment, and (with
        repair on) wait for reconvergence before returning."""
        methods = {
            "deploy-wave": self._scenario_deploy_wave,
            "crash-loop": self._scenario_crash_loop,
            "health-flap": self._scenario_health_flap,
            "expiry-storm": self._scenario_expiry_storm,
            "netem-episode": self._scenario_netem_episode,
            "leader-kill": self._scenario_leader_kill,
            "quorum-loss": self._scenario_quorum_loss,
            "rolling-upgrade": self._scenario_rolling_upgrade,
            "partition-minority": self._scenario_partition_minority,
            "shard-kill": self._scenario_shard_kill,
            "reshard-wave": self._scenario_reshard_wave,
            "overload-storm": self._scenario_overload_storm,
        }
        ensemble_only = {
            "leader-kill", "quorum-loss", "rolling-upgrade",
            "partition-minority",
        }
        sharded_only = {"shard-kill", "reshard-wave", "overload-storm"}
        if fault_id not in methods:
            raise ValueError(f"unknown scenario {fault_id!r}")
        if fault_id in ensemble_only and self.ensemble is None:
            raise ValueError(
                f"scenario {fault_id!r} needs ensemble= > 1 (ISSUE 10)"
            )
        if fault_id in sharded_only and self.router is None:
            raise ValueError(
                f"scenario {fault_id!r} needs shards= > 0 (ISSUE 12)"
            )
        self.scenario = fault_id
        started = self.now()
        try:
            await methods[fault_id](**kwargs)
            if self.repair:
                await self.wait_healthy()
        finally:
            self.segments.append((fault_id, started, self.now()))
            self.scenario = None

    async def _scenario_deploy_wave(
        self, wave: Optional[int] = None, down_s: float = 0.1
    ) -> None:
        """A rolling deploy using drain restarts: each member leaves DNS
        (clean unregister), the process "exits", and a successor
        re-registers — the bounded per-member gap drain mode promises
        (handoff mode's zero-gap restart is proven by
        tests/test_restart_e2e.py; this measures the drain envelope)."""
        count = wave if wave is not None else max(2, self.n_members // 2)
        live = self._live_members()
        order = self.rng.sample(live, min(count, len(live)))
        for member in order:
            event = self.inject("deploy-wave", member=member.idx)
            await unregister(member.client, member.znodes)
            await member.client.close()
            await asyncio.sleep(down_s)
            if self.repair:
                await self._restart_member(member)
                self.clear(event)

    async def _scenario_crash_loop(
        self, crashes: int = 2, restart_delay: float = 0.15
    ) -> None:
        """SIGKILL shape, in a loop: the session is force-expired out
        from under the member (ephemerals vanish at once, like a host
        dying with its supervisor), a stale handoff state survives, and
        the successor's seeded resume is refused — it registers fresh,
        exactly the degraded statefile fallback of docs/OPERATIONS.md's
        restart fault rows."""
        member = self._pick_member()
        if member is None:
            return  # nobody left to crash (repair disabled earlier)
        for _ in range(crashes):
            event = self.inject("crash-loop", member=member.idx)
            stale = (member.client.session_id, member.client.session_passwd)
            await self._any_server().expire_session(member.client.session_id)
            await asyncio.sleep(restart_delay)
            if not self.repair:
                break  # the member stays dead; looping adds nothing
            await self._restart_member(member, resume=stale)
            self.clear(event)
            await self.wait_healthy()

    async def _scenario_health_flap(
        self, flaps: int = 3, down_s: float = 0.1, up_s: float = 0.08
    ) -> None:
        """Health-check flapping: the agent's fail->deregister /
        ok->re-register transitions, at the znode level — the member
        leaves DNS deliberately and comes back on "recovery"."""
        member = self._pick_member()
        if member is None:
            return  # nobody left to flap (repair disabled earlier)
        for _ in range(flaps):
            event = self.inject("health-flap", member=member.idx)
            await unregister(member.client, member.znodes)
            await asyncio.sleep(down_s)
            if not self.repair:
                break  # the member stays deregistered; no more flaps
            member.znodes = await register(
                member.client, self._registration(),
                admin_ip=member.admin_ip, hostname=member.hostname,
                settle_delay=0,
            )
            self.clear(event)
            await self.wait_healthy()
            await asyncio.sleep(up_s)

    async def _scenario_expiry_storm(
        self, victims: Optional[int] = None, restart_delay: float = 0.15
    ) -> None:
        """Several members' sessions expired at once (an ensemble-side
        purge): the fleet-wide recovery runs concurrently, the way a
        reborn fleet's jittered pipelines would."""
        count = victims if victims is not None else max(2, self.n_members // 2)
        live = self._live_members()
        chosen = self.rng.sample(live, min(count, len(live)))
        events = []
        for member in chosen:
            events.append(self.inject("expiry-storm", member=member.idx))
            await self._any_server().expire_session(member.client.session_id)
        await asyncio.sleep(restart_delay)
        if self.repair:
            await asyncio.gather(
                *(self._restart_member(m) for m in chosen)
            )
            for event in events:
                self.clear(event)

    async def _scenario_netem_episode(
        self, episodes: int = 1, blackhole_s: Optional[float] = None
    ) -> None:
        """A per-member network fault episode: the member's proxy goes
        total-void (Blackhole both directions + connection drop) long
        enough for the server to expire the unreachable session; the
        link then heals and the member re-registers."""
        hold = (
            blackhole_s
            if blackhole_s is not None
            else 2.2 * self.session_timeout_ms / 1000.0
        )
        member = self._pick_member()
        if member is None:
            return  # nobody left to blackhole (repair disabled earlier)
        for _ in range(episodes):
            event = self.inject("netem-episode", member=member.idx)
            for proxy in member.proxies:
                proxy.add(Blackhole(), direction=UP)
                proxy.add(Blackhole(), direction=DOWN)
                proxy.drop_connections()
            await asyncio.sleep(hold)
            for proxy in member.proxies:
                proxy.clear()
            if self.repair:
                await self._restart_member(member)
                self.clear(event)
                await self.wait_healthy()

    # -- ensemble scenarios (ISSUE 10; need ensemble= > 1) -------------------

    async def _scenario_leader_kill(
        self, kills: int = 1, down_s: float = 0.3
    ) -> None:
        """SIGKILL-shaped leader death **mid-registration**: a fleet
        member deregisters (the observable outage the probes time), the
        ensemble leader is killed while the member's re-registration is
        in flight, and the write rides the election + failover — retried
        through connection drops and NOT_READONLY until the new leader
        commits it.  MTTR covers deregistration -> election -> commit."""
        for _ in range(kills):
            member = self._pick_member()
            leader_idx = self.ensemble.leader_index
            if member is None or leader_idx is None:
                return  # no live fleet member / no leader to kill
            event = self.inject("leader-kill", member=leader_idx)
            await unregister(member.client, member.znodes)
            member.znodes = []
            reregister = asyncio.ensure_future(
                register(
                    member.client, self._registration(),
                    admin_ip=member.admin_ip, hostname=member.hostname,
                    settle_delay=0, retry_policy=self._register_retry(),
                )
            )
            await asyncio.sleep(0)  # the pipeline is now in flight
            await self.ensemble.kill(leader_idx)
            # The leader stays dead for down_s: the re-registration must
            # ride the election + failover, not race an instant restart.
            await asyncio.sleep(down_s)
            try:
                member.znodes = await reregister
            except Exception:
                reregister.cancel()
                raise
            if self.repair:
                await self.ensemble.restart(leader_idx)
                self.clear(event)
                await self.wait_healthy()
            else:
                return  # the leader stays dead

    async def _scenario_quorum_loss(self, hold_s: float = 0.6) -> None:
        """Kill members down to a minority: the survivors degrade to
        read-only (fleet writes refuse with NOT_READONLY; resolves keep
        answering through the ro member), sessions freeze (no leader =
        no expiry), and when the members return writes resume without
        operator action — the registrations were never lost."""
        size = self.ensemble.size
        running = set(self.ensemble.live)
        live = [
            i for i, m in enumerate(self.ensemble.servers)
            if m is not None and m in running
        ]
        majority = size // 2 + 1
        victims = live[: max(0, len(live) - (majority - 1))]
        if not victims:
            return
        event = self.inject("quorum-loss")
        for i in victims:
            await self.ensemble.kill(i)
        await asyncio.sleep(hold_s)
        if not self.repair:
            return  # quorum never returns
        for i in victims:
            await self.ensemble.restart(i)
        self.clear(event)
        await self.wait_healthy()

    async def _scenario_rolling_upgrade(self, pause_s: float = 0.25) -> None:
        """Restart every ensemble member one at a time (quorum held
        throughout): the fleet's sessions fail over member to member and
        the polling resolver should see no gap at all."""
        event = self.inject("rolling-upgrade")
        for i in range(self.ensemble.size):
            await self.ensemble.kill(i)
            await asyncio.sleep(pause_s)
            if not self.repair:
                return  # the "upgrade" wedges after the first member
            await self.ensemble.restart(i)
            await asyncio.sleep(pause_s)
        self.clear(event)
        await self.wait_healthy()

    async def _scenario_partition_minority(self, hold_s: float = 0.6) -> None:
        """Partition one member away from the majority: it degrades to
        read-only with a frozen view while the majority keeps serving
        writes; healing the partition catches it back up."""
        size = self.ensemble.size
        minority = size - 1
        event = self.inject("partition-minority", member=minority)
        self.ensemble.partition(
            [list(range(size - 1)), [minority]]
        )
        await asyncio.sleep(hold_s)
        if not self.repair:
            return  # the partition never heals
        self.ensemble.heal_partition()
        self.clear(event)
        await self.wait_healthy()

    # -- sharded serve tier scenarios (ISSUE 12; need shards= > 0) -----------

    def _slice_domains_of(self, shard_id: int) -> List[str]:
        return [
            name
            for name in self.slice_expected
            if self.router.ring.owner(name) == shard_id
        ]

    async def _wait_slices_healthy(
        self,
        domains: List[str],
        respawns_before: Optional[int] = None,
        timeout: float = 12.0,
    ) -> None:
        """Block until ``domains`` answer through the tier again — the
        shard scenarios' reconvergence barrier.  With
        ``respawns_before`` given, first wait for the router's respawn
        to land (a kill propagates asynchronously: clearing the fault
        on a probe round that simply raced ahead of the supervisor
        would close the attribution window before the outage even
        started)."""
        deadline = self.now() + timeout
        while (
            respawns_before is not None
            and self.router.respawns_total() <= respawns_before
        ):
            if self.now() >= deadline:
                raise RuntimeError("shard respawn never happened")
            await asyncio.sleep(0.01)
        while True:
            healthy = True
            for name in domains:
                try:
                    res = await self.shard_client.resolve(name, "A")
                    if {a.data for a in res.answers} != {
                        self.slice_expected[name]
                    }:
                        healthy = False
                except Exception:  # noqa: BLE001 - still recovering
                    healthy = False
            if healthy:
                return
            if self.now() >= deadline:
                raise RuntimeError(
                    "sharded tier never reconverged "
                    f"(slice errors: {self.slice_errors})"
                )
            await asyncio.sleep(self.probe_interval)

    async def _scenario_shard_kill(self, kills: int = 1) -> None:
        """SIGKILL one shard worker: its slice fails until the router's
        respawn + warm refill lands (the MTTR the probes measure), and —
        asserted, not just hoped — sibling shards' slices never blip.
        With repair withheld the respawn never comes and the slice
        stays dark (the detection proof's nines drop)."""
        for _ in range(kills):
            victim = self.router.ring.owner(
                next(iter(self.slice_expected))
            )
            victims = self._slice_domains_of(victim)
            siblings = [
                name
                for name in self.slice_expected
                if name not in victims
            ]
            sibling_errs = {
                name: self.slice_errors[name] for name in siblings
            }
            respawns_before = self.router.respawns_total()
            event = self.inject("shard-kill", member=victim)
            self.router.kill_worker(victim)
            if not self.repair:
                return  # the worker stays dead (respawn withheld)
            await self._wait_slices_healthy(victims, respawns_before)
            self.clear(event)
            await self.wait_healthy()
            blipped = {
                name: self.slice_errors[name] - before
                for name, before in sibling_errs.items()
                if self.slice_errors[name] != before
            }
            if blipped:
                raise RuntimeError(
                    f"sibling slices blipped during shard-kill: {blipped}"
                )

    async def _scenario_reshard_wave(self, hold_s: float = 0.15) -> None:
        """Reshard the tier up one shard and back down mid-traffic: the
        warm handoff + ring-flip ordering must keep every slice
        answering — ZERO shard-probe errors across the whole wave is
        asserted (this is the zero-downtime scenario; it never shows up
        in MTTD/MTTR because a correct reshard is never detected as an
        outage)."""
        errs_before = dict(self.slice_errors)
        event = self.inject("reshard-wave")
        if not self.repair:
            # The broken run: earlier withheld recoveries leave dead
            # slices whose steady-state errors are not this wave's —
            # there is nothing honest to reshard or assert here.
            await asyncio.sleep(hold_s)
            return
        await self.router.reshard(self.n_shards + 1)
        await asyncio.sleep(hold_s)
        await self.router.reshard(self.n_shards)
        await asyncio.sleep(hold_s)
        self.clear(event)
        await self.wait_healthy()
        blipped = {
            name: self.slice_errors[name] - before
            for name, before in errs_before.items()
            if self.slice_errors[name] != before
        }
        if blipped:
            raise RuntimeError(
                f"reshard-wave was not zero-error: {blipped}"
            )

    async def _scenario_overload_storm(
        self,
        storm_s: float = 1.5,
        clients: int = 6,
        pipeline: int = 36,
    ) -> None:
        """Seeded heavy-tailed storm far past the tier's capacity
        (ISSUE 17): Zipf warm traffic, a flash crowd on one slice,
        never-exists churn, malformed frames, and slow-loris/half-open
        clients — all over the real direct-client paths, while the
        probes keep flying.  With armor on (the overload config
        _start_shard_tier installs iff repair) the tier must DEGRADE,
        not collapse — asserted: every queue-depth sample stays under
        the configured bound, no worker dies, the storm was actually
        refused work (sheds > 0) and every refusal carried an explicit
        shed reason with ZERO timeouts, and the write deadline cut the
        slow-loris connections loose.  With repair=False the SAME seed
        hits an unarmored tier and whatever happens to the probes is
        the honest answer — the detection proof's collapse."""
        from registrar_tpu.testing import workload

        storm = workload.StormWorkload(
            self.router.socket_path,
            list(self.slice_expected),
            # Derived from the harness seed: --prove-detection re-runs
            # the SAME storm with the armor withheld.
            seed=(self.seed ^ 0x17AC0CE) & 0xFFFFFFFF,
            duration_s=storm_s,
            clients=clients,
            pipeline=pipeline,
            loris_frames=12000,
        )
        event = self.inject("overload-storm")
        if not self.repair:
            # Unarmored: no admission control, no bounds, no deadline.
            # The storm's queued cold fills and pinned handler tasks
            # outlive the storm window itself; nothing here recovers
            # deliberately, so the event is never cleared.
            await storm.run()
            return
        respawns_before = self.router.respawns_total()
        bound = self.shard_overload["maxQueueDepth"]
        peak_depth = 0
        stop_sampling = asyncio.Event()

        async def sample_depth() -> None:
            # Rides OP_STATUS — satellite 2's priority lane, exercised
            # live: the sampler must keep answering while resolves shed.
            nonlocal peak_depth
            while not stop_sampling.is_set():
                status = await self.router.status()
                for entry in status["shards"].values():
                    peak_depth = max(
                        peak_depth, int(entry.get("queue_depth") or 0)
                    )
                try:
                    await asyncio.wait_for(stop_sampling.wait(), 0.15)
                except asyncio.TimeoutError:
                    pass

        sampler = asyncio.get_running_loop().create_task(sample_depth())
        try:
            report = await storm.run()
        finally:
            stop_sampling.set()
            await sampler
        self.clear(event)
        await self.wait_healthy()
        problems = []
        if report.sheds_total == 0:
            problems.append("the storm never overloaded the tier (0 sheds)")
        if report.timeouts_total:
            problems.append(
                f"{report.timeouts_total} storm requests timed out — a "
                "shed must be an explicit fast refusal, never silence"
            )
        if peak_depth > bound:
            problems.append(
                f"queue depth {peak_depth} exceeded the configured "
                f"bound {bound}"
            )
        if self.router.respawns_total() != respawns_before:
            problems.append("a worker died under the storm")
        if report.loris["conns"] and not report.loris["disconnected"]:
            problems.append(
                "no slow-loris client was disconnected (write-deadline "
                "armor never engaged)"
            )
        if problems:
            raise RuntimeError(
                "overload-storm armor failed: " + "; ".join(problems)
            )
        log.info(
            "overload-storm envelope: peak_depth=%d %s",
            peak_depth, report.summary(),
        )

    # -- the report ---------------------------------------------------------

    async def settle(self, seconds: float = 0.2) -> None:
        """Trailing ok probes so the last scenario's windows close."""
        await asyncio.sleep(seconds)

    def report(self, trace_name: str = "custom") -> Dict[str, Any]:
        """Stop probing and roll the timeline up into the SLO report.

        Emits one ``outage`` event per attributed merged window (the
        ``registrar_slo_outage_seconds_total{fault}`` feed), so call it
        exactly once per run."""
        self._stop_probing.set()
        self._finished_at = self.now()
        end = self._finished_at
        per_fault, windows = fault_summary(self.faults, self.probes, end)
        # Per-class availability over the UNION of that class's probe
        # segments — a trace may run the same scenario more than once
        # (the full trace does), and the class's number must cover all
        # of its runs, not just the last.
        segment_probes: Dict[str, List[Probe]] = {}
        for fid, start_t, end_t in self.segments:
            segment_probes.setdefault(fid, []).extend(
                p for p in self.probes if start_t <= p.t <= end_t
            )
        for fid, probes in segment_probes.items():
            if fid in per_fault and probes:
                avail = availability(probes)
                per_fault[fid]["availability"] = round(avail, 6)
                per_fault[fid]["nines"] = nines(avail)
        for window in windows:
            owner = window_owner(window, self.faults)
            if owner is not None:
                self.emit("outage", owner.fault, window[1] - window[0])
        overall = availability(self.probes) if self.probes else 0.0
        worst = max(
            windows, key=lambda w: w[1] - w[0], default=None
        )
        worst_info = None
        if worst is not None:
            owner = window_owner(worst, self.faults)
            trace_ids = [
                p.trace_id
                for p in self.probes
                if worst[0] <= p.t <= worst[1]
                and not p.ok
                and p.trace_id is not None
            ]
            worst_info = {
                "start_s": round(worst[0] - self._started_at, 4),
                "duration_s": round(worst[1] - worst[0], 4),
                "fault": owner.fault if owner is not None else None,
                "trace_ids": trace_ids[:5],
            }
        hist = self.registry.get("registrar_resolve_seconds")
        staleness = {
            "stale_cached_probes": self.stale_probes,
            "cached_probes": self.cached_probes,
            "stale_ratio": round(
                self.stale_probes / self.cached_probes, 6
            ) if self.cached_probes else None,
            "cache_coherence_lag_ms_last": self.cache.stats[
                "coherence_lag_ms_last"
            ] if self.cache is not None else None,
        }
        for source in ("cached", "live"):
            for q in (0.50, 0.95, 0.99):
                value = hist.quantile(q, {"source": source})
                staleness[
                    f"resolve_{source}_p{int(q * 100)}_ms"
                ] = round(value * 1000.0, 4) if value is not None else None
        # Lever attribution (ISSUE 20): how often each availability
        # lever actually fired this run — race wins across the fleet's
        # (current) clients, watchdog suspicions, the cache's SWR
        # serves/refusals, and the recovery-tuning profile in force.
        # Reported with levers OFF too (all-zero by construction), so
        # --prove-levers diffs one shape.
        clients = [
            m.client for m in self.members if m.client is not None
        ] + [
            c
            for c in (
                self.live_client, self.cache_client, self._slice_client
            )
            if c is not None
        ]
        policy = self._reconnect_policy()
        levers = {
            "enabled": self.levers,
            "raced_connects": {
                "race_wins": sum(c.race_stats["wins"] for c in clients),
            },
            "failure_detector": {
                "suspicions": sum(c.watchdog_drops for c in clients),
            },
            "swr_cache": {
                "stale_serves": (
                    self.cache.stats["stale_serves"]
                    if self.cache is not None
                    else 0
                ),
                "stale_refusals": (
                    self.cache.stats["stale_refusals"]
                    if self.cache is not None
                    else 0
                ),
            },
            "recovery_tuning": {
                "session_timeout_ms": self.session_timeout_ms,
                "election_ms": (
                    self.election_ms if self.n_ensemble > 1 else None
                ),
                "reconnect_floor_ms": round(policy.initial_delay * 1000.0, 1),
                "reconnect_cap_ms": round(policy.max_delay * 1000.0, 1),
                "attach": (
                    "spread"
                    if self.levers and self.n_ensemble > 1
                    else "any"
                ),
            },
        }
        mttr_all = [f.mttr_s for f in self.faults if f.mttr_s is not None]
        mttd_all = [f.mttd_s for f in self.faults if f.mttd_s is not None]
        measured = sum(
            1
            for entry in per_fault.values()
            if entry["detected"] and entry["mttr_s_mean"] is not None
        )
        downtime = round(total_outage_s(windows), 4)
        gate_metrics = {
            "availability_pct": round(overall * 100.0, 4),
            "downtime_s_total": downtime,
            "worst_outage_s": (
                worst_info["duration_s"] if worst_info is not None else 0.0
            ),
            "mttr_s_mean": _round_stats(mttr_all)["mean"],
            "mttd_s_mean": _round_stats(mttd_all)["mean"],
            "fault_classes_measured": measured,
        }
        return {
            "trace": trace_name,
            "seed": self.seed,
            "repair": self.repair,
            "members": self.n_members,
            "ensemble": {
                "members": self.n_ensemble,
                "election_ms": (
                    self.election_ms if self.n_ensemble > 1 else None
                ),
                "elections": (
                    self.ensemble.state.elections
                    if self.ensemble is not None
                    else 0
                ),
            },
            "shards": {
                "shards": self.n_shards,
                "slice_domains": len(self.slice_expected),
                "slice_probes": self.shard_probes,
                "slice_errors": sum(self.slice_errors.values()),
                "dns_probes": self.dns_probes,
                "dns_errors": self.dns_errors,
                "respawns": (
                    self.router.respawns_total()
                    if self.router is not None
                    else 0
                ),
                "reshards": (
                    self.router.reshards
                    if self.router is not None
                    else 0
                ),
            },
            "probe_interval_ms": round(self.probe_interval * 1000.0, 1),
            "duration_s": round(end - self._started_at, 3),
            "probes": {
                "total": len(self.probes),
                "ok": sum(1 for p in self.probes if p.ok),
                "fail": sum(1 for p in self.probes if not p.ok),
            },
            "availability": round(overall, 6),
            "nines": nines(overall) if self.probes else 0.0,
            "faults": per_fault,
            "outages": {
                "windows": len(windows),
                "downtime_s_total": downtime,
                "worst": worst_info,
            },
            "staleness": staleness,
            "levers": levers,
            "gate_metrics": gate_metrics,
        }

    async def collect_worst_trace(self, report: Dict[str, Any]) -> None:
        """Upgrade the report's worst-outage entry from trace IDS to
        the assembled cross-process trace TREE (ISSUE 13).

        Picks the first failing probe's trace id inside the worst
        window and assembles one tree across every process that saw it
        — the harness's own recorder (probe spans, fleet zk.ops) plus,
        in shards mode, the router's relay spans and each worker's
        resolve subtree via ``OP_TRACE``.  Call between :meth:`report`
        and :meth:`stop` (the workers must still be alive to hand over
        their fragments; spans a dead worker took with it surface under
        ``<missing parent>``, which is the point).  No-op when the run
        had no outage.
        """
        worst = (report.get("outages") or {}).get("worst")
        if not worst or not worst.get("trace_ids"):
            return
        trace_id = worst["trace_ids"][0]
        if self.router is not None:
            # The router shares this harness's tracer (and process), so
            # the fan-out already folds the probe spans in alongside
            # every worker's fragment.
            tree = await self.router.collect_trace(trace_id)
        else:
            tree = traceview.assemble(
                self.tracer.dump(trace_id=trace_id).get("entries", []),
                trace_id,
            )
        worst["trace_tree"] = tree


# ---------------------------------------------------------------------------
# Named traces
# ---------------------------------------------------------------------------

#: The trace matrix (tools/slo.py --trace).  ``quick`` is the CI/gate
#: trace: every fault class once, ~8 s wall; ``full`` is the long soak
#: (make slo): a bigger fleet, repeated episodes.
TRACES: Dict[str, Dict[str, Any]] = {
    "quick": {
        "members": 5,
        "probe_interval": 0.02,
        "session_timeout_ms": 800,
        "pause_s": 0.5,
        # The quick trace runs against a real 3-member ensemble (ISSUE
        # 10): every pre-existing fault class now recovers through
        # leader/follower members, and the headline leader-failover
        # scenario's envelope lands in SLO_HISTORY.json.
        "ensemble": 3,
        "election_ms": 120.0,
        # ISSUE 20 lever overrides (tools/slo.py's default mode;
        # --reference restores the r19 envelope above): 200 ms sessions
        # bound the SERVER side of failure detection — a dead member's
        # ephemerals clear in 0.2 s instead of 0.8 — the 40 ms election
        # window shrinks every leader failover the fleet rides, and the
        # 15 ms probe cadence resolves the sub-100 ms outages the
        # levers leave behind (a 20 ms cadence would quantize them).
        "levers": {
            "session_timeout_ms": 200,
            "election_ms": 35.0,
            "probe_interval": 0.01,
            # Recovery-path knobs ONLY are retuned below: the deploy
            # pipeline's stop->start gap and the supervisor's restart
            # delay are the operator's own machinery, which the levers
            # are allowed to make fast.  Fault-SEVERITY knobs are
            # byte-identical to the reference rows above — health-flap
            # down time, the netem 2.2x-session blackhole formula,
            # partition/quorum holds, the leader's 0.3 s death, and the
            # storm's length/shape all stay put (a lever that shrinks
            # the fault instead of the recovery proves nothing).
            "scenarios": (
                ("deploy-wave", {"wave": 2, "down_s": 0.02}),
                ("crash-loop", {"crashes": 2, "restart_delay": 0.03}),
                ("health-flap", {"flaps": 2, "down_s": 0.1}),
                ("expiry-storm", {"victims": 3, "restart_delay": 0.03}),
                ("netem-episode", {"episodes": 1}),
                ("leader-kill", {"kills": 1, "down_s": 0.3}),
                ("rolling-upgrade", {"pause_s": 0.15}),
                ("partition-minority", {"hold_s": 0.4}),
                ("quorum-loss", {"hold_s": 0.4}),
                ("shard-kill", {"kills": 1}),
                ("reshard-wave", {"hold_s": 0.15}),
                ("overload-storm", {"storm_s": 1.5}),
            ),
        },
        # The quick trace also fronts the backends with a 2-shard serve
        # tier (ISSUE 12): every scenario's probes now include the
        # sharded resolve path, and the shard fault classes land in the
        # gated envelope (shard-kill measured; reshard-wave asserted
        # zero-error, so it never owns an outage window).
        "shards": 2,
        "scenarios": (
            ("deploy-wave", {"wave": 2, "down_s": 0.1}),
            ("crash-loop", {"crashes": 2, "restart_delay": 0.12}),
            ("health-flap", {"flaps": 2, "down_s": 0.1}),
            ("expiry-storm", {"victims": 3, "restart_delay": 0.12}),
            ("netem-episode", {"episodes": 1}),
            ("leader-kill", {"kills": 1, "down_s": 0.3}),
            ("rolling-upgrade", {"pause_s": 0.15}),
            ("partition-minority", {"hold_s": 0.4}),
            ("quorum-loss", {"hold_s": 0.4}),
            ("shard-kill", {"kills": 1}),
            ("reshard-wave", {"hold_s": 0.15}),
            ("overload-storm", {"storm_s": 1.5}),
        ),
    },
    "full": {
        "members": 10,
        "probe_interval": 0.05,
        "session_timeout_ms": 1500,
        "pause_s": 1.5,
        "ensemble": 3,
        "election_ms": 150.0,
        # The soak keeps its production-shaped 1.5 s sessions and its
        # reference scenario knobs; the levers there are the
        # client-side ones (raced connects, ping schedule, SWR) plus a
        # halved election window.
        "levers": {"election_ms": 75.0},
        "shards": 3,
        "scenarios": (
            ("deploy-wave", {"wave": 6, "down_s": 0.15}),
            ("crash-loop", {"crashes": 4, "restart_delay": 0.2}),
            ("health-flap", {"flaps": 4, "down_s": 0.15}),
            ("expiry-storm", {"victims": 5, "restart_delay": 0.2}),
            ("netem-episode", {"episodes": 2}),
            ("leader-kill", {"kills": 2, "down_s": 0.3}),
            ("rolling-upgrade", {"pause_s": 0.3}),
            ("partition-minority", {"hold_s": 0.8}),
            ("quorum-loss", {"hold_s": 0.8}),
            ("shard-kill", {"kills": 2}),
            ("reshard-wave", {"hold_s": 0.3}),
            ("overload-storm", {"storm_s": 2.0, "clients": 8}),
            ("deploy-wave", {"wave": 6, "down_s": 0.15}),
            ("expiry-storm", {"victims": 5, "restart_delay": 0.2}),
        ),
    },
}


async def run_trace(
    trace: str = "quick",
    seed: Optional[int] = None,
    repair: bool = True,
    scenarios: Optional[Sequence[Tuple[str, Dict[str, Any]]]] = None,
    levers: bool = False,
) -> Dict[str, Any]:
    """Drive one named trace end to end and return the SLO report.

    ``levers`` (ISSUE 20) turns on the harness availability levers AND
    applies the trace's ``"levers"`` timing overrides (tighter
    sessions/election/cadence); ``False`` runs the reference-exact r19
    envelope — same seed, so ``--prove-levers`` can diff the two."""
    if trace not in TRACES:
        raise ValueError(f"unknown trace {trace!r} (have {sorted(TRACES)})")
    params = dict(TRACES[trace])
    overrides = params.pop("levers", None)
    if levers and overrides:
        params.update(overrides)
    if seed is None:
        seed = random.randrange(2**32)
    harness = SLOHarness(
        members=params["members"],
        seed=seed,
        probe_interval=params["probe_interval"],
        session_timeout_ms=params["session_timeout_ms"],
        repair=repair,
        ensemble=params.get("ensemble", 1),
        election_ms=params.get("election_ms", 150.0),
        shards=params.get("shards", 0),
        levers=levers,
    )
    await harness.start()
    try:
        for fault_id, kwargs in (
            scenarios if scenarios is not None else params["scenarios"]
        ):
            await harness.run_scenario(fault_id, **kwargs)
            # Steady-state gap between scenarios: the availability
            # denominator includes healthy operation (a trace that is
            # 100% fault time measures the faults, not the service),
            # and the next scenario's windows start from health.
            await harness.settle(params.get("pause_s", 0.5))
        await harness.settle(max(0.2, 5 * params["probe_interval"]))
        report = harness.report(trace_name=trace)
        # Before stop(): the workers must still be alive to hand their
        # trace fragments over (ISSUE 13) — the worst-outage entry
        # carries one ASSEMBLED cross-process tree, not just trace ids.
        await harness.collect_worst_trace(report)
        return report
    finally:
        await harness.stop()
