"""An in-process ZooKeeper server for hermetic tests.

The reference's integration tests require a live ZooKeeper at
127.0.0.1:2181 (reference test/helper.js:57-62) — the single biggest
testing gap called out in SURVEY.md §4.  This module closes it: a real
asyncio TCP server speaking the ZooKeeper 3.4 client protocol (the same
subset our client uses), with genuine session semantics:

  * session establishment with timeout negotiation (clamped to
    [min_session_timeout, max_session_timeout]),
  * ephemeral nodes deleted when their owner session expires or closes,
  * session reattachment by (session_id, passwd) within the timeout,
  * one-shot watches (data / exists / children) with NodeCreated /
    NodeDeleted / NodeDataChanged / NodeChildrenChanged notifications,
  * zxid ordering across all write ops,
  * a real leader/follower replication protocol for ensembles (ISSUE
    10): quorum-gated writes ordered by the elected leader, elections
    with a configurable window, read-only minority mode behind the 3.4
    ``read_only`` handshake flag, committed-backlog catch-up for
    rejoining members, and leader-only session expiry (see
    :class:`_SharedState` and :class:`ZKEnsemble`).

Because the client under test talks to this server over an actual socket,
the full wire path (framing, jute encoding, xid bookkeeping, watch
dispatch) is exercised, not mocked.  Tests can also force failures:
:meth:`ZKServer.expire_session`, :meth:`ZKServer.drop_connections`, and
the ISSUE 3 state-corruption controls :meth:`ZKServer.corrupt_node`
(out-of-band payload overwrite) and :meth:`ZKServer.seize_node`
(ephemeralOwner rewrite) that mint the drift classes the reconciler
sweeps for.

Run standalone for manual end-to-end runs of the daemon:

    python -m registrar_tpu.testing.server --port 21811
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Set, Tuple

from registrar_tpu.events import spawn_owned
from registrar_tpu.zk import protocol as proto
from registrar_tpu.zk.framing import FrameReader
from registrar_tpu.zk.jute import Reader, Writer
from registrar_tpu.zk.protocol import Err, EventType, KeeperState, OpCode, Stat
from registrar_tpu.zk.quota import (
    LIMITS_LEAF,
    QUOTA_ROOT,
    STATS_LEAF,
    format_quota,
    parse_quota,
)

log = logging.getLogger("registrar_tpu.testing.server")


def _now_ms() -> int:
    return int(time.time() * 1000)


@dataclass
class ZNode:
    data: bytes = b""
    ephemeral_owner: int = 0
    children: Dict[str, "ZNode"] = field(default_factory=dict)
    czxid: int = 0
    mzxid: int = 0
    pzxid: int = 0
    ctime: int = 0
    mtime: int = 0
    version: int = 0
    cversion: int = 0
    aversion: int = 0
    acls: List[proto.ACL] = field(
        default_factory=lambda: list(proto.OPEN_ACL_UNSAFE)
    )

    def stat(self) -> Stat:
        return Stat(
            czxid=self.czxid,
            mzxid=self.mzxid,
            ctime=self.ctime,
            mtime=self.mtime,
            version=self.version,
            cversion=self.cversion,
            aversion=self.aversion,
            ephemeral_owner=self.ephemeral_owner,
            data_length=len(self.data),
            num_children=len(self.children),
            pzxid=self.pzxid,
        )

    def stat_packed(self) -> bytes:
        """The 68-byte wire Stat packed straight from the node fields —
        no :class:`Stat` dataclass intermediate (the EXISTS/GET_DATA
        reply fast lane under 1k–10k-znode sweeps, ISSUE 11).
        Byte-identity with ``self.stat()._packed()`` is pinned by
        tests/test_wire_golden.py."""
        return proto.pack_stat(
            self.czxid,
            self.mzxid,
            self.ctime,
            self.mtime,
            self.version,
            self.cversion,
            self.aversion,
            self.ephemeral_owner,
            len(self.data),
            len(self.children),
            self.pzxid,
        )


@dataclass
class Session:
    session_id: int
    passwd: bytes
    timeout_ms: int
    last_heard: float
    ephemerals: Set[str] = field(default_factory=set)
    conn: Optional["_Connection"] = None
    closed: bool = False
    # (scheme, id) identities granted via addauth on the *current*
    # connection — real ZK scopes auth to the connection, not the session,
    # so these are cleared when the carrying connection goes away.
    auth_ids: Set[Tuple[str, str]] = field(default_factory=set)

    @property
    def connected(self) -> bool:
        return self.conn is not None


#: sentinel returned by the sync GET_DATA fast lane when the request
#: must route through the async ``_dispatch`` (quota-stats refresh)
_SLOW_PATH = object()

#: quota subtree prefix (reads under it may rewrite the stats node)
_QUOTA_PREFIX = QUOTA_ROOT + "/"

#: Reply-batching caps: flush at least every this-many queued replies —
#: or this many queued bytes (a burst of big getData answers must not
#: buffer unboundedly; the per-reply drain this batching replaced was
#: also the memory backpressure) — even mid-burst (ZKServer._serve).
_MAX_QUEUED = 256
_MAX_QUEUED_BYTES = 1 << 20


def _event_frame(ev_type: int, path: str) -> bytes:
    """The framed watcher-notification packet — the ONE encoder for both
    single-target sends and the fan-out path."""
    w = Writer()
    proto.ReplyHeader(
        xid=proto.XID_NOTIFICATION, zxid=-1, err=Err.OK
    ).write(w)
    proto.WatcherEvent(
        type=ev_type, state=KeeperState.SYNC_CONNECTED, path=path
    ).write(w)
    return proto.frame(w.to_bytes())


class _Connection:
    """One client TCP connection (carries at most one session)."""

    def __init__(self, server: "ZKServer", reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.session: Optional[Session] = None
        self.closed = False
        peer = writer.get_extra_info("peername")
        self.peer_ip: Optional[str] = peer[0] if peer else None
        self._outbuf: List[bytes] = []
        self._outbytes = 0  # staged bytes (see queue_full)
        # Serializes writer.drain(): the serve loop and a watch fan-out
        # from another connection's task can drain concurrently, and
        # StreamWriter only supports multiple simultaneous drain waiters
        # on Python >= 3.11 (FlowControlMixin asserted a single waiter
        # before that).
        self._drain_lock = asyncio.Lock()

    def queue(self, payload: bytes) -> None:
        """Stage a reply for the next :meth:`flush`.

        The request loop queues replies while more pipelined requests
        are already buffered and flushes once per input burst — one
        send() syscall for a whole heartbeat sweep instead of one per
        reply.  Order with watch events is preserved because every path
        that emits a frame (send, send_event) drains this queue first.
        """
        framed = proto.frame(payload)
        self._outbuf.append(framed)
        self._outbytes += len(framed)

    def queue_full(self) -> bool:
        """True when the staged replies hit either batching cap — the
        request loop must flush even though the input burst continues."""
        return (
            len(self._outbuf) >= _MAX_QUEUED
            or self._outbytes >= _MAX_QUEUED_BYTES
        )

    def _write_out(self) -> None:
        """Join and write everything queued, counting packets_sent.

        ``packets_sent`` means *written to the transport* (real ZK's
        ``packetSent()`` increments when the packet leaves the outgoing
        queue, not on TCP delivery): counting here — the single point
        both the flush and fan-out paths funnel through — keeps frames
        on connections that die mid-burst counted, where the previous
        count-after-drain scheme leaked them (a closed connection's
        drain returned early and dropped its in-flight tally).
        """
        chunks, self._outbuf = self._outbuf, []
        self._outbytes = 0
        if not chunks:
            return
        try:
            self.writer.write(b"".join(chunks))
            self.server.packets_sent += len(chunks)
        except (ConnectionError, OSError):
            pass  # the follow-up drain() surfaces the loss and closes

    async def flush(self) -> None:
        if self.closed:
            self._outbuf.clear()
            self._outbytes = 0
            return
        self._write_out()
        await self.drain()

    async def send(self, payload: bytes) -> None:
        if self.closed:
            return
        self.queue(payload)
        await self.flush()

    def post_framed(self, framed: bytes) -> None:
        """Synchronously write an already-framed packet (behind any queued
        replies, preserving per-connection order); the caller awaits
        :meth:`drain` afterwards.  Lets a watch-event fan-out write every
        watcher back-to-back without interleaved awaits."""
        if self.closed:
            return
        self._outbuf.append(framed)
        self._write_out()

    async def drain(self) -> None:
        """Await transport flow control (accounting happens at
        :meth:`_write_out` — see its docstring for the packets_sent
        semantics)."""
        if self.closed:
            return
        async with self._drain_lock:
            try:
                await self.writer.drain()
            except (ConnectionError, OSError):
                await self.close()

    async def send_event(self, ev_type: int, path: str) -> None:
        self.post_framed(_event_frame(ev_type, path))
        await self.drain()

    async def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self.session is not None and self.session.conn is self:
            # Connection gone; the session lingers until its timeout, but
            # auth is per-connection (real ZK keeps authInfo on the cnxn) —
            # a reattaching client must replay addauth.
            self.session.conn = None
            self.session.auth_ids.clear()
        try:
            self.writer.close()
        except Exception:
            pass


# Watch kind -> which event types clear it.
_WATCH_DATA = "data"
_WATCH_EXIST = "exist"
_WATCH_CHILD = "child"

#: admin "four letter word" commands answered on the client port, like real
#: ZooKeeper (operator runbooks probe ensemble health with `ruok`/`srvr`/
#: `mntr` — e.g. the checks the reference's README pairs with zkCli.sh).
_FOUR_LETTER_WORDS = frozenset(
    w.encode()
    for w in (
        "ruok", "srvr", "stat", "mntr", "cons", "dump", "wchs", "isro",
        "wchc", "wchp", "envi", "conf",
    )
)

_SERVER_VERSION = "3.4.14-registrar-tpu-testing"

#: state-changing (quorum) opcodes gated by the replication write gate:
#: a read-only member answers them NOT_READONLY, a mid-election member
#: drops the connection.  SYNC is quorum-bound too — it flushes the
#: leader's pipeline, which a partitioned minority cannot reach.
_QUORUM_OPS = frozenset(
    (
        OpCode.CREATE, OpCode.DELETE, OpCode.SET_DATA, OpCode.SET_ACL,
        OpCode.MULTI, OpCode.SYNC,
    )
)


class _SharedState:
    """Replicated state an ensemble's members hold in common.

    Real ZooKeeper members replicate the znode tree, session table, and
    zxid via ZAB; an in-process ensemble models the *converged* result by
    letting every member operate on one state object (the tests all run
    in a single event loop, so each request applies atomically — the same
    linearizable history ZAB would produce).  Watches live here too: a
    write through member A must notify a watcher connected to member B,
    exactly as in a real ensemble.

    Members configured with ``apply_delay_ms`` opt out of instant
    convergence on the *read* side: their read view freezes at the
    pre-commit state when another member commits, and catches up on
    sync()/own-write/quiescence (see ZKServer.apply_delay_ms) — the
    stale-follower-read behavior sync() exists to fence.

    ISSUE 10 adds the replication *protocol* on top of the replicated
    state: one elected leader orders and commits writes (ZAB-style zxid
    ordering through :meth:`ZKServer._next_zxid`, which also appends to
    the committed-backlog ``log``), and a write is only admitted while
    the serving member can reach a leader holding **quorum**
    (``ensemble_size // 2 + 1`` live members in its partition group).  A
    member cut off from quorum degrades to ZooKeeper's read-only mode:
    its read view freezes (majority commits are invisible across a
    partition), the ``read_only`` handshake flag gates which clients may
    attach, and writes answer ``Err.NOT_READONLY``.  Elections take
    ``election_ms`` (members are ``looking`` and drop writers meanwhile,
    like real followers that lost their leader); only the leader expires
    sessions, so a quorum-less ensemble keeps every session — and its
    ephemerals — frozen until quorum returns, exactly the property the
    registrar fleet's "writes resume without operator action" recovery
    depends on.
    """

    def __init__(self) -> None:
        self.root = ZNode(czxid=0, ctime=_now_ms(), mtime=_now_ms())
        self.zxid = 0
        self.sessions: Dict[int, Session] = {}
        self.next_session = int(time.time()) << 24
        # path -> set of connections, per watch kind
        self.watches: Dict[str, Dict[str, Set[_Connection]]] = {
            _WATCH_DATA: {},
            _WATCH_EXIST: {},
            _WATCH_CHILD: {},
        }
        #: live members, so a commit through one can freeze the stale read
        #: view of members configured with an apply delay (see
        #: ZKServer.apply_delay_ms)
        self.members: Set["ZKServer"] = set()
        #: monotonic time of the newest commit — drives lagging members'
        #: quiescence-based catch-up
        self.last_commit = 0.0
        #: members currently configured with apply_delay_ms > 0.  Kept as
        #: a count (recomputed on membership/lag changes, which are rare)
        #: so the per-commit freeze scan in _next_zxid is skipped entirely
        #: in the common no-lag case — the write hot path must not pay for
        #: a feature no member uses (round-5 perf directive).
        self.lag_members = 0
        #: path -> zxid of its newest create, recorded only while a member
        #: is configured to lag and cleared once every member has caught
        #: up.  Lets _catch_up detect a node created *and deleted* within
        #: a lag window: the stale/live diff shows nothing, but a real
        #: follower applying the backlog would still fire the armed
        #: exists watch's NODE_CREATED (round-4 advisor finding).
        self.lag_creates: Dict[str, int] = {}
        # -- replication protocol (ISSUE 10); inert for standalone
        # -- servers, configured by ZKEnsemble ---------------------------
        #: configured member count (NOT live count: quorum arithmetic is
        #: over the configured ensemble, like real ZK's QuorumMaj)
        self.ensemble_size = 1
        #: writes need a leader that can reach this many live members
        self.quorum = 1
        #: election duration (ms): leader death -> new leader serving
        self.election_ms = 0.0
        #: the elected leader, or None (mid-election / quorum lost)
        self.leader: Optional["ZKServer"] = None
        #: monotonic deadline of the pending election; None = no pending
        self.election_due: Optional[float] = None
        #: monotonic stamp of the current election's start (MTTR math)
        self.election_started: Optional[float] = None
        #: completed elections (test/4lw observability)
        self.elections = 0
        #: member-connectivity partition groups as sets of server_ids;
        #: None = fully connected (set via ZKEnsemble.partition)
        self.groups: Optional[List[Set[int]]] = None
        #: committed-transaction backlog: (zxid, op, path) per commit,
        #: bounded — a rejoining member whose departure point fell off
        #: the tail must take a full snapshot instead of a diff replay
        #: (ZKEnsemble(backlog_max=...) sizes it)
        self.log: Deque[Tuple[int, str, str]] = deque(maxlen=512)
        ensure_system_nodes(self.root)

    def recount_lag(self) -> None:
        self.lag_members = sum(
            1 for m in self.members if m.apply_delay_ms > 0
        )

    # -- quorum / election (ISSUE 10) ----------------------------------------

    def _group_ids(self, server_id: int) -> Optional[Set[int]]:
        """The partition group containing ``server_id`` (None = all)."""
        if self.groups is None:
            return None
        for group in self.groups:
            if server_id in group:
                return group
        return {server_id}  # unlisted member: isolated

    def reachable(self, member: "ZKServer") -> List["ZKServer"]:
        """Live members ``member`` can talk to (its partition group)."""
        group = self._group_ids(member.server_id)
        return [
            m for m in self.members
            if group is None or m.server_id in group
        ]

    def _quorum_candidates(self) -> List["ZKServer"]:
        return [
            m for m in self.members if len(self.reachable(m)) >= self.quorum
        ]

    def reevaluate(self) -> None:
        """Recompute leadership and roles after a membership or
        partition change.

        A live leader that still reaches quorum keeps the crown (a
        rejoining follower never forces an election, like real ZK); a
        dead or isolated leader starts an election over the members that
        can still assemble quorum, completing after ``election_ms``
        (the sweep loops drive completion; 0 = instant).  With no quorum
        anywhere, every member degrades to read-only and the election
        stays parked until membership changes again.
        """
        lead = self.leader
        if (
            lead is not None
            and lead in self.members
            and len(self.reachable(lead)) >= self.quorum
        ):
            self.election_due = None
            self._assign_roles(lead)
            return
        self.leader = None
        candidates = self._quorum_candidates()
        if not candidates:
            # No quorum anywhere: park the election, everyone read-only.
            self.election_due = None
            self.election_started = None
            self._assign_roles(None)
            return
        now = time.monotonic()
        if self.election_due is None:
            self.election_started = now
            self.election_due = now + self.election_ms / 1000.0
            for member in self.members:
                member._set_role(
                    "looking" if member in candidates else "read-only"
                )
        if self.election_ms <= 0 or now >= self.election_due:
            self.complete_election()

    def complete_election(self) -> None:
        """Elect the most-caught-up candidate (highest applied zxid,
        ties to the lowest server_id — real ZK's epoch/zxid/sid order)."""
        self.election_due = None
        candidates = self._quorum_candidates()
        if not candidates:
            self._assign_roles(None)
            return
        leader = max(
            candidates, key=lambda m: (m._view_zxid(), -m.server_id)
        )
        self.elections += 1
        elapsed = (
            time.monotonic() - self.election_started
            if self.election_started is not None
            else 0.0
        )
        self.election_started = None
        log.debug(
            "member %d elected leader (election %d, %.0f ms)",
            leader.server_id, self.elections, elapsed * 1000.0,
        )
        self._assign_roles(leader)

    def _assign_roles(self, leader: Optional["ZKServer"]) -> None:
        self.leader = leader
        in_quorum = (
            set(self.reachable(leader)) if leader is not None else set()
        )
        for member in self.members:
            if member in in_quorum:
                member._set_role(
                    "leader" if member is leader else "follower"
                )
            else:
                member._set_role("read-only")


def ensure_system_nodes(root: ZNode) -> None:
    zk = root.children.setdefault("zookeeper", ZNode(ctime=_now_ms()))
    zk.children.setdefault("quota", ZNode(ctime=_now_ms()))


def _clone_tree(node: ZNode) -> ZNode:
    """Deep point-in-time copy of a znode subtree (a lagging member's
    frozen read view).  Immutable payloads (bytes) are shared; structure,
    stats, and ACL lists are copied."""
    return ZNode(
        data=node.data,
        ephemeral_owner=node.ephemeral_owner,
        children={k: _clone_tree(v) for k, v in node.children.items()},
        czxid=node.czxid,
        mzxid=node.mzxid,
        pzxid=node.pzxid,
        ctime=node.ctime,
        mtime=node.mtime,
        version=node.version,
        cversion=node.cversion,
        aversion=node.aversion,
        acls=list(node.acls),
    )


class ZKServer:
    """Single-node in-process ZooKeeper (see module docstring)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        min_session_timeout_ms: int = 100,
        max_session_timeout_ms: int = 60_000,
        tick_ms: int = 50,
        snapshot: Optional["ZKServer"] = None,
        shared: Optional[_SharedState] = None,
        server_id: int = 0,
        apply_delay_ms: int = 0,
    ):
        """``snapshot``: adopt another (stopped) server's tree, sessions,
        and zxid — models a real ensemble surviving a member restart, so
        rolling-restart scenarios (client reattaches, ephemerals survive)
        are testable.  Session expiry countdowns restart from now.

        ``shared``: join a live ensemble's replicated state (see
        :class:`ZKEnsemble`); mutually exclusive with ``snapshot``.

        ``apply_delay_ms``: model a lagging follower.  When > 0, a commit
        made through any *other* member freezes this member's read view
        at the pre-commit state; reads served here stay stale until the
        member catches up — on ``sync()`` through it (the client-visible
        barrier real ZooKeeper's sync provides), on a write it serves
        itself (ZooKeeper's read-your-writes guarantee: a follower applies
        a commit before acking it to the issuing client), or once the
        commit stream has been quiescent for ``apply_delay_ms`` (the
        sweeper's batch catch-up; under continuous churn the member stays
        behind, as a saturated real follower would).  Watches still fire
        from the replicated state, which may notify a client of a change
        its next read does not show yet — the same reordering a real
        follower's event pipeline can exhibit.  See ZKEnsemble.set_lag.
        """
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.min_session_timeout_ms = min_session_timeout_ms
        self.max_session_timeout_ms = max_session_timeout_ms
        self.tick_ms = tick_ms
        self.server_id = server_id
        #: the member's replication role, reported by the srvr/mntr admin
        #: words and enforced by the write gate: "standalone" (no
        #: ensemble), "leader" / "follower" (in quorum), "read-only"
        #: (minority / quorum lost), "looking" (mid-election).  Assigned
        #: by _SharedState.reevaluate for ensemble members.
        self.mode = "standalone"
        self._is_ensemble_member = shared is not None
        if snapshot is not None and shared is not None:
            raise ValueError("snapshot= and shared= are mutually exclusive")
        if snapshot is not None:
            if snapshot._server is not None:
                raise ValueError(
                    "snapshot donor must be stopped first (its tree and "
                    "sessions are adopted by reference)"
                )
            if snapshot._is_ensemble_member:
                # The donor's state is the ensemble's live shared state;
                # adopting it would alias a running ensemble (and the watch
                # reset below would wipe the live members' watch tables).
                raise ValueError(
                    "cannot adopt an ensemble member as a snapshot donor; "
                    "use ZKEnsemble.restart() to rejoin the ensemble"
                )
            self._state = snapshot._state
            # The donor is stopped, so every watch-holding connection is
            # dead; start from a clean watch table.
            self._state.watches = {
                _WATCH_DATA: {},
                _WATCH_EXIST: {},
                _WATCH_CHILD: {},
            }
            self._adopted_sessions = True
            for sess in self.sessions.values():
                sess.conn = None
        elif shared is not None:
            self._state = shared
        else:
            self._state = _SharedState()
        self._server: Optional[asyncio.AbstractServer] = None
        self._sweeper: Optional[asyncio.Task] = None
        self._conns: Set[_Connection] = set()
        # Fire-and-forget fan-out tasks (lag-watch reconciliation).  The
        # event loop only weak-references running tasks, so a discarded
        # create_task() handle can be garbage-collected mid-flight; this
        # set owns them until done, and stop() cancels stragglers.
        self._bg_tasks: Set[asyncio.Task] = set()
        #: number of sessions expired by the sweeper (test observability)
        self.expired_count = 0
        #: connections refused because the client had seen a newer zxid
        #: than this member's view (test observability)
        self.refused_count = 0
        #: handshakes refused because this member is read-only and the
        #: client did not offer the read_only flag (test observability)
        self.refused_ro = 0
        #: handshakes refused because this member was mid-election
        #: ("looking"; test observability — distinct from refused_count's
        #: newer-zxid refusals)
        self.refused_looking = 0
        #: write requests answered NOT_READONLY while read-only
        self.writes_refused = 0
        #: write connections dropped mid-election ("looking")
        self.election_drops = 0
        #: commits this member ordered while leader (ZAB observability)
        self.commits = 0
        #: writes this member forwarded to the leader while follower
        self.forwarded_writes = 0
        #: catch-up bookkeeping: committed-backlog txns replayed on
        #: rejoin/catch-up, and full-snapshot restores (backlog truncated
        #: past the member's departure point)
        self.catchup_replayed = 0
        self.catchup_snapshots = 0
        #: soft-quota violations logged by this member (test observability)
        self.quota_warnings = 0
        #: request/reply counters surfaced via the 4lw admin commands.
        #: packets_sent counts frames *written* to the transport (real
        #: ZK's packetSent(), incremented as the packet leaves the
        #: outgoing queue), not frames the peer provably received.
        self.packets_received = 0
        self.packets_sent = 0
        # While a multi transaction applies, watch events queue here so the
        # apply loop never awaits (no other connection's request can
        # interleave with a half-applied transaction); flushed on commit.
        self._deferred_events: Optional[List[tuple]] = None
        #: when True, requests are read but never answered (still counted as
        #: session liveness) — simulates a wedged-but-connected server for
        #: client watchdog tests
        self.freeze = False
        #: replication lag (see __init__ docstring); mutable at runtime
        self.apply_delay_ms = apply_delay_ms
        #: frozen stale read view while behind; None = caught up
        self._lag_root: Optional[ZNode] = None
        #: the zxid the frozen view corresponds to (stamped on replies
        #: while lagging); meaningful only when _lag_root is not None
        self._lag_zxid = 0
        #: watches armed against the stale view — each may guard a
        #: transition that already committed, so catch-up must deliver
        #: the missed event (real ZK fires it when the follower applies
        #: the txn); list of (kind, path, conn)
        self._lag_watches: List[Tuple[str, str, _Connection]] = []

    # -- replicated state (delegates to _SharedState so ensemble members
    # -- converge by construction; standalone servers own a private one) ----

    @property
    def root(self) -> ZNode:
        return self._state.root

    @root.setter
    def root(self, value: ZNode) -> None:
        self._state.root = value

    @property
    def zxid(self) -> int:
        return self._state.zxid

    @zxid.setter
    def zxid(self, value: int) -> None:
        self._state.zxid = value

    @property
    def sessions(self) -> Dict[int, Session]:
        return self._state.sessions

    @sessions.setter
    def sessions(self, value: Dict[int, Session]) -> None:
        self._state.sessions = value

    @property
    def _next_session(self) -> int:
        return self._state.next_session

    @_next_session.setter
    def _next_session(self, value: int) -> None:
        self._state.next_session = value

    @property
    def _watches(self) -> Dict[str, Dict[str, Set["_Connection"]]]:
        return self._state.watches

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "ZKServer":
        if getattr(self, "_adopted_sessions", False):
            # Expiry countdowns restart when service resumes, not at
            # construction — a gap between __init__ and start() must not
            # expire adopted sessions.
            now = time.monotonic()
            for sess in self.sessions.values():
                sess.last_heard = now
            self._adopted_sessions = False
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._state.members.add(self)
        self._state.recount_lag()
        if self._is_ensemble_member:
            # Joining member: roles recompute (a live leader keeps the
            # crown; a quorum-less ensemble may become electable again).
            self._state.reevaluate()
        self._sweeper = asyncio.create_task(self._sweep_loop())
        log.debug("ZKServer listening on %s:%d", self.host, self.port)
        return self

    def _spawn(self, coro) -> "asyncio.Task":
        """Run a fire-and-forget coroutine as an owned background task
        (cancelled by stop(), unlike emit()'s dispatch tasks)."""
        return spawn_owned(coro, self._bg_tasks)

    async def stop(self) -> None:
        self._state.members.discard(self)
        self._state.recount_lag()
        if self._is_ensemble_member:
            # Departing member: a dead leader triggers an election; a
            # death that breaks quorum degrades the survivors to
            # read-only (their write gate starts refusing).
            self._state.reevaluate()
        if self._sweeper:
            self._sweeper.cancel()
            try:
                await self._sweeper
            except asyncio.CancelledError:
                pass
        for task in list(self._bg_tasks):
            task.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        for conn in list(self._conns):
            await conn.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None  # marks this instance as a valid snapshot donor

    async def __aenter__(self) -> "ZKServer":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    # -- replication roles (ISSUE 10) ----------------------------------------

    def _freeze_view(self) -> None:
        """Pin this member's read view at the current replicated state
        (used on entering read-only: majority commits made across a
        partition must be invisible here until the member rejoins)."""
        if self._lag_root is None:
            self._lag_root = _clone_tree(self._state.root)
            self._lag_zxid = self._state.zxid

    def _set_role(self, role: str) -> None:
        """Apply a role transition computed by the shared-state election.

        Entering ``read-only`` freezes the read view and drops every
        client connection (real ZK restarts the server in ro mode; the
        surviving clients must renegotiate with the ``read_only``
        handshake flag or fail over).  Leaving it catches the member up
        — counted as backlog replay or a snapshot restore — and drops
        connections again so ro sessions renegotiate to read-write.
        Leader/follower churn keeps connections: a follower serving a
        client does not care which member orders the commits.
        """
        old = self.mode
        if old == role:
            return
        self.mode = role
        if role == "read-only":
            self._freeze_view()
            if old in ("leader", "follower", "looking"):
                self._spawn(self.drop_connections())
            log.debug("member %d degraded to read-only", self.server_id)
        elif old == "read-only":
            self._count_catchup()
            self._catch_up()
            self._spawn(self.drop_connections())
            log.debug(
                "member %d rejoined quorum as %s", self.server_id, role
            )

    def _count_catchup(self) -> None:
        """Account a frozen (read-only) member's pending catch-up from
        its applied zxid — the rejoin-after-partition shape."""
        if self._lag_root is not None:
            self.catchup_from(self._lag_zxid)

    def catchup_from(self, departed_zxid: Optional[int]) -> None:
        """Account a rejoin sync from ``departed_zxid`` — the ONE copy
        of the classification rule, shared by restart-after-kill
        (ZKEnsemble.restart) and partition-heal (_count_catchup): diff
        replay when the committed backlog still covers the departure
        point, else a full snapshot restore (real ZK's DIFF vs SNAP)."""
        if departed_zxid is None or self._state.zxid <= departed_zxid:
            return
        backlog = self._state.log
        if backlog and backlog[0][0] <= departed_zxid + 1:
            self.catchup_replayed += sum(
                1 for zxid, _, _ in backlog if zxid > departed_zxid
            )
        else:
            # The departure point fell off the bounded backlog: a real
            # member would transfer a full snapshot (SNAP sync).
            self.catchup_snapshots += 1

    def _write_gate(self) -> str:
        """Admission verdict for a state-changing request on this member:
        ``"ok"`` (leader reachable with quorum — commit proceeds),
        ``"ro"`` (read-only: answer NOT_READONLY), ``"drop"``
        (mid-election: drop the connection, like a follower that lost
        its leader)."""
        if not self._is_ensemble_member:
            return "ok"
        if self.mode in ("leader", "follower"):
            return "ok"
        if self.mode == "looking":
            return "drop"
        return "ro"

    # -- test controls ------------------------------------------------------

    async def expire_session(self, session_id: int) -> None:
        """Force-expire a session (kills its connection, drops ephemerals)."""
        sess = self.sessions.get(session_id)
        if sess is None:
            return
        await self._expire(sess)

    async def drop_connections(self) -> None:
        """Sever all client TCP connections without expiring sessions."""
        for conn in list(self._conns):
            await conn.close()

    async def corrupt_node(self, path: str, data: bytes) -> None:
        """Overwrite a znode's payload out-of-band (ISSUE 3 control).

        Models an operator's ``zkcli set`` / a tool clobbering a record:
        a genuine setData (version bump, mzxid, data watches fire), just
        not issued by the owner — exactly the drift the reconciler's
        ``payload``/``staleService`` sweep exists to catch.  Raises
        ZKError(NO_NODE) when the path does not exist.
        """
        await self._set_data_node(path, data, -1)

    def seize_node(self, path: str, owner: int) -> None:
        """Rewrite a node's ephemeralOwner (ISSUE 3 control).

        Models the ownership corruptions a live run can be left with — a
        zombie predecessor's stale znode (owner = a dead/foreign session
        id), or a node flattened to persistent (owner = 0) by a bad
        restore.  Session ephemeral-sets are kept coherent so the expiry
        sweeper's behavior stays honest.  KeyError when the path does
        not exist.
        """
        node = self._resolve(path)
        if node.ephemeral_owner:
            prev = self.sessions.get(node.ephemeral_owner)
            if prev is not None:
                prev.ephemerals.discard(path)
        node.ephemeral_owner = owner
        if owner:
            sess = self.sessions.get(owner)
            if sess is not None:
                sess.ephemerals.add(path)

    def get_node(self, path: str) -> Optional[ZNode]:
        """Direct tree access for assertions (bypasses the protocol)."""
        try:
            return self._resolve(path)
        except KeyError:
            return None

    def dump_tree(self, path: str = "/") -> Dict[str, bytes]:
        """Flat {path: data} map of the subtree at ``path`` (tooling/tests)."""
        out: Dict[str, bytes] = {}

        def walk(node: ZNode, prefix: str) -> None:
            out[prefix or "/"] = node.data
            for name, child in sorted(node.children.items()):
                walk(child, f"{prefix}/{name}")

        try:
            start = self._resolve(path)
        except KeyError:
            return out
        walk(start, "" if path == "/" else path.rstrip("/"))
        return out

    # -- disk snapshots ------------------------------------------------------
    #
    # Real ZooKeeper persists its tree in snapshot + txlog files so a
    # restarted member comes back with the same data, zxid, and session
    # table (sessions then expire normally unless their clients reattach).
    # The standalone dev server models that with a single JSON snapshot:
    # save on shutdown, load on start.  Like the in-memory ``snapshot=``
    # donor, loaded sessions resume disconnected with their expiry
    # countdown restarted.

    def save_snapshot(self, path: str) -> None:
        """Atomically write the tree + session table + zxid to ``path``."""
        import base64
        import json

        nodes = []

        def walk(node: ZNode, prefix: str) -> None:
            nodes.append(
                {
                    "path": prefix or "/",
                    "data": base64.b64encode(node.data).decode(),
                    "ephemeral_owner": node.ephemeral_owner,
                    "czxid": node.czxid,
                    "mzxid": node.mzxid,
                    "pzxid": node.pzxid,
                    "ctime": node.ctime,
                    "mtime": node.mtime,
                    "version": node.version,
                    "cversion": node.cversion,
                    "aversion": node.aversion,
                    "acls": [
                        {"perms": a.perms, "scheme": a.scheme, "id": a.id}
                        for a in node.acls
                    ],
                }
            )
            for name, child in sorted(node.children.items()):
                walk(child, f"{prefix}/{name}")

        walk(self.root, "")
        payload = {
            "format": 1,
            "zxid": self.zxid,
            "next_session": self._next_session,
            "sessions": [
                {
                    "session_id": s.session_id,
                    "passwd": base64.b64encode(s.passwd).decode(),
                    "timeout_ms": s.timeout_ms,
                    "ephemerals": sorted(s.ephemerals),
                }
                for s in self.sessions.values()
                if not s.closed
            ],
            "nodes": nodes,
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load_snapshot(self, path: str) -> None:
        """Replace this (not-yet-started) server's state from a snapshot."""
        import base64
        import json

        if self._server is not None:
            raise RuntimeError("load_snapshot before start()")
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
        if payload.get("format") != 1:
            raise ValueError(f"unknown snapshot format {payload.get('format')!r}")

        self.zxid = int(payload["zxid"])
        self._next_session = int(payload["next_session"])
        self.root = ZNode()
        for entry in payload["nodes"]:
            node = ZNode(
                data=base64.b64decode(entry["data"]),
                ephemeral_owner=int(entry["ephemeral_owner"]),
                czxid=int(entry["czxid"]),
                mzxid=int(entry["mzxid"]),
                pzxid=int(entry["pzxid"]),
                ctime=int(entry["ctime"]),
                mtime=int(entry["mtime"]),
                version=int(entry["version"]),
                cversion=int(entry["cversion"]),
                aversion=int(entry["aversion"]),
                acls=[
                    proto.ACL(a["perms"], a["scheme"], a["id"])
                    for a in entry["acls"]
                ],
            )
            p = entry["path"]
            if p == "/":
                node.children = self.root.children
                self.root = node
                continue
            parent_path, name = self._split(p)
            self._resolve(parent_path).children[name] = node  # parents first
        ensure_system_nodes(self.root)  # snapshots may predate /zookeeper
        self.sessions = {}
        for s in payload["sessions"]:
            sess = Session(
                session_id=int(s["session_id"]),
                passwd=base64.b64decode(s["passwd"]),
                timeout_ms=int(s["timeout_ms"]),
                last_heard=time.monotonic(),
                ephemerals=set(s["ephemerals"]),
            )
            self.sessions[sess.session_id] = sess
        # Countdowns restart when service resumes (same as the in-memory
        # snapshot donor path in start()).
        self._adopted_sessions = True

    # -- 4-letter-word admin commands ---------------------------------------

    def _count_nodes(self) -> Tuple[int, int]:
        """(znode count, approximate data size) over this member's read
        view — a lagging follower reports what it has applied, like real
        ZooKeeper's stats."""
        count, size = 0, 0
        stack = [self._lag_root if self._lag_root is not None else self.root]
        while stack:
            node = stack.pop()
            count += 1
            size += len(node.data)
            stack.extend(node.children.values())
        return count, size

    def _watch_stats(self) -> Tuple[int, int]:
        """(total watch registrations, distinct watched paths)."""
        total, paths = 0, set()
        for kind in self._watches.values():
            for path, conns in kind.items():
                total += len(conns)
                paths.add(path)
        return total, len(paths)

    def _four_letter(self, cmd: str) -> str:
        """Answer an admin command with real-ZooKeeper-shaped text."""
        if cmd == "ruok":
            return "imok"
        if cmd == "isro":
            # "ro" for a read-only (minority) member — and mid-election,
            # when the member cannot admit writers either; the client's
            # rw-probe uses this to find a serving read-write member.
            return (
                "ro" if self.mode in ("read-only", "looking") else "rw"
            )
        nodes, data_size = self._count_nodes()
        watches, watched_paths = self._watch_stats()
        if cmd == "srvr" or cmd == "stat":
            lines = []
            if cmd == "stat":
                lines.append(f"Zookeeper version: {_SERVER_VERSION}")
                lines.append("Clients:")
                for conn in self._conns:
                    peer = conn.writer.get_extra_info("peername") or ("?", 0)
                    sid = conn.session.session_id if conn.session else 0
                    lines.append(f" /{peer[0]}:{peer[1]}[1](sid=0x{sid:x})")
                lines.append("")
            else:
                lines.append(f"Zookeeper version: {_SERVER_VERSION}")
            lines += [
                "Latency min/avg/max: 0/0/0",
                f"Received: {self.packets_received}",
                f"Sent: {self.packets_sent}",
                f"Connections: {len(self._conns)}",
                "Outstanding: 0",
                # a lagging follower reports the zxid it has applied up
                # to (real ZK's lastProcessedZxid), so `admin srvr`
                # against each member makes replication lag visible
                f"Zxid: 0x{self._view_zxid():x}",
                f"Mode: {self.mode}",
                f"Node count: {nodes}",
            ]
            if self._is_ensemble_member:
                # Election/quorum observability (ISSUE 10): operators and
                # tests read the member's real role, applied zxid (the
                # Zxid line above), and quorum shape off one probe.
                lines += [
                    f"Quorum size: {self._state.quorum}",
                    f"Ensemble size: {self._state.ensemble_size}",
                    f"Elections: {self._state.elections}",
                ]
            return "\n".join(lines) + "\n"
        if cmd == "mntr":
            ephemerals = sum(len(s.ephemerals) for s in self.sessions.values())
            rows = [
                ("zk_version", _SERVER_VERSION),
                ("zk_avg_latency", 0),
                ("zk_packets_received", self.packets_received),
                ("zk_packets_sent", self.packets_sent),
                ("zk_num_alive_connections", len(self._conns)),
                ("zk_outstanding_requests", 0),
                ("zk_server_state", self.mode),
                ("zk_znode_count", nodes),
                ("zk_watch_count", watches),
                ("zk_ephemerals_count", ephemerals),
                ("zk_approximate_data_size", data_size),
                ("zk_expired_sessions", self.expired_count),
            ]
            if self._is_ensemble_member:
                rows += [
                    ("zk_quorum_size", self._state.quorum),
                    ("zk_ensemble_size", self._state.ensemble_size),
                    ("zk_applied_zxid", self._view_zxid()),
                    ("zk_elections", self._state.elections),
                    ("zk_write_refusals", self.writes_refused),
                    ("zk_leader_commits", self.commits),
                    ("zk_forwarded_writes", self.forwarded_writes),
                    ("zk_catchup_replayed_txns", self.catchup_replayed),
                    ("zk_catchup_snapshot_loads", self.catchup_snapshots),
                ]
            return "".join(f"{k}\t{v}\n" for k, v in rows)
        if cmd == "cons":
            lines = []
            for conn in self._conns:
                peer = conn.writer.get_extra_info("peername") or ("?", 0)
                sid = conn.session.session_id if conn.session else 0
                timeout = conn.session.timeout_ms if conn.session else 0
                lines.append(
                    f" /{peer[0]}:{peer[1]}[1]"
                    f"(sid=0x{sid:x},to={timeout})"
                )
            return "\n".join(lines) + "\n"
        if cmd == "dump":
            lines = ["SessionTracker dump:", f"Session Sets ({len(self.sessions)}):"]
            for sid, sess in sorted(self.sessions.items()):
                lines.append(f"0x{sid:x}\t{sess.timeout_ms}ms")
            lines.append("ephemeral nodes dump:")
            with_eph = {
                sid: s for sid, s in self.sessions.items() if s.ephemerals
            }
            lines.append(f"Sessions with Ephemerals ({len(with_eph)}):")
            for sid, sess in sorted(with_eph.items()):
                lines.append(f"0x{sid:x}:")
                lines.extend(f"\t{p}" for p in sorted(sess.ephemerals))
            return "\n".join(lines) + "\n"
        if cmd in ("wchc", "wchp"):
            # One traversal of the watch tables yields (sid, path) pairs;
            # wchc groups by session, wchp by path (like real ZK).
            pairs = {
                (c.session.session_id if c.session else 0, path)
                for kind in self._watches.values()
                for path, conns in kind.items()
                for c in conns
            }
            grouped: Dict[object, Set[object]] = {}
            for sid, path in pairs:
                key, member = (sid, path) if cmd == "wchc" else (path, sid)
                grouped.setdefault(key, set()).add(member)

            def show(v: object) -> str:
                return f"0x{v:x}" if isinstance(v, int) else str(v)

            # Keys are homogeneous per command (ints for wchc, paths for
            # wchp), so plain sorted() orders sessions numerically; show()
            # is formatting only.
            lines = []
            for key in sorted(grouped):
                lines.append(show(key))
                lines.extend(f"\t{show(m)}" for m in sorted(grouped[key]))
            return "\n".join(lines) + "\n"
        if cmd == "envi":
            import platform
            import sys as _sys

            rows = [
                ("zookeeper.version", _SERVER_VERSION),
                ("host.name", platform.node()),
                ("os.name", platform.system()),
                ("os.arch", platform.machine()),
                ("python.version", platform.python_version()),
                ("python.executable", _sys.executable),
            ]
            return "Environment:\n" + "".join(
                f"{k}={v}\n" for k, v in rows
            )
        if cmd == "conf":
            rows = [
                ("clientPort", self.port),
                ("minSessionTimeout", self.min_session_timeout_ms),
                ("maxSessionTimeout", self.max_session_timeout_ms),
                ("tickTime", self.tick_ms),
                ("serverId", self.server_id),
            ]
            return "".join(f"{k}={v}\n" for k, v in rows)
        if cmd == "wchs":
            conns_watching = len(
                {
                    id(c)
                    for kind in self._watches.values()
                    for conns in kind.values()
                    for c in conns
                }
            )
            return (
                f"{conns_watching} connections watching {watched_paths} paths\n"
                f"Total watches:{watches}\n"
            )
        return ""  # unreachable: _FOUR_LETTER_WORDS gates entry

    # -- session sweeper ----------------------------------------------------

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(self.tick_ms / 1000.0)
            now = time.monotonic()
            # A pending election completes after election_ms (driven by
            # whichever member's sweeper ticks first — deterministic to
            # within one tick, which the tests budget for).
            st = self._state
            if st.election_due is not None and now >= st.election_due:
                st.complete_election()
            # Lagging member batch catch-up: once the commit stream has
            # been quiescent for apply_delay_ms, the member applies its
            # backlog (real followers stream commits; quiescence-gating is
            # what keeps the frozen view a true point-in-time prefix).
            if (
                self._lag_root is not None
                and self.apply_delay_ms > 0
                and now - self._state.last_commit >= self.apply_delay_ms / 1000.0
            ):
                self._catch_up()
            # Only the LEADER expires sessions (real ZK's session tracker
            # lives on the leader): a quorum-less ensemble keeps every
            # session — and its ephemerals — frozen until quorum returns,
            # so a fleet riding out an outage through a read-only member
            # resumes with the same sessions.  Standalone servers sweep
            # as before.
            if self._is_ensemble_member and st.leader is not self:
                continue
            for sess in list(self.sessions.values()):
                # A live connection keeps the session alive via pings; the
                # expiry countdown only runs while disconnected (matching
                # real ZK, where the leader hears session pings).
                if sess.connected:
                    continue
                if now - sess.last_heard > sess.timeout_ms / 1000.0:
                    self.expired_count += 1
                    await self._expire(sess)

    async def _expire(self, sess: Session) -> None:
        if sess.closed:
            return  # another ensemble member's sweeper got here first
        log.debug("expiring session 0x%x", sess.session_id)
        sess.closed = True
        self.sessions.pop(sess.session_id, None)
        if sess.conn is not None:
            # Real ZK notifies an attached client of expiry then drops it.
            await sess.conn.send_event(EventType.NONE, "")
            conn, sess.conn = sess.conn, None
            await conn.close()
        await self._remove_ephemerals(sess)

    async def _remove_ephemerals(self, sess: Session) -> None:
        for path in sorted(sess.ephemerals, key=len, reverse=True):
            try:
                await self._delete_node(path)
            except KeyError:
                pass
        sess.ephemerals.clear()

    # -- quotas (real ZK 3.4 semantics: soft limits under /zookeeper/quota,
    # -- violations logged, never enforced) ----------------------------------

    def _subtree_usage(self, path: str) -> Tuple[int, int]:
        """(znode count, total data bytes) of the subtree at ``path``."""
        try:
            start = self._resolve(path)
        except KeyError:
            return (0, 0)
        count, size = 0, 0
        stack = [start]
        while stack:
            node = stack.pop()
            count += 1
            size += len(node.data)
            stack.extend(node.children.values())
        return (count, size)

    def _governing_quota(self, path: str) -> Optional[Tuple[str, Dict[str, int]]]:
        """The quota target governing ``path``, if any: walk the path's
        prefixes looking for /zookeeper/quota<prefix>/zookeeper_limits
        (setquota forbids nesting, so at most one governs)."""
        if path == "/" or path == "/zookeeper" or path.startswith("/zookeeper/"):
            return None
        comps = path.strip("/").split("/")
        quota_node = self.get_node(QUOTA_ROOT)
        if quota_node is None or not quota_node.children:
            return None
        node = quota_node
        prefix = ""
        for comp in comps:
            node = node.children.get(comp)
            if node is None:
                return None
            prefix += "/" + comp
            limits = node.children.get(LIMITS_LEAF)
            if limits is not None:
                return (prefix, parse_quota(limits.data))
        return None

    def _check_quota(self, path: str) -> None:
        """After a write under a quota'd subtree, log (never fail) when the
        limit is exceeded — real ZK's soft enforcement."""
        governing = self._governing_quota(path)
        if governing is None:
            return
        target, limits = governing
        count, nbytes = self._subtree_usage(target)
        if limits["count"] >= 0 and count > limits["count"]:
            self.quota_warnings += 1
            log.warning(
                "Quota exceeded: %s count=%d limit=%d",
                target, count, limits["count"],
            )
        if limits["bytes"] >= 0 and nbytes > limits["bytes"]:
            self.quota_warnings += 1
            log.warning(
                "Quota exceeded: %s bytes=%d limit=%d",
                target, nbytes, limits["bytes"],
            )

    async def _refresh_quota_stats(self, path: str) -> None:
        """Serve live usage from a .../zookeeper_stats read — the lazy
        equivalent of real ZK updating the stats node on every write,
        applied as a genuine setData (version bump + data watches) so
        stat/watch semantics on the stats node stay honest."""
        if not (
            path.startswith(QUOTA_ROOT + "/") and path.endswith("/" + STATS_LEAF)
        ):
            return
        target = path[len(QUOTA_ROOT): -len("/" + STATS_LEAF)]
        try:
            node = self._resolve(path)
        except KeyError:
            return
        count, nbytes = self._subtree_usage(target)
        data = format_quota(count, nbytes)
        if data != node.data:
            await self._set_data_node(path, data, -1)

    # -- tree ops -----------------------------------------------------------

    def _resolve(self, path: str, root: Optional[ZNode] = None) -> ZNode:
        node = root if root is not None else self.root
        if path == "/":
            return node
        for comp in path.strip("/").split("/"):
            node = node.children[comp]  # KeyError -> NO_NODE
        return node

    def _resolve_read(self, path: str) -> ZNode:
        """Resolve against this member's *read view*: the frozen stale
        tree while lagging behind the replicated state, else the live
        tree.  Write paths always use :meth:`_resolve` (commits go to the
        replicated state, as a real follower forwards them to the
        leader)."""
        return self._resolve(
            path, self._lag_root if self._lag_root is not None else self.root
        )

    def _split(self, path: str) -> Tuple[str, str]:
        parent, _, name = path.rpartition("/")
        return (parent or "/", name)

    def _next_zxid(self, op: str = "", path: str = "") -> int:
        # A commit is about to apply to the replicated state: every other
        # live member configured to lag, and currently caught up, freezes
        # its read view at the pre-commit state.  (The committing member
        # itself never freezes — a follower applies a commit before acking
        # it, preserving read-your-writes.)  Guarded by the shared lag
        # count so the no-lag configuration — every production-shaped
        # bench and test — pays nothing for the lag model on its write
        # hot path.
        if self._state.lag_members:
            for member in self._state.members:
                if (
                    member is not self
                    and member.apply_delay_ms > 0
                    and member._lag_root is None
                ):
                    member._lag_root = _clone_tree(self._state.root)
                    member._lag_zxid = self._state.zxid
        self.zxid += 1
        self._state.last_commit = time.monotonic()
        if self._is_ensemble_member:
            # ZAB bookkeeping: the LEADER orders and commits every write
            # (a serving follower forwards — here, the shared state makes
            # the forward a direct commit through the same zxid order);
            # the bounded committed backlog feeds rejoin catch-up.
            leader = self._state.leader
            if leader is not None:
                leader.commits += 1
                if leader is not self:
                    self.forwarded_writes += 1
            self._state.log.append((self.zxid, op, path))
        return self.zxid

    def _view_zxid(self) -> int:
        """The zxid this member's read view corresponds to — the frozen
        pre-commit zxid while lagging, else the replicated zxid."""
        return self._lag_zxid if self._lag_root is not None else self.zxid

    def _catch_up(self) -> None:
        """Apply the replicated state up to now: drop the stale read view.

        Watches armed against the stale view guard transitions that may
        already have committed (their events fired before the watch
        existed); real ZooKeeper's never-miss-a-transition guarantee
        means the follower delivers them when it applies the txns, so
        compare each armed path's stale state against the live tree and
        synthesize the missed event — the same reconciliation the
        SetWatches handler performs for reconnecting clients.

        A read-only member never catches up here: across a partition the
        majority's commits are unreachable, so its view stays frozen
        until the election machinery readmits it (``_set_role`` flips
        the role back first, then drives this catch-up).
        """
        if self._lag_root is None or self.mode == "read-only":
            return
        stale_root, self._lag_root = self._lag_root, None
        frozen_zxid = self._lag_zxid
        pending, self._lag_watches = self._lag_watches, []
        for kind, path, conn in pending:
            if conn.closed:
                continue
            # Only reconcile watches still armed: a watch the live
            # commit path already fired (popping it from the shared
            # table) must not deliver twice — one-shot semantics.  This
            # also collapses duplicate _lag_watches entries.
            holders = self._watches[kind].get(path)
            if holders is None or conn not in holders:
                continue
            try:
                live: Optional[ZNode] = self._resolve(path)
            except KeyError:
                live = None
            try:
                stale: Optional[ZNode] = self._resolve(path, stale_root)
            except KeyError:
                stale = None
            # A create logged after the freeze while the stale view had
            # the node means a delete+recreate happened inside the lag
            # window: the first backlog event an armed watch is owed is
            # the NODE_DELETED (one-shot watches consume it), not the
            # net data/children diff.
            recreated = (
                stale is not None
                and self._state.lag_creates.get(path, -1) > frozen_zxid
            )
            ev: Optional[int] = None
            if kind == _WATCH_EXIST:
                if live is not None:
                    ev = EventType.NODE_CREATED
                elif self._state.lag_creates.get(path, -1) > frozen_zxid:
                    # Created then deleted entirely inside the lag
                    # window: the stale/live diff is empty, but the
                    # backlog contains the create this watch is owed.
                    ev = EventType.NODE_CREATED
            elif kind == _WATCH_DATA:
                if live is None or recreated:
                    ev = EventType.NODE_DELETED
                elif stale is not None and live.mzxid != stale.mzxid:
                    ev = EventType.NODE_DATA_CHANGED
            elif kind == _WATCH_CHILD:
                if live is None or recreated:
                    ev = EventType.NODE_DELETED
                elif stale is not None and live.cversion != stale.cversion:
                    ev = EventType.NODE_CHILDREN_CHANGED
            if ev is None:
                continue  # no missed transition; the armed watch stands
            # One-shot semantics: retire this connection's watch, leave
            # other holders of the same (kind, path) armed.
            holders.discard(conn)
            if not holders:
                self._watches[kind].pop(path, None)
            self._spawn(self._send_watch_events({conn}, ev, path))
        # The create log only serves members still behind; once everyone
        # has applied the backlog it is dead weight — clear it so it
        # cannot grow across lag windows.
        if not any(m._lag_root is not None for m in self._state.members):
            self._state.lag_creates.clear()

    async def _fire_watches(self, kind: str, path: str, ev_type: int) -> None:
        conns = self._watches[kind].pop(path, set())
        if self._deferred_events is not None:
            self._deferred_events.append((conns, ev_type, path))
            return
        await self._send_watch_events(conns, ev_type, path)

    async def _send_watch_events(self, conns, ev_type: int, path: str) -> None:
        # Fan-out shape: encode the event once, write every watcher's
        # socket back-to-back without interleaved awaits, then drain.
        # The serialized per-watcher send_event walk made delivery to
        # the last of N watchers O(N) awaited round-trips.
        targets = [c for c in conns if not c.closed]
        if not targets:
            return
        framed = _event_frame(ev_type, path)
        for conn in targets:
            conn.post_framed(framed)
        for conn in targets:
            await conn.drain()

    def _add_watch(
        self, kind: str, path: str, conn: _Connection, stale_view: bool = False
    ) -> None:
        self._watches[kind].setdefault(path, set()).add(conn)
        if stale_view and self._lag_root is not None:
            # Armed against the stale view: catch-up must reconcile it
            # against the live tree (see _catch_up).  Watches re-armed by
            # the SET_WATCHES handler never enroll — that handler already
            # reconciled them against the live tree via relative_zxid, so
            # a catch-up event would duplicate what the client has seen.
            self._lag_watches.append((kind, path, conn))

    # -- ACLs (ZooKeeper 3.4 semantics) --------------------------------------
    #
    # Enforcement points match real ZK's PrepRequestProcessor/FinalRP:
    # create -> CREATE on the parent, delete -> DELETE on the parent,
    # setData -> WRITE, getData/getChildren -> READ, setACL -> ADMIN;
    # exists and getACL are deliberately unchecked (3.4 behavior).  The
    # reference never sets ACLs (zkplus creates everything world:anyone,
    # SURVEY.md §2.4), so none of this triggers for registrar traffic.

    @staticmethod
    def _ip_matches(acl_id: str, peer_ip: Optional[str]) -> bool:
        if peer_ip is None:
            return False
        import ipaddress

        try:
            addr = ipaddress.ip_address(peer_ip)
            if "/" in acl_id:
                return addr in ipaddress.ip_network(acl_id, strict=False)
            return addr == ipaddress.ip_address(acl_id)
        except ValueError:
            return False

    def _fix_acls(
        self, acls: List[proto.ACL], sess: Session
    ) -> List[proto.ACL]:
        """Validate a client-supplied ACL list, expanding the ``auth``
        scheme into the session's digest identities (real ZK's fixupACL)."""
        if not acls:
            raise proto.ZKError(Err.INVALID_ACL)
        out: List[proto.ACL] = []
        for acl in acls:
            if not isinstance(acl.perms, int) or not (
                0 < acl.perms <= proto.Perms.ALL
            ):
                raise proto.ZKError(Err.INVALID_ACL)
            if acl.scheme == "world":
                if acl.id != "anyone":
                    raise proto.ZKError(Err.INVALID_ACL)
                out.append(acl)
            elif acl.scheme == "auth":
                ids = sorted(
                    i for s, i in sess.auth_ids if s == "digest"
                )
                if not ids:
                    raise proto.ZKError(Err.INVALID_ACL)
                out.extend(proto.ACL(acl.perms, "digest", i) for i in ids)
            elif acl.scheme == "digest":
                if ":" not in acl.id:
                    raise proto.ZKError(Err.INVALID_ACL)
                out.append(acl)
            elif acl.scheme == "ip":
                import ipaddress

                try:
                    if "/" in acl.id:
                        ipaddress.ip_network(acl.id, strict=False)
                    else:
                        ipaddress.ip_address(acl.id)
                except ValueError:
                    raise proto.ZKError(Err.INVALID_ACL)
                out.append(acl)
            else:
                raise proto.ZKError(Err.INVALID_ACL)
        return out

    def _check_acl(
        self, acls: List[proto.ACL], perm: int, sess: Optional[Session]
    ) -> None:
        """Raise NO_AUTH unless some ACL entry grants ``perm`` to ``sess``."""
        for acl in acls:
            if not (acl.perms & perm):
                continue
            if acl.scheme == "world" and acl.id == "anyone":
                return
            if sess is None:
                continue
            if acl.scheme == "digest" and ("digest", acl.id) in sess.auth_ids:
                return
            if acl.scheme == "ip" and sess.conn is not None:
                if self._ip_matches(acl.id, sess.conn.peer_ip):
                    return
        raise proto.ZKError(Err.NO_AUTH)

    def _handle_auth(self, req: proto.AuthPacket, sess: Session) -> bool:
        """Apply an addauth packet; False means AUTH_FAILED (drop conn)."""
        if req.scheme == "digest":
            try:
                cred = (req.auth or b"").decode("utf-8")
                user, password = cred.split(":", 1)
            except (UnicodeDecodeError, ValueError):
                return False
            if not user:
                return False
            sess.auth_ids.add(
                ("digest", proto.digest_auth_id(user, password))
            )
            return True
        if req.scheme == "ip":
            # Real ZK's IPAuthenticationProvider just records the
            # connection's actual address, which _check_acl already matches
            # directly — accept and do nothing.
            return True
        return False

    async def _create_node(
        self,
        path: str,
        data: bytes,
        flags: int,
        session: Session,
        acls: Optional[List[proto.ACL]] = None,
    ) -> str:
        proto.check_path(path)
        acls = (
            self._fix_acls(acls, session)
            if acls is not None
            else list(proto.OPEN_ACL_UNSAFE)
        )
        parent_path, name = self._split(path)
        try:
            parent = self._resolve(parent_path)
        except KeyError:
            raise proto.ZKError(Err.NO_NODE, parent_path)
        self._check_acl(parent.acls, proto.Perms.CREATE, session)
        if parent.ephemeral_owner:
            raise proto.ZKError(Err.NO_CHILDREN_FOR_EPHEMERALS, parent_path)

        sequential = flags in (
            proto.CreateFlag.PERSISTENT_SEQUENTIAL,
            proto.CreateFlag.EPHEMERAL_SEQUENTIAL,
        )
        if sequential:
            name = f"{name}{parent.cversion:010d}"
            path = f"{parent_path.rstrip('/')}/{name}"
        if name in parent.children:
            raise proto.ZKError(Err.NODE_EXISTS, path)

        zxid = self._next_zxid("create", path)
        if self._state.lag_members:
            self._state.lag_creates[path] = zxid
        now = _now_ms()
        ephemeral = flags in (
            proto.CreateFlag.EPHEMERAL,
            proto.CreateFlag.EPHEMERAL_SEQUENTIAL,
        )
        node = ZNode(
            data=data or b"",
            ephemeral_owner=session.session_id if ephemeral else 0,
            czxid=zxid,
            mzxid=zxid,
            pzxid=zxid,
            ctime=now,
            mtime=now,
            acls=acls,
        )
        parent.children[name] = node
        parent.cversion += 1
        parent.pzxid = zxid
        if ephemeral:
            session.ephemerals.add(path)
        self._check_quota(path)
        await self._fire_watches(_WATCH_EXIST, path, EventType.NODE_CREATED)
        await self._fire_watches(_WATCH_DATA, path, EventType.NODE_CREATED)
        await self._fire_watches(
            _WATCH_CHILD, parent_path, EventType.NODE_CHILDREN_CHANGED
        )
        return path

    async def _delete_node(
        self, path: str, version: int = -1, sess: Optional[Session] = None
    ) -> None:
        # ``sess=None`` marks internal calls (ephemeral cleanup on session
        # close/expiry), which bypass ACL checks like real ZK's does.
        parent_path, name = self._split(path)
        parent = self._resolve(parent_path)  # KeyError propagates
        node = parent.children.get(name)
        if node is None:
            raise KeyError(path)
        if sess is not None:
            self._check_acl(parent.acls, proto.Perms.DELETE, sess)
        if version != -1 and node.version != version:
            raise proto.ZKError(Err.BAD_VERSION, path)
        if node.children:
            raise proto.ZKError(Err.NOT_EMPTY, path)
        # Allocate the zxid before mutating: lagging members freeze their
        # read view at the pre-commit state inside _next_zxid.
        zxid = self._next_zxid("delete", path)
        del parent.children[name]
        parent.cversion += 1
        parent.pzxid = zxid
        if node.ephemeral_owner:
            owner = self.sessions.get(node.ephemeral_owner)
            if owner:
                owner.ephemerals.discard(path)
        await self._fire_watches(_WATCH_DATA, path, EventType.NODE_DELETED)
        await self._fire_watches(_WATCH_EXIST, path, EventType.NODE_DELETED)
        await self._fire_watches(
            _WATCH_CHILD, parent_path, EventType.NODE_CHILDREN_CHANGED
        )
        await self._fire_watches(_WATCH_CHILD, path, EventType.NODE_DELETED)

    async def _set_data_node(
        self,
        path: str,
        data: Optional[bytes],
        version: int,
        sess: Optional[Session] = None,
    ) -> Stat:
        try:
            node = self._resolve(path)
        except KeyError:
            raise proto.ZKError(Err.NO_NODE, path)
        if sess is not None:
            self._check_acl(node.acls, proto.Perms.WRITE, sess)
        if version != -1 and node.version != version:
            raise proto.ZKError(Err.BAD_VERSION, path)
        # zxid first: _next_zxid freezes lagging members' pre-commit view.
        node.mzxid = self._next_zxid("setData", path)
        node.data = data or b""
        node.version += 1
        node.mtime = _now_ms()
        self._check_quota(path)
        await self._fire_watches(_WATCH_DATA, path, EventType.NODE_DATA_CHANGED)
        return node.stat()

    # -- multi (atomic transactions) ----------------------------------------

    def _validate_multi(self, ops: List[tuple], sess: Session) -> None:
        """Dry-run a transaction against an overlay of the tree.

        Raises the first op's ZKError without touching state, so the apply
        phase only ever runs transactions that fully succeed (real ZK's
        PrepRequestProcessor plays the same role).  The overlay tracks
        existence, version, ephemeral-ness, and child counts per path —
        enough for create/delete/setData/check semantics, including ops that
        observe earlier ops in the same transaction.
        """
        overlay: Dict[str, Dict[str, object]] = {}

        def lookup(path: str) -> Dict[str, object]:
            ent = overlay.get(path)
            if ent is None:
                try:
                    node = self._resolve(path)
                    ent = {
                        "exists": True,
                        "version": node.version,
                        "ephemeral": bool(node.ephemeral_owner),
                        "nchildren": len(node.children),
                        "cversion": node.cversion,
                        "acls": node.acls,
                    }
                except KeyError:
                    ent = {
                        "exists": False, "version": 0,
                        "ephemeral": False, "nchildren": 0, "cversion": 0,
                        "acls": [],
                    }
                overlay[path] = ent
            return ent

        for index, (op_type, req) in enumerate(ops):
            try:
                self._validate_one(op_type, req, lookup, sess)
            except proto.ZKError as err:
                err.op_index = index
                raise

    def _validate_one(self, op_type: int, req, lookup, sess: Session) -> None:
        try:
            proto.check_path(req.path)
        except ValueError:
            raise proto.ZKError(Err.BAD_ARGUMENTS, req.path)
        if op_type == OpCode.CREATE:
            acls = self._fix_acls(req.acls, sess)  # raises INVALID_ACL
            parent_path, _ = self._split(req.path)
            parent = lookup(parent_path)
            if not parent["exists"]:
                raise proto.ZKError(Err.NO_NODE, parent_path)
            self._check_acl(parent["acls"], proto.Perms.CREATE, sess)
            if parent["ephemeral"]:
                raise proto.ZKError(Err.NO_CHILDREN_FOR_EPHEMERALS, parent_path)
            sequential = req.flags in (
                proto.CreateFlag.PERSISTENT_SEQUENTIAL,
                proto.CreateFlag.EPHEMERAL_SEQUENTIAL,
            )
            # Resolve the effective path the apply phase will use —
            # sequential names derive from the parent's cversion, which the
            # overlay tracks, so collisions with pre-existing nodes are
            # caught here instead of aborting mid-apply.
            path = req.path
            if sequential:
                _, name = self._split(req.path)
                path = (
                    f"{parent_path.rstrip('/')}/"
                    f"{name}{parent['cversion']:010d}"
                )
            ent = lookup(path)
            if ent["exists"]:
                raise proto.ZKError(Err.NODE_EXISTS, path)
            ent.update(
                exists=True,
                version=0,
                ephemeral=req.flags in (
                    proto.CreateFlag.EPHEMERAL,
                    proto.CreateFlag.EPHEMERAL_SEQUENTIAL,
                ),
                nchildren=0,
                cversion=0,  # fresh node — a delete+recreate in the same
                # txn must not inherit the old node's child counter, or
                # sequential-name prediction diverges from the apply phase
                acls=acls,
            )
            parent["nchildren"] += 1
            parent["cversion"] = int(parent["cversion"]) + 1
        elif op_type == OpCode.DELETE:
            ent = lookup(req.path)
            if not ent["exists"]:
                raise proto.ZKError(Err.NO_NODE, req.path)
            parent = lookup(self._split(req.path)[0])
            self._check_acl(parent["acls"], proto.Perms.DELETE, sess)
            if req.version != -1 and ent["version"] != req.version:
                raise proto.ZKError(Err.BAD_VERSION, req.path)
            if ent["nchildren"]:
                raise proto.ZKError(Err.NOT_EMPTY, req.path)
            ent["exists"] = False
            parent["nchildren"] -= 1
            parent["cversion"] = int(parent["cversion"]) + 1
        elif op_type in (OpCode.SET_DATA, OpCode.CHECK):
            ent = lookup(req.path)
            if not ent["exists"]:
                raise proto.ZKError(Err.NO_NODE, req.path)
            self._check_acl(
                ent["acls"],
                proto.Perms.WRITE if op_type == OpCode.SET_DATA
                else proto.Perms.READ,
                sess,
            )
            if req.version != -1 and ent["version"] != req.version:
                raise proto.ZKError(Err.BAD_VERSION, req.path)
            if op_type == OpCode.SET_DATA:
                ent["version"] = int(ent["version"]) + 1
        else:
            raise proto.ZKError(Err.UNIMPLEMENTED, req.path)

    async def _multi(
        self, req: proto.MultiRequest, sess: Session
    ) -> proto.MultiResponse:
        """Atomically apply a transaction (validate first, then apply).

        On failure nothing is applied and the per-op results carry the
        failing op's code with RUNTIME_INCONSISTENCY for the rest — the
        documented ZooKeeper multi abort contract.
        """
        try:
            self._validate_multi(req.ops, sess)
        except proto.ZKError as err:
            failed_at = getattr(err, "op_index", 0)
            return proto.MultiResponse(
                results=[
                    proto.ErrorResult(
                        err=err.code if i == failed_at
                        else Err.RUNTIME_INCONSISTENCY
                    )
                    for i in range(len(req.ops))
                ]
            )

        # Apply with watch delivery deferred: the tree mutations below never
        # await, so the whole transaction commits within one event-loop step
        # (no other client's request — nor another multi — can observe or
        # create a half-applied state).  Validation above guarantees every
        # op succeeds, including sequential-name collisions.
        results = []
        self._deferred_events = []
        try:
            for op_type, op_req in req.ops:
                if op_type == OpCode.CREATE:
                    path = await self._create_node(
                        op_req.path, op_req.data, op_req.flags, sess,
                        op_req.acls,
                    )
                    results.append(proto.CreateResponse(path=path))
                elif op_type == OpCode.DELETE:
                    try:
                        await self._delete_node(
                            op_req.path, op_req.version, sess
                        )
                    except KeyError:
                        raise proto.ZKError(
                            Err.RUNTIME_INCONSISTENCY, op_req.path
                        )
                    results.append(proto.DeleteResult())
                elif op_type == OpCode.SET_DATA:
                    stat = await self._set_data_node(
                        op_req.path, op_req.data, op_req.version, sess
                    )
                    results.append(proto.SetDataResponse(stat=stat))
                else:  # OpCode.CHECK — validated above, nothing to apply
                    results.append(proto.CheckResult())
        finally:
            deferred, self._deferred_events = self._deferred_events, None
        for conns, ev_type, path in deferred:
            await self._send_watch_events(conns, ev_type, path)
        return proto.MultiResponse(results=results)

    # -- connection handling ------------------------------------------------

    async def _handle_conn(self, reader, writer) -> None:
        conn = _Connection(self, reader, writer)
        self._conns.add(conn)
        try:
            await self._serve(conn)
        except Exception:
            log.exception("connection handler crashed")
        finally:
            # Detach FIRST: the flush below can suspend, and a session
            # that still looks connected is exempt from the expiry sweep
            # — cleanup must never be hostage to the peer's read rate.
            self._conns.discard(conn)
            if conn.session is not None and conn.session.conn is conn:
                conn.session.conn = None
                conn.session.auth_ids.clear()
                conn.session.last_heard = time.monotonic()
            # Replies generated for earlier requests in a burst must not
            # be dropped because a LATER frame was malformed (or any
            # other serve-loop exit): pre-batching, each reply went out
            # immediately — deliver what was queued, bounded so a
            # non-reading peer cannot wedge the handler.
            try:
                await asyncio.wait_for(conn.flush(), timeout=1.0)
            except asyncio.CancelledError:
                await conn.close()
                raise  # honor cancellation once cleanup is done
            except Exception:  # noqa: BLE001 - timeout/conn loss: close below
                pass
            await conn.close()

    async def _serve(self, conn: _Connection) -> None:
        # --- handshake (or a 4-letter-word admin command) ---
        # Real ZooKeeper multiplexes admin "four letter words" (ruok, srvr,
        # stat, mntr, ...) onto the client port: 4 ASCII bytes instead of a
        # length-prefixed frame.  A genuine frame header is a small
        # big-endian length (<16 MiB), so its first byte is 0x00 — ASCII
        # command bytes are unambiguous.
        frames = FrameReader(conn.reader)
        first4 = await frames.read4()
        if first4 is None:
            return
        if first4 in _FOUR_LETTER_WORDS:
            text = self._four_letter(first4.decode("ascii"))
            try:
                conn.writer.write(text.encode())
                await conn.writer.drain()
            except (ConnectionError, OSError):
                pass
            return
        payload = await frames.frame(header=first4)
        if payload is None:
            return
        req = proto.ConnectRequest.read(Reader(payload))
        # Real ZooKeeper refuses a session whose client has seen a newer
        # zxid than this server ("Refusing session request as it has seen
        # zxid ...") by closing the connection without a ConnectResponse;
        # the client then tries another member.  Essential for lagging
        # members: accepting such a client would rewind its last_zxid via
        # our stale reply stamps and later re-deliver watch events it
        # already observed.
        view_zxid = self._view_zxid()
        if req.last_zxid_seen > view_zxid:
            self.refused_count += 1
            log.warning(
                "refusing session 0x%x: client has seen zxid 0x%x, ours is 0x%x",
                req.session_id, req.last_zxid_seen, view_zxid,
            )
            return
        if self.mode == "looking":
            # Mid-election a real member is not serving clients at all
            # (LOOKING state closes the client port); refuse by closing,
            # the same wire shape as the zxid refusal but counted apart
            # — the client's next reconnect attempt lands after the
            # election.
            self.refused_looking += 1
            return
        if self.mode == "read-only" and not req.read_only:
            # Real ZooKeeper's ReadOnlyZooKeeperServer only admits
            # clients that set the ConnectRequest read_only flag
            # (canBeReadOnly); everyone else is refused so they keep
            # looking for a read-write member.
            self.refused_ro += 1
            log.debug(
                "refusing non-read-only client while in read-only mode"
            )
            return
        sess = self._establish_session(req)
        w = Writer()
        if sess is None:
            # Expired/unknown session: real ZK answers with session_id 0
            # and timeout 0; the client treats this as session expiry.
            proto.ConnectResponse(
                protocol_version=0, timeout_ms=0, session_id=0, passwd=b"\x00" * 16
            ).write(w)
            await conn.send(w.to_bytes())
            return
        if sess.conn is not None and sess.conn is not conn:
            # Session moved: real ZK closes the superseded connection when
            # a client reattaches the session from a new one.
            old, sess.conn = sess.conn, None
            await old.close()
        # Auth is per-connection (real ZK's authInfo lives on the cnxn):
        # whatever the previous connection added must not leak to this one
        # — the client replays addauth itself after reconnecting.
        sess.auth_ids.clear()
        conn.session = sess
        sess.conn = conn
        sess.last_heard = time.monotonic()
        proto.ConnectResponse(
            protocol_version=0,
            timeout_ms=sess.timeout_ms,
            session_id=sess.session_id,
            passwd=sess.passwd,
            # The 3.4 wire flag (protocol.py ConnectResponse): tells the
            # client it attached to a read-only member — reads serve,
            # writes answer NOT_READONLY until it fails over.
            read_only=self.mode == "read-only",
        ).write(w)
        await conn.send(w.to_bytes())

        # --- request loop ---
        while not conn.closed:
            # Sync fast lane: a pipelined sweep leaves the next frame
            # already buffered — skip the coroutine round trip per
            # request (ISSUE 11; frame() still owns EOF/corrupt-length).
            payload = frames.frame_nowait()
            if payload is None:
                payload = await frames.frame()
                if payload is None:
                    return
            self.packets_received += 1
            sess.last_heard = time.monotonic()
            r = Reader(payload)
            hdr = proto.RequestHeader.read(r)
            if hdr.type == OpCode.CLOSE_SESSION:
                # closeSession is a QUORUM transaction too (it commits
                # the ephemeral deletes): a read-only minority member
                # cannot process it — the session and its ephemerals
                # stay alive until a leader expires them, exactly the
                # frozen-until-quorum invariant; mid-election the writer
                # is dropped like any other write.
                if self._is_ensemble_member:
                    verdict = self._write_gate()
                    if verdict == "drop":
                        self.election_drops += 1
                        await conn.flush()
                        await conn.close()
                        return
                    if verdict == "ro":
                        self.writes_refused += 1
                        await conn.send(
                            self._reply(hdr.xid, Err.NOT_READONLY)
                        )
                        return
                await self._close_session(sess)
                w = Writer()
                proto.ReplyHeader(hdr.xid, self.zxid, Err.OK).write(w)
                await conn.send(w.to_bytes())
                return
            if self.freeze:
                # Swallow the request: wedged-server simulation.  Replies
                # already generated for earlier requests in this burst
                # predate the wedge — deliver them first, matching the
                # pre-batching behavior where each was sent immediately.
                await conn.flush()
                continue
            if hdr.type == OpCode.AUTH:
                req = proto.AuthPacket.read(r)
                ok = self._handle_auth(req, sess)
                await conn.send(
                    self._reply(hdr.xid, Err.OK if ok else Err.AUTH_FAILED)
                )
                if not ok:
                    # Real ZK answers AUTH_FAILED then drops the connection.
                    return
                continue
            if hdr.type in _QUORUM_OPS and self._is_ensemble_member:
                verdict = self._write_gate()
                if verdict == "drop":
                    # Mid-election: a follower that lost its leader drops
                    # its writers (the in-flight op surfaces client-side
                    # as CONNECTION_LOSS, retryable); queued replies for
                    # earlier reads in the burst still go out.
                    self.election_drops += 1
                    await conn.flush()
                    await conn.close()
                    return
                if verdict == "ro":
                    self.writes_refused += 1
                    conn.queue(self._reply(hdr.xid, Err.NOT_READONLY))
                    if conn.queue_full() or not frames.pending():
                        await conn.flush()
                    continue
            # Coroutine-free lanes for the hot read ops (ISSUE 11): a
            # 10k-node heartbeat sweep is 10k EXISTS requests and a
            # resolve burst is getData/getChildren2 — none of which ever
            # await; routing them through the async _dispatch cost a
            # coroutine per request.
            if hdr.type == OpCode.EXISTS:
                reply = self._exists_fast(conn, sess, hdr, r)
            elif hdr.type == OpCode.GET_DATA:
                reply = self._get_data_fast(conn, sess, hdr, r)
                if reply is _SLOW_PATH:  # quota-stats read: may setData
                    reply = await self._dispatch(
                        conn, sess, hdr, Reader(payload, 8)
                    )
            elif hdr.type in (OpCode.GET_CHILDREN, OpCode.GET_CHILDREN2):
                reply = self._children_fast(conn, sess, hdr, r)
            else:
                reply = await self._dispatch(conn, sess, hdr, r)
            if reply is not None:
                conn.queue(reply)
            # Flush once per input burst — but also whenever the staged
            # replies hit the count/byte caps, so a client that streams
            # requests continuously (keeping a complete frame buffered
            # at all times) still receives replies and the queue stays
            # bounded in BOTH dimensions; the per-reply drain this
            # batching replaced was also the backpressure.
            if conn.queue_full() or not frames.pending():
                await conn.flush()

    def _establish_session(self, req: proto.ConnectRequest) -> Optional[Session]:
        if req.session_id:
            sess = self.sessions.get(req.session_id)
            if sess is None or sess.closed or sess.passwd != req.passwd:
                return None
            return sess
        timeout = max(
            self.min_session_timeout_ms,
            min(req.timeout_ms, self.max_session_timeout_ms),
        )
        self._next_session += 1
        sess = Session(
            session_id=self._next_session,
            passwd=os.urandom(16),
            timeout_ms=timeout,
            last_heard=time.monotonic(),
        )
        self.sessions[sess.session_id] = sess
        return sess

    async def _close_session(self, sess: Session) -> None:
        sess.closed = True
        self.sessions.pop(sess.session_id, None)
        await self._remove_ephemerals(sess)

    async def _dispatch(
        self, conn: _Connection, sess: Session, hdr: proto.RequestHeader, r: Reader
    ) -> Optional[bytes]:
        op = hdr.type
        try:
            if op == OpCode.PING:
                return self._reply(proto.XID_PING, Err.OK)
            if op == OpCode.CREATE:
                req = proto.CreateRequest.read(r)
                path = await self._create_node(
                    req.path, req.data, req.flags, sess, req.acls
                )
                self._catch_up()  # read-your-writes on this member
                return self._reply(hdr.xid, Err.OK, proto.CreateResponse(path=path))
            if op == OpCode.DELETE:
                req = proto.DeleteRequest.read(r)
                proto.check_path(req.path)
                try:
                    await self._delete_node(req.path, req.version, sess)
                except KeyError:
                    raise proto.ZKError(Err.NO_NODE, req.path)
                self._catch_up()
                return self._reply(hdr.xid, Err.OK)
            if op == OpCode.EXISTS:
                return self._exists_fast(conn, sess, hdr, r)
            if op == OpCode.GET_DATA:
                req = proto.GetDataRequest.read(r)
                proto.check_path(req.path)
                await self._refresh_quota_stats(req.path)
                try:
                    node = self._resolve_read(req.path)
                except KeyError:
                    raise proto.ZKError(Err.NO_NODE, req.path)
                self._check_acl(node.acls, proto.Perms.READ, sess)
                if req.watch:
                    self._add_watch(
                        _WATCH_DATA, req.path, conn, stale_view=True
                    )
                return (
                    proto.pack_reply_header(hdr.xid, self._view_zxid(), Err.OK)
                    + proto.pack_buffer(node.data)
                    + node.stat_packed()
                )
            if op == OpCode.SET_DATA:
                req = proto.SetDataRequest.read(r)
                proto.check_path(req.path)
                stat = await self._set_data_node(
                    req.path, req.data, req.version, sess
                )
                self._catch_up()
                return self._reply(
                    hdr.xid, Err.OK, proto.SetDataResponse(stat=stat)
                )
            if op == OpCode.GET_ACL:
                req = proto.GetACLRequest.read(r)
                proto.check_path(req.path)
                try:
                    node = self._resolve_read(req.path)
                except KeyError:
                    raise proto.ZKError(Err.NO_NODE, req.path)
                # Unchecked in 3.4 (ADMIN-gating arrived with 3.5's
                # checkGetACL flag) — anyone may inspect ACLs.
                return self._reply(
                    hdr.xid,
                    Err.OK,
                    proto.GetACLResponse(
                        acls=list(node.acls), stat=node.stat()
                    ),
                )
            if op == OpCode.SET_ACL:
                req = proto.SetACLRequest.read(r)
                proto.check_path(req.path)
                try:
                    node = self._resolve(req.path)
                except KeyError:
                    raise proto.ZKError(Err.NO_NODE, req.path)
                self._check_acl(node.acls, proto.Perms.ADMIN, sess)
                if req.version != -1 and node.aversion != req.version:
                    raise proto.ZKError(Err.BAD_VERSION, req.path)
                # Validate (fix_acls raises INVALID_ACL) before the zxid
                # is allocated — a failed op must not consume a zxid or
                # freeze lagging members.
                fixed_acls = self._fix_acls(req.acls, sess)
                # a write transaction, but mzxid untouched
                self._next_zxid("setAcl", req.path)
                node.acls = fixed_acls
                node.aversion += 1
                self._catch_up()
                return self._reply(
                    hdr.xid, Err.OK, proto.SetACLResponse(stat=node.stat())
                )
            if op in (OpCode.GET_CHILDREN, OpCode.GET_CHILDREN2):
                return self._children_fast(conn, sess, hdr, r)
            if op == OpCode.SET_WATCHES:
                req = proto.SetWatches.read(r)
                # Real ZooKeeper compares each path's state against the
                # client's relative_zxid and immediately delivers events the
                # client missed while disconnected, instead of silently
                # re-arming a watch for a change that already happened.
                for p in req.data_watches:
                    try:
                        node = self._resolve(p)
                    except KeyError:
                        await conn.send_event(EventType.NODE_DELETED, p)
                        continue
                    if node.mzxid > req.relative_zxid:
                        await conn.send_event(EventType.NODE_DATA_CHANGED, p)
                    else:
                        self._add_watch(_WATCH_DATA, p, conn)
                for p in req.exist_watches:
                    try:
                        self._resolve(p)
                        await conn.send_event(EventType.NODE_CREATED, p)
                    except KeyError:
                        self._add_watch(_WATCH_EXIST, p, conn)
                for p in req.child_watches:
                    try:
                        node = self._resolve(p)
                    except KeyError:
                        await conn.send_event(EventType.NODE_DELETED, p)
                        continue
                    if node.pzxid > req.relative_zxid:
                        await conn.send_event(EventType.NODE_CHILDREN_CHANGED, p)
                    else:
                        self._add_watch(_WATCH_CHILD, p, conn)
                return self._reply(hdr.xid, Err.OK)
            if op == OpCode.SYNC:
                req = proto.SyncRequest.read(r)
                # The catch-up barrier: real ZK's sync makes the serving
                # follower flush the leader's pipeline so subsequent reads
                # through it are current.  A lagging member applies its
                # whole backlog here; a caught-up one degenerates to a
                # request-pipeline ordering barrier.
                self._catch_up()
                return self._reply(
                    hdr.xid, Err.OK, proto.SyncResponse(path=req.path)
                )
            if op == OpCode.MULTI:
                req = proto.MultiRequest.read(r)
                results = await self._multi(req, sess)
                # Catch up BEFORE encoding, like the other write ops: a
                # write multi served by a lagging member must stamp its
                # reply with the applied zxid, not the frozen one —
                # otherwise the client's last_zxid understates its own
                # commit and the connect-time zxid-refusal guard cannot
                # protect its read-your-writes across a reconnect.
                self._catch_up()
                return self._reply(hdr.xid, Err.OK, results)
            if op == OpCode.CHECK:
                req = proto.CheckVersionRequest.read(r)
                proto.check_path(req.path)
                try:
                    node = self._resolve(req.path)
                except KeyError:
                    raise proto.ZKError(Err.NO_NODE, req.path)
                if req.version != -1 and node.version != req.version:
                    raise proto.ZKError(Err.BAD_VERSION, req.path)
                return self._reply(hdr.xid, Err.OK)
            log.warning("unimplemented opcode %d", op)
            return self._reply(hdr.xid, Err.UNIMPLEMENTED)
        except proto.ZKError as e:
            return self._reply(hdr.xid, e.code)
        except ValueError:
            return self._reply(hdr.xid, Err.BAD_ARGUMENTS)

    def _exists_fast(
        self, conn: "_Connection", sess: Session, hdr: proto.RequestHeader,
        r: Reader,
    ) -> bytes:
        """EXISTS handled without a coroutine (the request loop calls
        this directly) and without Stat/ExistsResponse intermediates —
        the server half of the heartbeat sweep's hot path (ISSUE 11).
        Replies are byte-identical to the general ``_dispatch`` path
        (``encode_reply_payload(.., ExistsResponse(node.stat()))``),
        pinned by tests/test_wire_golden.py; the error contract mirrors
        ``_dispatch``'s except clauses.
        """
        try:
            # Fields read inline (no ExistsRequest dataclass): this runs
            # once per swept znode.
            path = r.read_ustring()
            watch = r.read_bool()
            proto.check_path(path)
            try:
                node = self._resolve_read(path)
            except KeyError:
                if watch:
                    self._add_watch(_WATCH_EXIST, path, conn, stale_view=True)
                return self._reply(hdr.xid, Err.NO_NODE)
            if watch:
                self._add_watch(_WATCH_DATA, path, conn, stale_view=True)
            return proto.pack_reply_header(
                hdr.xid, self._view_zxid(), Err.OK
            ) + node.stat_packed()
        except proto.ZKError as e:
            return self._reply(hdr.xid, e.code)
        except ValueError:
            return self._reply(hdr.xid, Err.BAD_ARGUMENTS)

    def _get_data_fast(
        self, conn: "_Connection", sess: Session, hdr: proto.RequestHeader,
        r: Reader,
    ):
        """GET_DATA without a coroutine or dataclass intermediates (the
        resolver's op).  Quota-stats reads — which may genuinely rewrite
        the stats node — return :data:`_SLOW_PATH` so the request loop
        routes them through the async ``_dispatch``.  Replies byte-
        identical to the general path (tests/test_wire_golden.py)."""
        try:
            path = r.read_ustring()
            watch = r.read_bool()
            proto.check_path(path)
            if path.startswith(_QUOTA_PREFIX):
                return _SLOW_PATH
            try:
                node = self._resolve_read(path)
            except KeyError:
                return self._reply(hdr.xid, Err.NO_NODE)
            self._check_acl(node.acls, proto.Perms.READ, sess)
            if watch:
                self._add_watch(_WATCH_DATA, path, conn, stale_view=True)
            return (
                proto.pack_reply_header(hdr.xid, self._view_zxid(), Err.OK)
                + proto.pack_buffer(node.data)
                + node.stat_packed()
            )
        except proto.ZKError as e:
            return self._reply(hdr.xid, e.code)
        except ValueError:
            return self._reply(hdr.xid, Err.BAD_ARGUMENTS)

    def _children_fast(
        self, conn: "_Connection", sess: Session, hdr: proto.RequestHeader,
        r: Reader,
    ) -> bytes:
        """GET_CHILDREN/GET_CHILDREN2 without a coroutine (sync by
        construction); the vector body keeps the general record encoder.
        Serves both the request loop's fast lane and ``_dispatch``."""
        try:
            req = proto.GetChildrenRequest.read(r)
            proto.check_path(req.path)
            try:
                node = self._resolve_read(req.path)
            except KeyError:
                return self._reply(hdr.xid, Err.NO_NODE)
            self._check_acl(node.acls, proto.Perms.READ, sess)
            if req.watch:
                self._add_watch(_WATCH_CHILD, req.path, conn, stale_view=True)
            children = sorted(node.children)
            if hdr.type == OpCode.GET_CHILDREN:
                body = proto.GetChildrenResponse(children=children)
            else:
                body = proto.GetChildren2Response(
                    children=children, stat=node.stat()
                )
            return self._reply(hdr.xid, Err.OK, body)
        except proto.ZKError as e:
            return self._reply(hdr.xid, e.code)
        except ValueError:
            return self._reply(hdr.xid, Err.BAD_ARGUMENTS)

    def _reply(self, xid: int, err: int, body=None) -> bytes:
        # A lagging member stamps replies with the zxid its frozen view
        # corresponds to (real followers report their own
        # lastProcessedZxid).  Stamping the live shared zxid would make a
        # client's last_zxid overstate what it observed, suppressing the
        # SetWatches reconciliation it is owed after a reconnect.
        return proto.encode_reply_payload(xid, self._view_zxid(), err, body)


class ZKEnsemble:
    """N in-process ZK members sharing one replicated tree + session table.

    Models the production deployment the reference points clients at — a
    3–5 member ensemble (reference etc/config.coal.json:9-16, README's
    ops guidance) — closing the round-1 gap that failover was only ever
    tested against a single restarted server.  A client holding a session
    through member A can, when A dies, reattach the *same* session (with
    its ephemeral znodes intact) through member B, because members share a
    :class:`_SharedState`.  Watches set via one member fire on writes made
    through any member.

    ISSUE 10 makes the ensemble a real replication protocol, not just
    shared state: one elected **leader** orders and commits writes at
    quorum (``size // 2 + 1``); killing it runs an election
    (``election_ms`` wide) during which candidates are ``looking``;
    members cut off from quorum — by deaths or by
    :meth:`partition` — serve **read-only** from a frozen
    zxid-consistent view (3.4 ``read_only`` handshake; writes answer
    NOT_READONLY) until quorum returns; a member
    :meth:`restart`\\ ed after :meth:`kill` catches up by committed-
    backlog replay or a snapshot (``backlog_max``); and only the leader
    expires sessions, so a quorum-less ensemble freezes every session
    in place.

    Usage::

        async with ZKEnsemble(3) as ens:
            cfg_servers = [
                {"host": h, "port": p} for h, p in ens.addresses
            ]
            ...
            await ens.kill(0)       # the member the client is talking to
            ...                     # client reattaches via another member
            await ens.restart(0)    # member rejoins with the shared state
    """

    def __init__(
        self,
        size: int = 3,
        host: str = "127.0.0.1",
        base_port: Optional[int] = None,
        election_ms: float = 0.0,
        backlog_max: int = 512,
        **server_kwargs,
    ):
        """``base_port``: members listen on consecutive ports starting
        here (for operators wanting a predictable servers list); default
        lets the OS pick free ports (right for tests).

        ``election_ms``: how long a leader election takes (ISSUE 10).
        0 (the default) elects instantly — the pre-quorum tests' shape;
        > 0 opens a real election window after a leader death during
        which candidate members are ``looking`` (handshakes refused,
        write connections dropped) and the failover MTTR a client
        measures includes the wait.

        ``backlog_max``: committed-transaction backlog bound.  A member
        rejoining within the backlog catches up by diff replay
        (``catchup_replayed``); one whose departure point fell off the
        tail takes a full snapshot (``catchup_snapshots``)."""
        if size < 1:
            raise ValueError("ensemble size must be >= 1")
        self.state = _SharedState()
        self.state.ensemble_size = size
        self.state.quorum = size // 2 + 1
        self.state.election_ms = election_ms
        self.state.log = deque(maxlen=max(1, backlog_max))
        self.servers: List[Optional[ZKServer]] = []
        self._host = host
        self._server_kwargs = server_kwargs
        self._size = size
        self._ports: List[Optional[int]] = [
            base_port + i if base_port else None for i in range(size)
        ]
        #: shared-state zxid at the moment each member was killed — the
        #: rejoin sync point (snapshot-vs-replay accounting in restart())
        self._departed_zxid: Dict[int, int] = {}

    def _new_member(self, i: int, port: int = 0) -> ZKServer:
        member = ZKServer(
            host=self._host,
            port=port,
            shared=self.state,
            server_id=i + 1,  # real ensembles number members from 1
            **self._server_kwargs,
        )
        return member

    async def start(self) -> "ZKEnsemble":
        self.servers = []
        for i in range(self._size):
            member = self._new_member(i, port=self._ports[i] or 0)
            await member.start()
            self._ports[i] = member.port
            self.servers.append(member)
        # The INITIAL election completes immediately even with an
        # election window configured: the window models failover (a
        # leader dying under live clients), not cold boot — tests must
        # be able to connect the moment start() returns.
        if self.state.election_due is not None:
            self.state.complete_election()
        self._elect()
        return self

    async def stop(self) -> None:
        for member in self.servers:
            if member is not None and member._server is not None:
                await member.stop()

    async def __aenter__(self) -> "ZKEnsemble":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    @property
    def addresses(self) -> List[Tuple[str, int]]:
        """(host, port) of every member, dead or alive — the client's
        ``servers`` list stays stable across member restarts."""
        return [(self._host, p) for p in self._ports if p is not None]

    def _elect(self) -> None:
        # Role assignment is the shared state's election machinery
        # (ISSUE 10); member start()/stop() already trigger it — this
        # remains as the explicit recompute hook.
        self.state.reevaluate()

    @property
    def leader_index(self) -> Optional[int]:
        """Index (into ``servers``) of the current leader, or None
        (mid-election / quorum lost)."""
        leader = self.state.leader
        if leader is None:
            return None
        for i, member in enumerate(self.servers):
            if member is leader:
                return i
        return None

    @property
    def has_quorum(self) -> bool:
        return self.state.leader is not None

    @property
    def size(self) -> int:
        return self._size

    def partition(self, groups: Sequence[Sequence[int]]) -> None:
        """Split member-to-member connectivity into ``groups`` of member
        indices (0-based).  The group that can assemble quorum keeps (or
        elects) the leader; every other member degrades to read-only
        with a frozen view — the partition-to-minority fault class.
        Members not named in any group are isolated singletons.
        """
        seen: Set[int] = set()
        for group in groups:
            for i in group:
                if not 0 <= i < self._size:
                    raise ValueError(f"member index {i} out of range")
                if i in seen:
                    raise ValueError(f"member {i} in more than one group")
                seen.add(i)
        # groups are stored by server_id (= index + 1, stable across
        # member restarts)
        self.state.groups = [{i + 1 for i in group} for group in groups]
        self.state.reevaluate()

    def heal_partition(self) -> None:
        """Restore full member-to-member connectivity (rejoining
        minority members catch up and resume as followers)."""
        self.state.groups = None
        self.state.reevaluate()

    async def kill(self, i: int) -> None:
        """Stop member ``i`` (connections die; sessions and ephemerals
        survive in the shared state until their own timeouts)."""
        member = self.servers[i]
        if member is None or member._server is None:
            return
        # The rejoin sync point: what this member had applied when it
        # departed (its view zxid — a lagging/ro member is behind the
        # shared head, and restart() owes it the difference).
        self._departed_zxid[i] = member._view_zxid()
        await member.stop()
        self.servers[i] = None

    async def restart(self, i: int) -> ZKServer:
        """Bring member ``i`` back on its original port, joined to the
        ensemble's shared state — catching up via committed-backlog
        replay, or a full snapshot when the backlog no longer covers its
        departure point (``catchup_replayed`` / ``catchup_snapshots``)."""
        if self.servers[i] is not None and self.servers[i]._server is not None:
            return self.servers[i]
        member = self._new_member(i, port=self._ports[i] or 0)
        await member.start()
        member.catchup_from(self._departed_zxid.pop(i, None))
        self._ports[i] = member.port
        self.servers[i] = member
        return member

    def set_lag(self, i: int, apply_delay_ms: int) -> None:
        """Make member ``i`` a lagging follower (``apply_delay_ms`` > 0)
        or bring it back in step (0, after an immediate catch-up).
        Lag starts from the *next* commit made through another member;
        the member's current view is the replicated state.  Reads through
        a lagging member then return stale data until a client issues
        ``sync()`` on it — the scenario ZKClient.sync exists for."""
        member = self.servers[i]
        if member is None or member._server is None:
            raise ValueError(f"member {i} is not running")
        member.apply_delay_ms = apply_delay_ms
        self.state.recount_lag()
        if apply_delay_ms <= 0:
            member._catch_up()

    @property
    def live(self) -> List[ZKServer]:
        return [
            m for m in self.servers if m is not None and m._server is not None
        ]

    def get_node(self, path: str) -> Optional[ZNode]:
        """Direct shared-tree access for assertions (member-independent)."""
        node = self.state.root
        if path != "/":
            for comp in path.strip("/").split("/"):
                node = node.children.get(comp)
                if node is None:
                    return None
        return node


async def _ctl_conn(ens: "ZKEnsemble", size: int, reader, writer) -> None:
    """One ensemble-control connection (see --ctl-port): line-oriented
    'stop N' / 'start N' / 'lag N MS' commands, N 1-based to match the
    CI zkctl convention (tests/test_real_zk_ensemble.py)."""
    try:
        while True:
            line = await reader.readline()
            if not line:
                return
            parts = line.decode("ascii", errors="replace").split()
            try:
                action = parts[0]
                if action == "heal":
                    ens.heal_partition()
                elif action == "partition":
                    # 'partition 1,2|3' — groups of 1-based members
                    groups = [
                        [int(m) - 1 for m in grp.split(",") if m]
                        for grp in parts[1].split("|")
                    ]
                    ens.partition(groups)
                else:
                    member = int(parts[1]) - 1
                    if not 0 <= member < size:
                        raise ValueError(f"member {parts[1]} out of range")
                    if action == "stop":
                        await ens.kill(member)
                    elif action == "start":
                        await ens.restart(member)
                    elif action == "lag":
                        ens.set_lag(member, int(parts[2]))
                    else:
                        raise ValueError(f"unknown action {action!r}")
            except (IndexError, ValueError) as e:
                writer.write(f"err {e}\n".encode())
            except Exception as e:  # noqa: BLE001 - report, keep serving
                writer.write(f"err {e!r}\n".encode())
            else:
                writer.write(b"ok\n")
            await writer.drain()
    except (ConnectionError, OSError):
        pass
    finally:
        try:
            writer.close()
        except Exception:  # noqa: BLE001
            pass


async def _amain(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="standalone in-process ZooKeeper test server"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=21811)
    parser.add_argument(
        "--max-session-timeout", type=int, default=60_000, metavar="MS"
    )
    parser.add_argument(
        "--snapshot-file", metavar="PATH", default=None,
        help="persist the tree/sessions/zxid here (loaded on startup when "
        "present, saved every --snapshot-interval seconds and on clean "
        "SIGTERM/SIGINT; a crash/SIGKILL loses at most one interval — "
        "real ZooKeeper's continuously-fsynced txlog has no analog here)",
    )
    parser.add_argument(
        "--snapshot-interval", type=float, default=30.0, metavar="SECONDS",
        help="periodic --snapshot-file save cadence (0 disables the "
        "periodic safety net, keeping shutdown-only saves)",
    )
    parser.add_argument(
        "--ensemble", type=int, default=1, metavar="N",
        help="run an N-member ensemble sharing one replicated tree on "
        "consecutive ports starting at --port (models the 3-5 member "
        "production deployments clients are pointed at)",
    )
    parser.add_argument(
        "--lag", action="append", default=[], metavar="MEMBER:MS",
        help="make ensemble member MEMBER (0-based) a lagging follower "
        "with an MS-millisecond apply delay (repeatable; requires "
        "--ensemble > 1).  Reads through that member return stale data "
        "until a client issues sync() on it — rehearses ZKClient.sync's "
        "read barrier from the command line",
    )
    parser.add_argument(
        "--election-ms", type=float, default=0.0, metavar="MS",
        help="(ensemble only) leader-election duration: after a leader "
        "death, candidate members spend MS milliseconds 'looking' "
        "(handshakes refused, writers dropped) before the new leader "
        "serves — rehearses client failover MTTR from the command line",
    )
    parser.add_argument(
        "--ctl-port", type=int, default=None, metavar="PORT",
        help="(ensemble only) listen on PORT (0 = pick a free one) for "
        "line-oriented member control: 'stop N' / 'start N' / 'lag N MS' "
        "with N 1-based, answered with 'ok' or 'err <reason>'.  Lets the "
        "real-ensemble interop suite (tests/test_real_zk_ensemble.py, "
        "ZK_ENSEMBLE_CTL=host:port) drive failover against this hermetic "
        "ensemble exactly as CI drives it against Apache ZooKeeper",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG)
    if args.ensemble > 1 and args.snapshot_file:
        parser.error("--snapshot-file is standalone-only (use --ensemble 1)")
    if args.lag and args.ensemble <= 1:
        parser.error("--lag requires --ensemble > 1")
    if args.ctl_port is not None and args.ensemble <= 1:
        parser.error("--ctl-port requires --ensemble > 1")
    lags = []
    for spec in args.lag:
        member_s, _, ms_s = spec.partition(":")
        try:
            member, ms = int(member_s), int(ms_s)
        except ValueError:
            parser.error(f"--lag expects MEMBER:MS (e.g. 1:60000), got {spec!r}")
        if not 0 <= member < args.ensemble:
            parser.error(
                f"--lag member {member} out of range for --ensemble {args.ensemble}"
            )
        if ms <= 0:
            parser.error("--lag MS must be positive")
        lags.append((member, ms))

    stopping = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stopping.set)
        except NotImplementedError:
            pass

    if args.ensemble > 1:
        ens = ZKEnsemble(
            size=args.ensemble,
            host=args.host,
            base_port=args.port or None,
            election_ms=args.election_ms,
            max_session_timeout_ms=args.max_session_timeout,
        )
        await ens.start()
        for member, ms in lags:
            ens.set_lag(member, ms)
            print(f"member {member} lagging (apply delay {ms} ms)", flush=True)
        hosts = ",".join(f"{h}:{p}" for h, p in ens.addresses)
        print(f"zk test ensemble listening on {hosts}", flush=True)
        ctl_server = None
        if args.ctl_port is not None:
            ctl_server = await asyncio.start_server(
                lambda r, w: _ctl_conn(ens, args.ensemble, r, w),
                args.host,
                args.ctl_port,
            )
            ctl_port = ctl_server.sockets[0].getsockname()[1]
            print(
                f"ensemble control listening on {args.host}:{ctl_port}",
                flush=True,
            )
        try:
            await stopping.wait()
        finally:
            # close() only — on 3.12 Server.wait_closed() blocks until
            # every handler transport reports closed, which can outlive
            # a ctl client that already disconnected; this is process
            # shutdown, there is nothing to flush.
            if ctl_server is not None:
                ctl_server.close()
            await ens.stop()
        return

    server = ZKServer(
        host=args.host,
        port=args.port,
        max_session_timeout_ms=args.max_session_timeout,
    )
    if args.snapshot_file and os.path.exists(args.snapshot_file):
        server.load_snapshot(args.snapshot_file)
        print(f"loaded snapshot from {args.snapshot_file}", flush=True)
    await server.start()
    print(f"zk test server listening on {args.host}:{server.port}", flush=True)

    async def periodic_saves() -> None:
        # Crash safety net: without it a SIGKILL would lose everything
        # since the last shutdown (the advisor's round-1 finding).  A
        # transiently failing save (disk full, permissions) must not kill
        # the net — log and retry next interval.
        while True:
            await asyncio.sleep(args.snapshot_interval)
            try:
                server.save_snapshot(args.snapshot_file)
            except OSError:
                log.exception("periodic snapshot save failed; will retry")

    saver = (
        asyncio.create_task(periodic_saves())
        if args.snapshot_file and args.snapshot_interval > 0
        else None
    )
    try:
        await stopping.wait()
    finally:
        if saver is not None:
            saver.cancel()
            try:
                await saver
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass  # a dead saver must not block the final save below
        await server.stop()
        if args.snapshot_file:
            server.save_snapshot(args.snapshot_file)
            print(f"saved snapshot to {args.snapshot_file}", flush=True)


if __name__ == "__main__":
    try:
        asyncio.run(_amain())
    except KeyboardInterrupt:
        pass
