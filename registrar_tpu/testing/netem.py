"""netem — a deterministic, toxiproxy-style TCP fault-injection proxy.

The chaos suite (tests/test_chaos.py) injects *server-side* faults: member
kills, dropped connections, replication lag.  The nastier failures live in
the network itself — half-open TCP, a peer that stops reading (slow-loris),
frames sliced into tiny segments, reply stalls shorter than the session
watchdog — and none of them can be produced by a well-behaved server.
:class:`ChaosProxy` interposes a real asyncio TCP proxy between
:class:`~registrar_tpu.zk.client.ZKClient` and a
:class:`~registrar_tpu.testing.server.ZKServer` (or ensemble member) and
applies composable, runtime-toggleable "toxics" per direction:

    ==================  ====================================================
    toxic               wire behavior
    ==================  ====================================================
    Latency             delay each chunk by latency ± jitter
    Bandwidth           throttle to N bytes/s (pacing sleep per chunk)
    Slicer              fragment every chunk into tiny segments
    Truncate            forward the first N bytes, then silence forever
                        (half-open TCP: peer is gone, no FIN ever arrives)
    Blackhole           connect succeeds, nothing is ever forwarded
    StopReading         stop draining the source socket (slow-loris): the
                        sender's kernel buffer fills and its ``drain()``
                        blocks — the watchdog-wedge scenario
    ResetAfter          forward N bytes, then RST both directions
    ==================  ====================================================

Direction ``"up"`` is client→server, ``"down"`` is server→client.  Toxics
taking randomness draw it from the proxy's seeded RNG, so a failing run is
reproducible from its seed (the chaos storm prints ``CHAOS_SEED``).

Usage::

    async with ZKServer() as server:
        async with ChaosProxy(server.address, seed=7) as proxy:
            client = await ZKClient([proxy.address]).connect()
            proxy.add(Latency(latency_ms=30, jitter_ms=10), direction="down")
            ...
            proxy.clear()          # heal the link (live connections too)

Toxics apply to live connections immediately: the pumps consult the
installed list on every chunk (and every read), which is what makes
mid-operation fault injection — the whole point — possible.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import struct
from typing import Dict, List, Optional, Sequence, Set, Tuple

from registrar_tpu.events import spawn_owned

log = logging.getLogger("registrar_tpu.testing.netem")

#: client -> server
UP = "up"
#: server -> client
DOWN = "down"

_READ_SIZE = 65536
#: cadence of the paused-pump poll (StopReading) — coarse is fine, the
#: point is *not* reading for a while, not precise timing
_PAUSE_POLL_S = 0.005


class Toxic:
    """One wire-fault behavior, applied to every chunk of one direction.

    Subclasses override :meth:`process` (transform/delay/swallow a chunk;
    returning None ends the chain for that chunk) and/or :meth:`paused`
    (True = the pump must not read from the source socket at all).  A
    toxic with ``masks_close = True`` also swallows the peer's EOF: the
    other side sees a half-open connection instead of an orderly FIN —
    exactly what a peer that died without closing looks like.
    """

    name = "toxic"
    masks_close = False

    def paused(self, link: "_Link") -> bool:
        return False

    async def process(self, link: "_Link", data: bytes) -> Optional[bytes]:
        return data

    def __repr__(self) -> str:  # seeds/params visible in failure output
        attrs = ", ".join(
            f"{k}={v!r}" for k, v in vars(self).items() if not k.startswith("_")
        )
        return f"{type(self).__name__}({attrs})"


class Latency(Toxic):
    """Delay each chunk by ``latency_ms`` ± uniform ``jitter_ms``."""

    name = "latency"

    def __init__(self, latency_ms: float = 50.0, jitter_ms: float = 0.0):
        self.latency_ms = latency_ms
        self.jitter_ms = jitter_ms

    async def process(self, link: "_Link", data: bytes) -> Optional[bytes]:
        delay = self.latency_ms
        if self.jitter_ms:
            delay += link.rng.uniform(-self.jitter_ms, self.jitter_ms)
        if delay > 0:
            await asyncio.sleep(delay / 1000.0)
        return data


class Bandwidth(Toxic):
    """Throttle a direction to ``bytes_per_s`` (sleep len/rate per chunk)."""

    name = "bandwidth"

    def __init__(self, bytes_per_s: float = 65536.0):
        if bytes_per_s <= 0:
            raise ValueError("bytes_per_s must be positive")
        self.bytes_per_s = bytes_per_s

    async def process(self, link: "_Link", data: bytes) -> Optional[bytes]:
        await asyncio.sleep(len(data) / self.bytes_per_s)
        return data


class Slicer(Toxic):
    """Fragment each chunk into tiny segments (``1..max_size`` bytes each,
    rng-sized), yielding to the event loop between segments so the far
    side's framing layer really sees torn frames.  Writes the segments
    itself, so it terminates the toxic chain for the chunk — install it
    last when composing.
    """

    name = "slicer"

    def __init__(self, max_size: int = 8, delay_ms: float = 0.0):
        if max_size < 1:
            raise ValueError("max_size must be >= 1")
        self.max_size = max_size
        self.delay_ms = delay_ms

    async def process(self, link: "_Link", data: bytes) -> Optional[bytes]:
        pos = 0
        while pos < len(data):
            n = link.rng.randint(1, self.max_size)
            link.write(data[pos: pos + n])
            pos += n
            if self.delay_ms:
                await asyncio.sleep(self.delay_ms / 1000.0)
            else:
                await asyncio.sleep(0)  # force separate transport writes
            await link.drain()
        return None


class Truncate(Toxic):
    """Forward the first ``n`` bytes of the direction, then silence forever
    — and mask the peer's close (half-open TCP: a frame can be cut mid-
    payload and no FIN ever tells the other side)."""

    name = "truncate"
    masks_close = True

    def __init__(self, n: int = 0):
        self.n = n

    async def process(self, link: "_Link", data: bytes) -> Optional[bytes]:
        passed = link.state.get(self, 0)
        if passed >= self.n:
            return None
        keep = data[: self.n - passed]
        link.state[self] = passed + len(keep)
        return keep


class Blackhole(Toxic):
    """Forward nothing, ever (connect still succeeds upstream of this).

    With it installed on both directions the peer is a total void: TCP
    accepts, writes are swallowed, replies never come, close is masked —
    the scenario the client's liveness watchdog exists for.
    """

    name = "blackhole"
    masks_close = True

    async def process(self, link: "_Link", data: bytes) -> Optional[bytes]:
        return None


class StopReading(Toxic):
    """Stop draining the source socket (slow-loris).

    The proxy's receive buffer, then the sender's kernel send buffer,
    fill; the sender's transport rises past its high-water mark and its
    ``drain()`` blocks indefinitely.  Installed on ``up``, this is the
    exact stall that wedged the pre-fix client watchdog
    (``ZKClient._ping_loop``) behind an unbounded drain.
    """

    name = "stop_reading"
    masks_close = True

    def paused(self, link: "_Link") -> bool:
        return True


class ResetAfter(Toxic):
    """Forward ``n`` bytes of the direction, then hard-reset the whole
    connection (RST via SO_LINGER, not an orderly FIN)."""

    name = "reset"

    def __init__(self, n: int = 0):
        self.n = n

    async def process(self, link: "_Link", data: bytes) -> Optional[bytes]:
        passed = link.state.get(self, 0)
        if passed + len(data) <= self.n:
            link.state[self] = passed + len(data)
            return data
        keep = data[: max(self.n - passed, 0)]
        if keep:
            link.write(keep)
            await link.drain()
        link.abort()
        return None


class _Link:
    """Per-connection, per-direction state handed to toxics."""

    __slots__ = ("direction", "conn", "rng", "writer", "state")

    def __init__(self, direction: str, conn: "_ProxyConn", writer) -> None:
        self.direction = direction
        self.conn = conn
        self.rng = conn.proxy.rng
        self.writer = writer
        #: per-toxic scratch (byte counters etc.), keyed by toxic identity
        self.state: Dict[Toxic, int] = {}

    def write(self, data: bytes) -> None:
        if not self.conn.closed:
            self.writer.write(data)

    async def drain(self) -> None:
        if self.conn.closed:
            return
        try:
            await self.writer.drain()
        except (ConnectionError, OSError):
            self.conn.close()

    def abort(self) -> None:
        self.conn.abort()


class _ProxyConn:
    """One proxied client connection (client socket + upstream socket)."""

    def __init__(self, proxy: "ChaosProxy", c_reader, c_writer, u_reader, u_writer):
        self.proxy = proxy
        self.c_reader = c_reader
        self.c_writer = c_writer
        self.u_reader = u_reader
        self.u_writer = u_writer
        self.closed = False

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for w in (self.c_writer, self.u_writer):
            try:
                w.close()
            except Exception:  # noqa: BLE001 - already-dead transport
                pass

    def abort(self) -> None:
        """RST both sides: linger-0 so close() emits a reset, not a FIN."""
        if self.closed:
            return
        self.closed = True
        for w in (self.c_writer, self.u_writer):
            try:
                sock = w.get_extra_info("socket")
                if sock is not None:
                    sock.setsockopt(
                        socket.SOL_SOCKET,
                        socket.SO_LINGER,
                        struct.pack("ii", 1, 0),
                    )
                w.transport.abort()
            except Exception:  # noqa: BLE001 - already-dead transport
                pass


class ChaosProxy:
    """Seeded fault-injection TCP proxy in front of one upstream address.

    ``seed`` drives every toxic's randomness (reproducible runs);
    ``sock_buf`` shrinks the proxy-side socket buffers (SO_RCVBUF on the
    accepting side, SO_SNDBUF/SO_RCVBUF upstream) so buffer-filling toxics
    (:class:`StopReading`) bite after kilobytes instead of megabytes.
    """

    def __init__(
        self,
        upstream: Tuple[str, int],
        host: str = "127.0.0.1",
        port: int = 0,
        seed: Optional[int] = None,
        sock_buf: Optional[int] = None,
    ):
        import random

        self.upstream = upstream
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.rng = random.Random(seed)
        self.sock_buf = sock_buf
        self._toxics: Dict[str, List[Toxic]] = {UP: [], DOWN: []}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: Set[_ProxyConn] = set()
        self._tasks: Set[asyncio.Task] = set()

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "ChaosProxy":
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if self.sock_buf is not None:
                # Set BEFORE listen: accepted sockets inherit RCVBUF.
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_RCVBUF, self.sock_buf
                )
            sock.bind((self.host, self._requested_port))
            sock.setblocking(False)
        except OSError:
            sock.close()
            raise
        self._server = await asyncio.start_server(self._handle, sock=sock)
        self.port = self._server.sockets[0].getsockname()[1]
        log.debug(
            "ChaosProxy %s:%d -> %s:%d", self.host, self.port, *self.upstream
        )
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
        for conn in list(self._conns):
            conn.close()
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)

    async def __aenter__(self) -> "ChaosProxy":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    # -- toxic management (runtime-toggleable) ------------------------------

    def add(self, toxic: Toxic, direction: str = DOWN) -> Toxic:
        """Install ``toxic`` on ``direction``; live connections pick it up
        on their next chunk/read.  Returns the toxic (handle for remove)."""
        if direction not in self._toxics:
            raise ValueError(f"direction must be {UP!r} or {DOWN!r}")
        self._toxics[direction].append(toxic)
        return toxic

    def remove(self, toxic: Toxic) -> None:
        for chain in self._toxics.values():
            if toxic in chain:
                chain.remove(toxic)

    def clear(self) -> None:
        """Heal the link: drop every toxic (paused pumps resume)."""
        for chain in self._toxics.values():
            chain.clear()

    def toxics(self, direction: str) -> List[Toxic]:
        return list(self._toxics[direction])

    def drop_connections(self) -> None:
        """Sever every proxied connection (the upstream server stays up)."""
        for conn in list(self._conns):
            conn.close()

    # -- data path ----------------------------------------------------------

    async def _connect_upstream(self):
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            if self.sock_buf is not None:
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDBUF, self.sock_buf
                )
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_RCVBUF, self.sock_buf
                )
            sock.setblocking(False)
            await asyncio.get_running_loop().sock_connect(sock, self.upstream)
        except (ConnectionError, OSError):
            sock.close()
            raise
        return await asyncio.open_connection(sock=sock)

    async def _handle(self, c_reader, c_writer) -> None:
        try:
            u_reader, u_writer = await self._connect_upstream()
        except (ConnectionError, OSError):
            # Upstream down: refuse by closing (the accept already
            # succeeded — same shape as a mid-dial member kill).
            try:
                c_writer.close()
            except Exception:  # noqa: BLE001
                pass
            return
        conn = _ProxyConn(self, c_reader, c_writer, u_reader, u_writer)
        self._conns.add(conn)
        up = spawn_owned(
            self._pump(_Link(UP, conn, u_writer), c_reader), self._tasks
        )
        down = spawn_owned(
            self._pump(_Link(DOWN, conn, c_writer), u_reader), self._tasks
        )
        try:
            await asyncio.gather(up, down, return_exceptions=True)
        finally:
            self._conns.discard(conn)
            conn.close()

    async def _pump(self, link: _Link, reader) -> None:
        conn = link.conn
        try:
            while not conn.closed:
                # StopReading gate: while any installed toxic pauses this
                # direction the pump must NOT touch the socket — kernel
                # buffers filling up IS the fault being injected.
                if any(
                    t.paused(link) for t in self._toxics[link.direction]
                ):
                    await asyncio.sleep(_PAUSE_POLL_S)
                    continue
                data = await reader.read(_READ_SIZE)
                if not data:
                    break  # orderly EOF from the source
                for toxic in self.toxics(link.direction):
                    data = await toxic.process(link, data)
                    if data is None:
                        break
                if data:
                    link.write(data)
                    await link.drain()
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            conn.close()
            return
        if conn.closed:
            return
        if any(t.masks_close for t in self._toxics[link.direction]):
            # Half-open: the source hung up but the fault being modeled is
            # "peer vanished without a FIN" — leave the other side open
            # and silent; the client's watchdog/deadline must save it.
            return
        conn.close()


#: name -> factory(rng) for storm-style random toxic injection
#: (tests/test_chaos.py draws from this with its seeded RNG).  The storm
#: set leans transient — every entry here either passes traffic through
#: eventually or resets the connection, so a converging storm stays
#: convergeable; the forever-silent toxics (Blackhole, StopReading,
#: Truncate) are deliberately not in it and are exercised by the
#: deterministic per-toxic tests instead.
STORM_TOXICS = {
    "latency": lambda rng: Latency(
        latency_ms=rng.uniform(5, 40), jitter_ms=rng.uniform(0, 15)
    ),
    "bandwidth": lambda rng: Bandwidth(bytes_per_s=rng.uniform(8, 64) * 1024),
    "slicer": lambda rng: Slicer(max_size=rng.randint(2, 16)),
    "reset": lambda rng: ResetAfter(n=rng.randint(0, 4096)),
}


async def proxy_fleet(
    addresses: Sequence[Tuple[str, int]],
    rng=None,
    sock_buf: Optional[int] = None,
) -> List["ChaosProxy"]:
    """One started :class:`ChaosProxy` per upstream address, each with
    its own seed drawn from ``rng`` (a ``random.Random``; None = module
    RNG).

    The ensemble front-door shape (ISSUE 10): a client pointed at the
    returned proxies' addresses reaches every ensemble member through an
    independently faultable wire — the chaos storm's ensemble leg and
    the SLO harness's ensemble mode both build their fleets with this,
    so per-member network faults and member kills compose.  Callers own
    the proxies (``stop()`` each when done).
    """
    import random as random_mod

    draw = (rng or random_mod).randrange
    proxies: List[ChaosProxy] = []
    try:
        for address in addresses:
            proxies.append(
                await ChaosProxy(
                    address, seed=draw(2**32), sock_buf=sock_buf
                ).start()
            )
    except BaseException:
        for proxy in proxies:
            await proxy.stop()
        raise
    return proxies
