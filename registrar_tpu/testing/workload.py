"""Seeded heavy-tailed workload storms for the sharded serve tier.

ISSUE 17's offense side.  Every number in BENCH_HISTORY and
SLO_HISTORY before this module drove uniform, well-behaved load; the
traffic a Binder-shaped resolver actually faces is none of those
things.  :class:`StormWorkload` drives the tier over the REAL client
paths (:class:`~registrar_tpu.shard.ShardDirectClient` per storm
client — the SO_REUSEPORT-shaped data plane the DNS frontend will use)
with the traffic mix the serve tier's armor exists for:

- **Zipf popularity** over the warm domain set (a handful of names take
  most of the hits — the head keeps every shard's warm slice hot while
  the tail forces cache churn),
- **flash-crowd bursts** concentrated on ONE shard's hash-ring slice
  (the victim is derived from the same deterministic ring the router
  uses, so a seeded storm always picks the same shard),
- **churned never-exists names** (each draw is a fresh name, so every
  one is a distinct negative-cache fill — the cold-fill stampede),
- **malformed frames** (the PR-15 hostile-input corpus shapes: short
  resolve bodies, qtype overruns, truncated trace blocks),
- **slow-loris clients** (flood pipelined resolves, then read one byte
  per poll — the netem ``StopReading`` toxic's behavior applied to the
  serve side's unix socket, where a TCP proxy can't sit), and
- **half-open clients** (a length prefix promising bytes that never
  come — the ``Truncate`` shape).

Outcomes are classified hard: an admitted answer, an explicit shed
(:class:`~registrar_tpu.shard.ShardShedError` with its reason), an
error, or a timeout.  The armored tier's contract — asserted by the
SLO scenario and gated by bench — is that the **timeout bucket stays
empty**: overload answers are fast answers or fast refusals, never
silence.

Everything is seeded.  The same ``seed`` draws the same names in the
same proportions, which is what lets tools/slo.py re-run one storm
with the armor withheld (``repair=False``) and prove the same traffic
collapses an unarmored tier.
"""

from __future__ import annotations

import asyncio
import bisect
import random
import socket
import struct
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from registrar_tpu.shard import (
    _HDR,
    DEFAULT_VNODES,
    OP_RESOLVE,
    TRACE_FLAG,
    HashRing,
    ShardClient,
    ShardDirectClient,
    ShardError,
    ShardShedError,
    pack_request,
    pack_resolve,
)

__all__ = [
    "StormReport",
    "StormWorkload",
    "half_open",
    "malformed_resolve_frames",
    "measure_capacity",
    "slow_loris",
    "zipf_weights",
]

#: traffic classes a resolver draw can belong to
CLASSES = ("warm", "flash", "churn")


def zipf_weights(n: int, s: float = 1.2) -> List[float]:
    """Zipf(s) popularity weights for ranks 1..n (unnormalized)."""
    return [1.0 / (rank ** s) for rank in range(1, n + 1)]


class _ZipfPicker:
    """Seedable O(log n) Zipf draw over an ordered name list."""

    def __init__(self, names: Sequence[str], s: float = 1.2):
        self.names = list(names)
        cum: List[float] = []
        total = 0.0
        for w in zipf_weights(len(self.names), s):
            total += w
            cum.append(total)
        self._cum = cum
        self._total = total

    def pick(self, rng: random.Random) -> str:
        i = bisect.bisect_left(self._cum, rng.random() * self._total)
        return self.names[min(i, len(self.names) - 1)]


def _quantile_ms(samples: List[float], q: float) -> Optional[float]:
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
    return round(ordered[idx] * 1000.0, 4)


class StormReport:
    """Mutable outcome ledger one storm run fills in, then summarizes."""

    def __init__(self, seed: int):
        self.seed = seed
        self.sent = {cls: 0 for cls in CLASSES}
        self.ok = {cls: 0 for cls in CLASSES}
        self.errors = {cls: 0 for cls in CLASSES}
        self.timeouts = {cls: 0 for cls in CLASSES}
        #: explicit sheds by reason (the client-visible taxonomy)
        self.sheds: Dict[str, int] = {}
        #: seconds, admitted warm+flash answers only (the bench p99)
        self.admitted_warm_s: List[float] = []
        #: seconds to an explicit shed reply (must be FAST — the
        #: fail-fast half of the contract)
        self.shed_s: List[float] = []
        self.duration_s = 0.0
        self.loris = {"conns": 0, "disconnected": 0, "frames": 0}
        self.half_open = {"conns": 0, "held": 0}
        self.malformed = {"sent": 0, "answered": 0}

    @property
    def sent_total(self) -> int:
        return sum(self.sent.values())

    @property
    def sheds_total(self) -> int:
        return sum(self.sheds.values())

    @property
    def timeouts_total(self) -> int:
        return sum(self.timeouts.values())

    def record_shed(self, reason: str, elapsed_s: float) -> None:
        self.sheds[reason] = self.sheds.get(reason, 0) + 1
        self.shed_s.append(elapsed_s)

    def summary(self) -> Dict[str, Any]:
        """The storm envelope: what bench prints and the SLO fault
        event records."""
        return {
            "seed": self.seed,
            "duration_s": round(self.duration_s, 3),
            "offered_rps": (
                round(self.sent_total / self.duration_s, 1)
                if self.duration_s
                else 0.0
            ),
            "classes": {
                cls: {
                    "sent": self.sent[cls],
                    "ok": self.ok[cls],
                    "errors": self.errors[cls],
                    "timeouts": self.timeouts[cls],
                }
                for cls in CLASSES
            },
            "sheds": dict(sorted(self.sheds.items())),
            "sheds_total": self.sheds_total,
            "timeouts_total": self.timeouts_total,
            "admitted_warm_p50_ms": _quantile_ms(self.admitted_warm_s, 0.50),
            "admitted_warm_p99_ms": _quantile_ms(self.admitted_warm_s, 0.99),
            "shed_fastfail_p99_ms": _quantile_ms(self.shed_s, 0.99),
            "loris": dict(self.loris),
            "half_open": dict(self.half_open),
            "malformed": dict(self.malformed),
        }


def malformed_resolve_frames(rng: random.Random, count: int) -> List[bytes]:
    """``count`` hostile OP_RESOLVE frames drawn from the PR-15 corpus
    shapes the worker classifies (and answers) as protocol errors:
    short body, qtype overrun, non-UTF-8 name, truncated trace block.
    Every frame keeps a VALID length prefix — the point is to poison
    the request, not the connection."""
    frames: List[bytes] = []
    for i in range(count):
        req_id = 0x7F000000 + i
        shape = rng.randrange(4)
        if shape == 0:
            # resolve body too short (< 2 bytes)
            frames.append(pack_request(req_id, OP_RESOLVE, b"\x00"))
        elif shape == 1:
            # qtype length overruns the body
            frames.append(
                pack_request(req_id, OP_RESOLVE, bytes((0, 200)) + b"A")
            )
        elif shape == 2:
            # name bytes that are not UTF-8
            frames.append(
                pack_request(
                    req_id, OP_RESOLVE, bytes((0, 1)) + b"A" + b"\xff\xfe"
                )
            )
        else:
            # trace flag set, frame too short for the context block
            frames.append(
                struct.pack(">I", _HDR.size + 2)
                + _HDR.pack(req_id, OP_RESOLVE | TRACE_FLAG)
                + b"xx"
            )
    return frames


async def _open_raw(socket_path: str, rcvbuf: Optional[int] = None):
    """A raw (reader, writer) pair on the shard unix socket, optionally
    with a tiny receive buffer (makes a non-reading client back-pressure
    the worker at KB scale, the same trick netem's ChaosProxy plays
    with ``sock_buf``)."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        if rcvbuf is not None:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
        sock.setblocking(False)
        await asyncio.get_running_loop().sock_connect(sock, socket_path)
    except BaseException:
        sock.close()
        raise
    return await asyncio.open_unix_connection(sock=sock)


async def slow_loris(
    socket_path: str,
    name: str = "loris.storm.slo.us",
    frames: int = 4000,
    hold_s: float = 2.0,
    rcvbuf: Optional[int] = 4096,
) -> Dict[str, Any]:
    """One slow-loris client against a shard socket: flood ``frames``
    pipelined resolves, then read ONE byte per poll (slow enough that
    the worker's reply buffer can only grow).  The armored worker's
    write deadline must disconnect us; an unarmored worker parks its
    handler tasks on ``drain()`` for as long as we care to hold.

    Returns ``{"disconnected": bool, "written": int, "read": int}`` —
    ``disconnected`` is the armor working.
    """
    reader, writer = await _open_raw(socket_path, rcvbuf=rcvbuf)
    written = 0
    read = 0
    disconnected = False
    deadline = time.monotonic() + hold_s
    try:
        body = pack_resolve(name, "A")
        chunk = b"".join(
            pack_request(i + 1, OP_RESOLVE, body) for i in range(frames)
        )
        writer.write(chunk)
        written = frames
        while time.monotonic() < deadline:
            try:
                # The slow read: one byte per 50 ms keeps us a reader in
                # name only.  EOF or a reset here IS the disconnect the
                # write-deadline armor promises.
                b = await asyncio.wait_for(reader.read(1), timeout=0.05)
                if not b:
                    disconnected = True
                    break
                read += 1
            except asyncio.TimeoutError:
                pass
            except (ConnectionError, OSError):
                disconnected = True
                break
        if not disconnected:
            # Verdict phase: on a unix socket the worker's abort()
            # surfaces as a clean EOF **behind** every reply byte
            # already buffered on our side — which the 1-byte/50 ms
            # read above would take minutes to drain.  Drain fast now:
            # reaching EOF means the worker hung up on us (the armor);
            # a still-live stream just runs the short timeout down.
            try:
                while True:
                    b = await asyncio.wait_for(
                        reader.read(65536), timeout=0.4
                    )
                    if not b:
                        disconnected = True
                        break
                    read += len(b)
            except asyncio.TimeoutError:
                pass
            except (ConnectionError, OSError):
                disconnected = True
    finally:
        try:
            writer.close()
        except Exception:  # noqa: BLE001 - hostile-client teardown
            pass
    return {"disconnected": disconnected, "written": written, "read": read}


async def half_open(
    socket_path: str,
    hold_s: float = 1.0,
) -> Dict[str, Any]:
    """One half-open client: a length prefix promising a frame that
    never arrives (netem's ``Truncate`` shape), held for ``hold_s``.
    The worker's read loop must simply wait it out — a half-open
    connection holds no in-flight slot, wedges nothing, and its EOF on
    close is a clean boundary for everyone else."""
    reader, writer = await _open_raw(socket_path)
    try:
        writer.write(struct.pack(">I", _HDR.size + 64))
        writer.write(_HDR.pack(1, OP_RESOLVE))
        await writer.drain()
        await asyncio.sleep(hold_s)
    finally:
        try:
            writer.close()
        except Exception:  # noqa: BLE001 - hostile-client teardown
            pass
    return {"held_s": hold_s}


async def measure_capacity(
    router_socket: str,
    names: Sequence[str],
    seconds: float = 0.4,
    clients: int = 4,
    pipeline: int = 4,
) -> float:
    """Measured warm-resolve capacity (requests/second): closed-loop
    round-robin resolves over ``names`` through the direct data plane.
    The number the "~5x capacity" storm sizing is anchored to."""
    done = 0
    deadline = time.monotonic() + seconds

    async def one_client(idx: int) -> None:
        nonlocal done
        client = await ShardDirectClient(router_socket).connect()
        try:
            i = idx
            while time.monotonic() < deadline:
                batch = [names[(i + k) % len(names)] for k in range(pipeline)]
                i += pipeline
                await asyncio.gather(
                    *(client.resolve(n, "A") for n in batch)
                )
                done += pipeline
        finally:
            await client.close()

    t0 = time.monotonic()
    await asyncio.gather(*(one_client(i) for i in range(clients)))
    elapsed = max(time.monotonic() - t0, 1e-6)
    return done / elapsed


class StormWorkload:
    """One seeded overload storm against a running sharded tier.

    ``warm_names`` must already resolve through the tier (the SLO
    harness hands its slice-probe domains; bench registers its own
    fixture set).  The flash-crowd victim shard is the owner of the
    LARGEST warm group on the same deterministic ring the router built,
    so one seed always storms one slice.

    ``offered_rps`` paces the resolver clients (None = unpaced, every
    client runs flat out); hostile connection counts are per-storm
    totals.  :meth:`run` returns the filled :class:`StormReport`.
    """

    def __init__(
        self,
        router_socket: str,
        warm_names: Sequence[str],
        seed: int,
        duration_s: float = 1.2,
        clients: int = 6,
        pipeline: int = 24,
        request_timeout_s: float = 2.0,
        offered_rps: Optional[float] = None,
        zipf_s: float = 1.2,
        churn_suffix: str = "churn.storm.slo.us",
        burst_every_s: float = 0.4,
        burst_s: float = 0.15,
        loris_conns: int = 2,
        loris_frames: int = 3000,
        half_open_conns: int = 1,
        malformed_frames: int = 24,
    ):
        if not warm_names:
            raise ValueError("a storm needs at least one warm name")
        self.router_socket = router_socket
        self.warm_names = list(warm_names)
        self.seed = int(seed)
        self.duration_s = duration_s
        self.clients = clients
        self.pipeline = pipeline
        self.request_timeout_s = request_timeout_s
        self.offered_rps = offered_rps
        self.zipf_s = zipf_s
        self.churn_suffix = churn_suffix
        self.burst_every_s = burst_every_s
        self.burst_s = burst_s
        self.loris_conns = loris_conns
        self.loris_frames = loris_frames
        self.half_open_conns = half_open_conns
        self.malformed_frames = malformed_frames
        self.report = StormReport(self.seed)
        self._churn_serial = 0
        self._deadline = 0.0
        self._t0 = 0.0

    # -- target selection ---------------------------------------------------

    async def _ring_info(self) -> Tuple[HashRing, Dict[int, str]]:
        async with ShardClient(self.router_socket) as rc:
            info = await rc.ring()
        sockets = {
            entry["shard"]: entry["socket"] for entry in info["shards"]
        }
        ring = HashRing(
            sockets.keys(), vnodes=info.get("vnodes", DEFAULT_VNODES)
        )
        return ring, sockets

    def _pick_victim(self, ring: HashRing) -> Tuple[int, List[str]]:
        """The flash-crowd victim: the shard owning the most warm names
        (ties break low, like the ring itself — deterministic)."""
        groups: Dict[int, List[str]] = {}
        for name in self.warm_names:
            groups.setdefault(
                ring.owner(name.rstrip(".").lower()), []
            ).append(name)
        victim = max(
            groups, key=lambda sid: (len(groups[sid]), -sid)
        )
        return victim, groups[victim]

    # -- the resolver storm --------------------------------------------------

    def _draw(
        self,
        rng: random.Random,
        warm: _ZipfPicker,
        flash: _ZipfPicker,
    ) -> Tuple[str, str]:
        """One (class, name) draw from the phase-dependent mixture."""
        elapsed = time.monotonic() - self._t0
        in_burst = (elapsed % self.burst_every_s) < self.burst_s
        r = rng.random()
        if in_burst:
            # Flash crowd: the victim slice takes the brunt.
            if r < 0.70:
                return "flash", flash.pick(rng)
            if r < 0.82:
                return "warm", warm.pick(rng)
        else:
            if r < 0.45:
                return "warm", warm.pick(rng)
            if r < 0.60:
                return "flash", flash.pick(rng)
        # Never-exists churn: every draw is a FRESH name, so every one
        # is a distinct negative-cache fill.
        self._churn_serial += 1
        return "churn", f"n{self._churn_serial}.{self.churn_suffix}"

    async def _one(self, client: ShardDirectClient, cls: str, name: str) -> None:
        rep = self.report
        rep.sent[cls] += 1
        t0 = time.monotonic()
        try:
            await asyncio.wait_for(
                client.resolve(name, "A"), self.request_timeout_s
            )
        except ShardShedError as err:
            rep.record_shed(err.reason, time.monotonic() - t0)
        except asyncio.TimeoutError:
            rep.timeouts[cls] += 1
        except (ShardError, ConnectionError, OSError):
            # Includes nonexistent-name errors on the churn class and
            # dead-worker connections in an unrepaired fleet: counted,
            # never fatal to the storm.
            rep.errors[cls] += 1
        else:
            rep.ok[cls] += 1
            if cls in ("warm", "flash"):
                rep.admitted_warm_s.append(time.monotonic() - t0)

    async def _resolver(
        self, idx: int, warm: _ZipfPicker, flash: _ZipfPicker
    ) -> None:
        rng = random.Random((self.seed * 1000003) ^ idx)
        try:
            client = await ShardDirectClient(self.router_socket).connect()
        except (ShardError, ConnectionError, OSError):
            self.report.errors["warm"] += 1
            return
        # Paced batches when offered_rps is set: each of C clients owes
        # offered/C requests per second, issued pipeline-at-a-time.
        batch_interval = (
            self.pipeline * self.clients / self.offered_rps
            if self.offered_rps
            else 0.0
        )
        try:
            while time.monotonic() < self._deadline:
                batch_t0 = time.monotonic()
                batch = [
                    self._draw(rng, warm, flash)
                    for _ in range(self.pipeline)
                ]
                await asyncio.gather(
                    *(self._one(client, cls, name) for cls, name in batch)
                )
                if batch_interval:
                    pause = batch_interval - (time.monotonic() - batch_t0)
                    if pause > 0:
                        await asyncio.sleep(
                            min(pause, self._deadline - time.monotonic())
                        )
        finally:
            await client.close()

    # -- the hostile connections --------------------------------------------

    async def _loris(self, victim_socket: str, idx: int) -> None:
        self.report.loris["conns"] += 1
        hold = max(self.duration_s - 0.1, 0.2)
        try:
            out = await slow_loris(
                victim_socket,
                name=self.warm_names[idx % len(self.warm_names)],
                frames=self.loris_frames,
                hold_s=hold,
            )
        except (ConnectionError, OSError):
            self.report.loris["disconnected"] += 1
            return
        self.report.loris["frames"] += out["written"]
        if out["disconnected"]:
            self.report.loris["disconnected"] += 1

    async def _half_open(self, victim_socket: str) -> None:
        self.report.half_open["conns"] += 1
        try:
            await half_open(
                victim_socket, hold_s=max(self.duration_s - 0.1, 0.2)
            )
            self.report.half_open["held"] += 1
        except (ConnectionError, OSError):
            pass

    async def _malformed(self, victim_socket: str) -> None:
        rng = random.Random(self.seed ^ 0x6D616C66)
        frames = malformed_resolve_frames(rng, self.malformed_frames)
        self.report.malformed["sent"] = len(frames)
        try:
            reader, writer = await _open_raw(victim_socket)
        except (ConnectionError, OSError):
            return
        try:
            writer.write(b"".join(frames))
            await writer.drain()
            answered = 0
            deadline = time.monotonic() + min(self.duration_s, 1.0)
            while answered < len(frames) and time.monotonic() < deadline:
                try:
                    head = await asyncio.wait_for(
                        reader.readexactly(4), timeout=0.2
                    )
                    (size,) = struct.unpack(">I", head)
                    await asyncio.wait_for(
                        reader.readexactly(size), timeout=0.2
                    )
                    answered += 1
                except (
                    asyncio.TimeoutError,
                    asyncio.IncompleteReadError,
                    ConnectionError,
                    OSError,
                ):
                    break
            self.report.malformed["answered"] = answered
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - hostile-client teardown
                pass

    # -- run ----------------------------------------------------------------

    async def run(self) -> StormReport:
        ring, sockets = await self._ring_info()
        victim, victim_names = self._pick_victim(ring)
        victim_socket = sockets[victim]
        warm = _ZipfPicker(self.warm_names, self.zipf_s)
        flash = _ZipfPicker(victim_names, self.zipf_s)
        self._t0 = time.monotonic()
        self._deadline = self._t0 + self.duration_s
        tasks = [
            self._resolver(i, warm, flash) for i in range(self.clients)
        ]
        tasks += [
            self._loris(victim_socket, i) for i in range(self.loris_conns)
        ]
        tasks += [
            self._half_open(victim_socket)
            for _ in range(self.half_open_conns)
        ]
        if self.malformed_frames:
            tasks.append(self._malformed(victim_socket))
        await asyncio.gather(*tasks)
        self.report.duration_s = time.monotonic() - self._t0
        return self.report
