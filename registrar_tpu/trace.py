"""Dependency-free operation tracing: spans, events, a flight recorder.

The reference's only observability is bunyan lines on stdout (reference
main.js:23-28) and our metrics.py is counters and point-gauges — neither
can say *where the time went* inside one operation, or *what the daemon
was doing* in the seconds before chaos killed it.  This module is the
missing layer (ISSUE 8 tentpole):

  * **Spans** — named, attributed, monotonic-clocked intervals with
    trace/span/parent ids, propagated across ``await`` boundaries and
    task spawns via :mod:`contextvars` (an ``asyncio.create_task`` copies
    the context, so a repair task's pipeline spans chain to the repair
    span that spawned them).  Span names are a documented contract:
    docs/OBSERVABILITY.md carries the catalog, and checklib's
    span-name-drift rule diffs the code against it.
  * **Events** — instantaneous points (a cache invalidation, a session
    loss) recorded into the same ring with the active trace id.
  * **Flight recorder** — a bounded in-memory ring of recently completed
    spans + events.  Dumped to a file on SIGUSR2 (main.py) and served at
    ``GET /debug/trace?n=`` (metrics.MetricsServer) — the post-incident
    "what was it doing" record that logs alone cannot reconstruct.
  * **Sinks** — every finished sampled span is offered to registered
    sink callables; :func:`registrar_tpu.metrics.instrument_tracing`
    feeds the latency histograms from exactly this hook.
  * **Slow spans** — a span outlasting ``slow_span_ms`` logs a
    warn-level line with its full parent chain, so "this resolve was
    slow" arrives pre-annotated with *what it was part of*.
  * **Log correlation** — :class:`TraceContextFilter` stamps the active
    trace_id/span_id onto every log record it filters; jlog's
    BunyanFormatter renders them when present (and only then — with
    tracing off, not a byte of log output changes).

Everything is opt-in via the ``observability`` config block
(docs/CONFIG.md).  **Default OFF is reference parity**: the module
default is :data:`DISABLED`, whose ``span()`` returns a shared no-op and
whose ``event()`` does nothing — zero allocations, zero new wire
operations, zero new log/metric output (pinned by
tests/test_trace.py's parity tests).

Instrumented code resolves its tracer through :func:`tracer_for`, so a
test (or the chaos harness) can hang a private :class:`Tracer` on one
client/cache (``obj.tracer = Tracer(...)``) without touching the global,
while the daemon configures the process-wide default once
(:func:`set_tracer`) and every subsystem picks it up.

Sampling is head-based: the decision is made once when a trace ROOT is
created (``sample_rate``), and every child span inherits the verdict —
an unsampled trace still propagates ids (log correlation keeps working)
but records nothing and feeds no sinks.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import tempfile
import time
from collections import deque
from typing import Any, Dict, List, Optional

log = logging.getLogger("registrar_tpu.trace")

#: default bound on the flight-recorder ring (spans + events)
DEFAULT_MAX_SPANS = 1024

#: the active span (or None), propagated by asyncio's context copying
_current: contextvars.ContextVar = contextvars.ContextVar(
    "registrar_trace_span", default=None
)

#: ambient attrs stamped onto every span/event created while an
#: :class:`annotate` block is active (or None — the common case costs
#: one contextvar read per span).  Propagates across awaits and task
#: spawns exactly like the current span.
_ambient: contextvars.ContextVar = contextvars.ContextVar(
    "registrar_trace_ambient", default=None
)


class annotate:
    """Stamp extra attrs onto every span and event created inside the
    block — across every nested call layer (ISSUE 9).

    The SLO harness wraps each availability probe in
    ``annotate(scenario=..., faults=...)`` so the probe's whole span
    tree — ``slo.probe`` down through ``resolve.query`` and the
    ``zk.op`` leaves — carries the active scenario and fault-class
    marks without threading them through the resolver's signatures.  An
    outage pulled out of the flight recorder is then attributable on
    sight.  Explicit attrs passed at the call site win over ambient
    ones on a key collision.  Nesting merges (inner blocks override per
    key); exiting restores the enclosing block's view."""

    __slots__ = ("attrs", "_token")

    def __init__(self, **attrs):
        self.attrs = attrs
        self._token = None

    def __enter__(self) -> "annotate":
        current = _ambient.get()
        merged = {**current, **self.attrs} if current else dict(self.attrs)
        self._token = _ambient.set(merged)
        return self

    def __exit__(self, *_exc) -> bool:
        if self._token is not None:
            _ambient.reset(self._token)
            self._token = None
        return False


class _NoopSpan:
    """The shared do-nothing span a disabled tracer hands out.

    One module-level instance: entering/exiting it is two cheap method
    calls and zero allocations, which is what "default OFF = reference
    parity" costs on every instrumented path.
    """

    __slots__ = ()
    sampled = False
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *_exc) -> bool:
        return False

    def mark(self, _name: str) -> None:
        pass

    def set_mark(self, _name: str, _seconds: float) -> None:
        pass

    def finish(self, status: str = "ok", **attrs) -> None:
        pass

    def set_attr(self, _key: str, _value) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def current_context():
    """The active span's wire-propagation triple — ``(trace_id,
    parent_span_id, sampled)`` as ``(int, int, int)`` — or None when no
    span is active (or tracing is off, since the no-op span carries no
    ids).  This is THE injection rule for every cross-process boundary:
    the shard protocol's trace-context block (ISSUE 13) and the
    health-check env stamps both serialize exactly this triple, so a
    remote process adopting it chains its spans under the caller's.
    """
    sp = _current.get()
    trace_id = getattr(sp, "trace_id", None)
    if trace_id is None:
        return None
    return (int(trace_id, 16), int(sp.span_id, 16), 1 if sp.sampled else 0)


class Span:
    """One traced interval.  Use as a context manager to also make it
    the *current* span (children parent to it); or keep the handle and
    :meth:`finish` it manually for intervals that end outside the
    creating context (the ZK client's queue/wire op spans end in the
    read loop's frame dispatch, not in the caller's coroutine)."""

    __slots__ = (
        "tracer", "name", "trace_id", "span_id", "parent_id", "parent",
        "attrs", "status", "sampled", "start", "wall_start", "duration_s",
        "marks", "_token", "_done",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        parent: Optional["Span"],
        sampled: bool,
        attrs: Dict[str, Any],
    ):
        self.tracer = tracer
        self.name = name
        self.parent = parent
        self.sampled = sampled
        # ids generated inline (no helper method): span creation sits
        # on the per-resolve hot path the bench holds to <10% overhead,
        # and two extra method calls per span are measurable there.
        rng = tracer._rng
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = f"{rng.getrandbits(64):016x}"
            self.parent_id = None
        self.span_id = f"{rng.getrandbits(64):016x}"
        self.attrs = attrs
        self.status = "ok"
        self.start = time.monotonic()
        self.wall_start = time.time()
        self.duration_s: Optional[float] = None
        #: named offsets (seconds from start) — the queue/wire split.
        #: Lazily allocated: most spans never mark, and this sits on the
        #: per-resolve hot path the bench holds to <10% overhead.
        self.marks: Optional[Dict[str, float]] = None
        self._token = None
        self._done = False

    # -- context-manager activation ---------------------------------------

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, _tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc is not None:
            self.finish("error", err=repr(exc))
        else:
            self.finish()
        return False  # never swallow

    # -- manual lifecycle --------------------------------------------------

    def mark(self, name: str) -> None:
        """Stamp a named offset (e.g. ``flushed``) on the span."""
        if self.marks is None:
            self.marks = {}
        self.marks[name] = time.monotonic() - self.start

    def set_mark(self, name: str, seconds: float) -> None:
        """Record an externally-measured mark value (seconds).  The
        shard relay span stamps the WORKER's self-reported handling
        time this way — a duration another process measured, not an
        offset on this span's own clock."""
        if self.marks is None:
            self.marks = {}
        self.marks[name] = seconds

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def chain(self) -> List[str]:
        """Span names root-first down to this span (slow-span evidence)."""
        names: List[str] = []
        sp: Optional[Span] = self
        while sp is not None:
            names.append(sp.name)
            sp = sp.parent
        names.reverse()
        return names

    def finish(self, status: str = "ok", **attrs) -> None:
        """End the span: record duration, feed the recorder and sinks.

        Idempotent — a span that already finished (e.g. failed by the
        connection teardown, then seen again by a late reply) is left
        with its first verdict.
        """
        if self._done:
            return
        self._done = True
        self.duration_s = time.monotonic() - self.start
        self.status = status
        if attrs:
            self.attrs.update(attrs)
        if self.sampled:
            # inlined tracer._record_span (hot path, see class docstring)
            tracer = self.tracer
            tracer.spans_recorded += 1
            tracer._ring.append(self)
            for sink in tracer._sinks:
                try:
                    sink(self)
                except Exception:  # noqa: BLE001 - sinks must not break tracing
                    log.exception("span sink raised")
            if tracer.slow_span_ms is not None:
                tracer._check_slow(self)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "time": self.wall_start,
            "duration_ms": (
                round(self.duration_s * 1000.0, 3)
                if self.duration_s is not None
                else None
            ),
            "status": self.status,
            "attrs": dict(self.attrs),
            "marks": (
                {k: round(v * 1000.0, 3) for k, v in self.marks.items()}
                if self.marks
                else {}
            ),
        }


class _RemoteSpan(Span):
    """A wire-adopted parent anchor: a span that LIVES in another
    process, reconstructed here from a propagated ``(trace_id,
    parent_span_id, sampled)`` triple so local spans chain under it.

    Never recorded (``_done`` is born True — the owning process records
    the real span), never finished, zero new ids: entering it only makes
    it the *current* span, so every child created inside inherits the
    remote trace id and parents to the remote span id.  The assembly
    layer (:mod:`registrar_tpu.traceview`) then joins the fragments
    across processes by exactly those ids.
    """

    def __init__(self, tracer, trace_id: int, span_id: int, sampled: bool):
        # Deliberately NOT Span.__init__: the ids come off the wire,
        # nothing here is ever recorded, and the anchor sits on the
        # traced wire hot path (one per adopted request) — so only the
        # slots children/start_span/chain actually read are set, and no
        # clocks are sampled.
        self.tracer = tracer
        self.name = "<remote>"
        self.parent = None
        self.sampled = sampled
        self.trace_id = f"{trace_id & 0xFFFFFFFFFFFFFFFF:016x}"
        self.span_id = f"{span_id & 0xFFFFFFFFFFFFFFFF:016x}"
        self._token = None
        self._done = True  # finish() is a no-op; the remote owner records


class Tracer:
    """One span factory + flight recorder + sink fan-out.

    ``sample_rate`` gates trace roots (children inherit);
    ``slow_span_ms`` (None = off) logs a warn line with the parent chain
    for any sampled span outlasting it; ``max_spans`` bounds the
    recorder ring.  ``rng`` injects determinism for tests.
    """

    enabled = True

    def __init__(
        self,
        sample_rate: float = 1.0,
        slow_span_ms: Optional[float] = None,
        max_spans: int = DEFAULT_MAX_SPANS,
        rng: Optional[random.Random] = None,
    ):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError("sample_rate must be within [0, 1]")
        if max_spans < 1:
            raise ValueError("max_spans must be >= 1")
        self.sample_rate = sample_rate
        self.slow_span_ms = slow_span_ms
        self._rng = rng if rng is not None else random.Random()
        self._ring: deque = deque(maxlen=max_spans)
        self._sinks: List = []
        #: completed sampled spans / recorded events (ring evictions
        #: excluded — the counters keep growing; the ring is bounded)
        self.spans_recorded = 0
        self.events_recorded = 0

    # -- span creation ------------------------------------------------------

    def start_span(self, name: str, **attrs) -> Span:
        parent = _current.get()
        if parent is NOOP_SPAN:
            parent = None
        if parent is not None and parent.tracer is not self:
            # Crossing tracer boundaries (a privately-traced cache under
            # a globally-traced caller): start a fresh root rather than
            # chaining into a span another recorder owns.
            parent = None
        sampled = (
            parent.sampled
            if parent is not None
            else (
                self.sample_rate >= 1.0
                or self._rng.random() < self.sample_rate
            )
        )
        ambient = _ambient.get()
        if ambient:
            attrs = {**ambient, **attrs}
        return Span(self, name, parent, sampled, attrs)

    #: ``span`` is the same method, not a delegating wrapper — one
    #: Python call per span creation is measurable on the traced hot
    #: path (a new span under the current one, context-manager ready).
    span = start_span

    def adopt(self, trace_id: int, parent_span_id: int, sampled: bool):
        """Adopt a wire-propagated context (ISSUE 13): returns a
        context manager making the REMOTE span the current parent, so
        every span created inside chains under the caller across the
        process boundary.  ``trace_id``/``parent_span_id`` are the u64
        ints off the wire (:func:`current_context`'s triple); the
        remote head-based ``sampled`` verdict is inherited whole — an
        unsampled remote trace propagates ids but records nothing here
        either."""
        return _RemoteSpan(self, trace_id, parent_span_id, bool(sampled))

    def event(self, name: str, **attrs) -> None:
        """Record an instantaneous point into the flight recorder.

        An event inside an *unsampled* trace is dropped — the head-based
        verdict covers the whole trace, spans and events alike (else a
        low sampleRate would still let a churning path's events evict
        the rare sampled spans from the ring).  An event outside any
        trace has no verdict to inherit and always records.
        """
        sp = _current.get()
        trace_id = None
        if isinstance(sp, Span) and sp.tracer is self:
            if not sp.sampled:
                return
            trace_id = sp.trace_id
        ambient = _ambient.get()
        if ambient:
            attrs = {**ambient, **attrs}
        self.events_recorded += 1
        self._ring.append(
            {
                "kind": "event",
                "name": name,
                "time": time.time(),
                "trace_id": trace_id,
                "attrs": attrs,
            }
        )

    # -- sinks / recorder ---------------------------------------------------

    def on_span(self, sink) -> None:
        """Register ``sink(span)`` for every finished sampled span."""
        self._sinks.append(sink)

    def _check_slow(self, span: Span) -> None:
        """Emit the slow-span warn line when ``span`` outlasts the
        threshold.  Recording itself is inlined in :meth:`Span.finish`
        (the ring holds the finished Span; dump() renders — building a
        dict per span would tax every traced hot-path operation to
        serve the rare dump)."""
        if not (
            span.duration_s is not None
            and span.duration_s * 1000.0 >= self.slow_span_ms
        ):
            return
        log.warning(
            "slow span: %s took %.1fms (threshold %.0fms)",
            span.name, span.duration_s * 1000.0, self.slow_span_ms,
            extra={
                "zdata": {
                    "trace_id": span.trace_id,
                    "span_id": span.span_id,
                    "span": span.name,
                    "durationMs": round(span.duration_s * 1000.0, 3),
                    "chain": span.chain(),
                    "attrs": {
                        k: _jsonable(v) for k, v in span.attrs.items()
                    },
                }
            },
        )

    def dump(
        self, n: Optional[int] = None, trace_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """The flight recorder's contents, newest last.

        ``n`` bounds to the most recent n entries (None/<=0 = all);
        ``trace_id`` keeps only one trace's spans and events (the
        OP_TRACE collection path — a worker answers exactly the
        fragment the assembler asked for, not its whole ring)."""
        entries = list(self._ring)
        if trace_id is not None:
            entries = [
                e
                for e in entries
                if (
                    e.trace_id if isinstance(e, Span) else e.get("trace_id")
                ) == trace_id
            ]
        if n is not None and n > 0:
            entries = entries[-n:]
        entries = [
            e.to_dict() if isinstance(e, Span) else e for e in entries
        ]
        return {
            "enabled": True,
            "sample_rate": self.sample_rate,
            "spans_recorded": self.spans_recorded,
            "events_recorded": self.events_recorded,
            "entries": entries,
        }

    def dump_to_file(self, path: Optional[str] = None) -> str:
        """Write the recorder to ``path`` (default: a pid-suffixed file
        in the system temp dir).  Returns the path written."""
        return write_dump(self.dump(), path)


def write_dump(payload: Dict[str, Any], path: Optional[str] = None) -> str:
    """Write an already-snapshotted :meth:`Tracer.dump` payload to
    ``path`` (default: a pid-suffixed file in the system temp dir),
    stamping ``dumped_at``/``pid``.  Returns the path written.

    Split from the snapshot so a caller on the event loop can take the
    snapshot there and hand only this blocking file I/O to a worker
    thread — main.py's SIGUSR2 handler does exactly that (a wedged
    filesystem at ``dumpPath`` must not stall the loop past the session
    timeout; the statefile writer learned the same lesson in PR 5).
    """
    if path is None:
        path = os.path.join(
            tempfile.gettempdir(), f"registrar-trace-{os.getpid()}.json"
        )
    payload["dumped_at"] = time.time()
    payload["pid"] = os.getpid()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, default=str)
        fh.write("\n")
    return path


class _DisabledTracer:
    """The reference-parity default: every call is a no-op."""

    enabled = False
    sample_rate = 0.0

    def span(self, _name: str, **_attrs) -> _NoopSpan:
        return NOOP_SPAN

    def start_span(self, _name: str, **_attrs) -> _NoopSpan:
        return NOOP_SPAN

    def event(self, _name: str, **_attrs) -> None:
        pass

    def on_span(self, _sink) -> None:
        pass

    def adopt(self, _trace_id: int, _parent_span_id: int, _sampled: bool):
        return NOOP_SPAN

    def dump(
        self,
        _n: Optional[int] = None,
        _trace_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        return {"enabled": False, "entries": []}


DISABLED = _DisabledTracer()

_default = DISABLED


def get_tracer():
    """The process-wide tracer (``DISABLED`` unless configured)."""
    return _default


def set_tracer(tracer) -> None:
    """Install (or, with None, uninstall) the process-wide tracer."""
    global _default
    _default = tracer if tracer is not None else DISABLED


def tracer_for(obj):
    """The tracer an instrumented call should use: the ``tracer``
    attribute hung on ``obj`` (a client, a cache, a health checker) when
    set, else the process-wide default.  THE one resolution rule, so a
    privately-traced object in a test and the daemon's global
    configuration go through identical code."""
    tracer = getattr(obj, "tracer", None)
    return tracer if tracer is not None else _default


def _jsonable(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


class TraceContextFilter(logging.Filter):
    """Stamps the active trace_id/span_id onto every record it filters.

    Installed on the root handlers by main.py when the ``observability``
    block is present; :class:`registrar_tpu.jlog.BunyanFormatter` renders
    the fields when (and only when) they are set, so with tracing off
    the log output is byte-identical to before.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        sp = _current.get()
        if isinstance(sp, Span):
            record.trace_id = sp.trace_id
            record.span_id = sp.span_id
        return True
