"""Length-prefixed frame buffering shared by the client and the server.

ZooKeeper's wire protocol frames every packet with a 4-byte big-endian
length (reference counterpart: the zkplus stack's socket framing; the
Apache client's ClientCnxnSocket does the same).  Both ends of this
rebuild read in bulk — one transport ``read()`` per TCP burst — and
carve complete frames out of a local buffer, instead of issuing two
awaited ``readexactly()`` calls per frame.  Pipelined storms (mkdirp,
heartbeat sweeps, registration fan-outs) land hundreds of frames per
segment, where the per-frame await overhead was a measurable slice of
the hot loops (docs/PERF.md).
"""

from __future__ import annotations

from typing import List, Optional

MAX_FRAME = 4 * 1024 * 1024  # matches real ZK's default jute.maxbuffer
_READ_SIZE = 65536


class FrameReader:
    """Buffered frame carving over an ``asyncio.StreamReader``."""

    __slots__ = ("_reader", "_buf")

    def __init__(self, reader) -> None:
        self._reader = reader
        self._buf = bytearray()

    async def fill(self) -> bool:
        """One transport read into the buffer; False on EOF/conn error."""
        try:
            chunk = await self._reader.read(_READ_SIZE)
        except (ConnectionError, OSError):
            return False
        if not chunk:
            return False
        self._buf += chunk
        return True

    def carve(self) -> List[bytes]:
        """Every complete frame payload currently buffered, in order.

        Raises ConnectionError on a corrupt length prefix — the stream
        has lost framing and cannot be resynchronized.
        """
        buf = self._buf
        pos, end = 0, len(buf)
        out: List[bytes] = []
        while end - pos >= 4:
            length = int.from_bytes(buf[pos:pos + 4], "big", signed=True)
            if length < 0 or length > MAX_FRAME:
                raise ConnectionError(f"bad frame length {length}")
            if end - pos - 4 < length:
                break
            out.append(bytes(buf[pos + 4:pos + 4 + length]))
            pos += 4 + length
        if pos:
            del buf[:pos]
        return out

    def pending(self) -> bool:
        """True when a complete frame is already buffered (reply batchers
        hold their flush until the input burst is exhausted)."""
        buf = self._buf
        if len(buf) < 4:
            return False
        length = int.from_bytes(buf[:4], "big", signed=True)
        return 0 <= length <= len(buf) - 4

    async def read4(self) -> Optional[bytes]:
        """The stream's next 4 bytes (a frame length — or a 4lw command)."""
        while len(self._buf) < 4:
            if not await self.fill():
                return None
        out = bytes(self._buf[:4])
        del self._buf[:4]
        return out

    async def frame(self, header: Optional[bytes] = None) -> Optional[bytes]:
        """The next complete frame payload; None on EOF or bad length.

        ``header`` supplies a 4-byte length already consumed via
        :meth:`read4` (the server handshake peeks it to disambiguate
        4lw admin commands from the ConnectRequest frame).
        """
        if header is not None:
            length = int.from_bytes(header, "big", signed=True)
        else:
            while len(self._buf) < 4:
                if not await self.fill():
                    return None
            length = int.from_bytes(self._buf[:4], "big", signed=True)
            del self._buf[:4]
        if length < 0 or length > MAX_FRAME:
            return None
        while len(self._buf) < length:
            if not await self.fill():
                return None
        out = bytes(self._buf[:length])
        del self._buf[:length]
        return out
