"""Length-prefixed frame buffering shared by the client and the server.

ZooKeeper's wire protocol frames every packet with a 4-byte big-endian
length (reference counterpart: the zkplus stack's socket framing; the
Apache client's ClientCnxnSocket does the same).  Both ends of this
rebuild read in bulk — one transport ``read()`` per TCP burst — and
carve complete frames out of the buffered data, instead of issuing two
awaited ``readexactly()`` calls per frame.  Pipelined storms (mkdirp,
heartbeat sweeps, registration fan-outs) land hundreds of frames per
segment, where the per-frame await overhead was a measurable slice of
the hot loops (docs/PERF.md).

Zero-copy carving (ISSUE 11): the transport's ``read()`` already hands
back a fresh immutable ``bytes`` chunk per call, so the reader keeps a
deque of those chunks AS IS instead of appending them into one growing
``bytearray``.  A frame that lies inside a single chunk — the common
case; a burst chunk carries many whole frames — is returned as a
``memoryview`` into that chunk: no copy on ingest, no copy on carve
(the old buffer made both, plus a memmove-compaction of the tail on
every fill).  Only a frame that genuinely spans chunks is joined into
fresh ``bytes`` (one copy, at the chunk boundary it crosses).  Exhausted
chunks are dropped as consumption passes them, so a 10k-znode sweep
burst never re-copies or even retains the front of the burst — the
growth policy is O(bytes ingested), never quadratic.

The views stay valid for as long as a consumer holds them (the chunks
are immutable and reference-counted); a pending reply future that parses
its body later pins at most its own chunk, briefly.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional

from registrar_tpu import malformed

MAX_FRAME = 4 * 1024 * 1024  # matches real ZK's default jute.maxbuffer
_READ_SIZE = 65536


class FrameReader:
    """Buffered frame carving over an ``asyncio.StreamReader``."""

    __slots__ = ("_reader", "_chunks", "_pos", "_size")

    def __init__(self, reader) -> None:
        self._reader = reader
        #: unconsumed receive chunks, oldest first (immutable bytes)
        self._chunks: Deque[bytes] = deque()
        self._pos = 0  # consumed prefix of _chunks[0]
        self._size = 0  # total unconsumed bytes across all chunks

    async def fill(self) -> bool:
        """Ingest the transport's whole buffered burst; False on EOF/error.

        The first ``read()`` may block; after it returns, everything the
        underlying ``StreamReader`` *already* holds is drained too —
        ``read()`` returns immediately (without suspending, so no new
        data can race in) while its buffer is non-empty, and each 64 KB
        read only takes part of a large burst.  Without the drain loop,
        ``pending()`` reports the burst exhausted at every 64 KB
        boundary and the reply batchers flush once per chunk instead of
        once per burst (ADVICE r5).  ``_buffer`` is asyncio private API:
        when absent, the loop degrades to the old one-read-per-fill
        behavior (64 KB batching granularity), never to an error.

        Each chunk lands in the deque uncopied; see the module
        docstring for the zero-copy carving contract.
        """
        try:
            chunk = await self._reader.read(_READ_SIZE)
        except (ConnectionError, OSError):
            return False
        if not chunk:
            return False
        self._chunks.append(chunk)
        self._size += len(chunk)
        buffered = getattr(self._reader, "_buffer", None)
        while buffered:
            try:
                chunk = await self._reader.read(_READ_SIZE)
            except (ConnectionError, OSError):
                break  # what was ingested so far still carves
            if not chunk:
                break
            self._chunks.append(chunk)
            self._size += len(chunk)
        return True

    async def _need(self, n: int) -> bool:
        while self._size < n:
            if not await self.fill():
                return False
        return True

    def _peek4(self) -> int:
        """The next 4 bytes as a signed big-endian int, not consumed.
        Caller guarantees at least 4 bytes are buffered."""
        first = self._chunks[0]
        pos = self._pos
        if len(first) - pos >= 4:
            return int.from_bytes(first[pos : pos + 4], "big", signed=True)
        out = bytearray(first[pos:])
        for chunk in list(self._chunks)[1:]:
            out += chunk[: 4 - len(out)]
            if len(out) == 4:
                break
        return int.from_bytes(out, "big", signed=True)

    def _skip(self, n: int) -> None:
        """Consume ``n`` buffered bytes without materializing them."""
        self._size -= n
        chunks = self._chunks
        while n:
            first = chunks[0]
            avail = len(first) - self._pos
            if n < avail:
                self._pos += n
                return
            n -= avail
            chunks.popleft()
            self._pos = 0

    def _take(self, n: int):
        """Consume ``n`` buffered bytes: a zero-copy view (or the whole
        chunk itself) when they lie within one chunk, joined ``bytes``
        when they span chunks.  Caller guarantees ``n <= _size``."""
        if n == 0:
            return b""
        self._size -= n
        chunks = self._chunks
        first = chunks[0]
        pos = self._pos
        end = pos + n
        flen = len(first)
        if end < flen:
            self._pos = end
            return memoryview(first)[pos:end]
        if end == flen:
            chunks.popleft()
            self._pos = 0
            return first if pos == 0 else memoryview(first)[pos:]
        parts = [memoryview(first)[pos:]]
        need = n - (flen - pos)
        chunks.popleft()
        self._pos = 0
        while need:
            chunk = chunks[0]
            clen = len(chunk)
            if clen <= need:
                parts.append(chunk)
                need -= clen
                chunks.popleft()
            else:
                parts.append(memoryview(chunk)[:need])
                self._pos = need
                need = 0
        return b"".join(parts)

    def carve(self) -> List[bytes]:
        """Every complete frame payload currently buffered, in order —
        zero-copy views for within-chunk frames (see module docstring).

        Raises ConnectionError on a corrupt length prefix — the stream
        has lost framing and cannot be resynchronized.
        """
        out: List[bytes] = []
        while self._size >= 4:
            length = self._peek4()
            if length < 0 or length > MAX_FRAME:
                malformed.note("zk_framing")
                raise ConnectionError(f"bad frame length {length}")
            if self._size - 4 < length:
                break
            self._skip(4)
            out.append(self._take(length))
        return out

    def pending(self) -> bool:
        """True when a complete frame is already buffered (reply batchers
        hold their flush until the input burst is exhausted)."""
        if self._size < 4:
            return False
        length = self._peek4()
        return 0 <= length <= self._size - 4

    def frame_nowait(self):
        """A complete buffered frame RIGHT NOW, or None.

        The server request loop's fast lane (ISSUE 11): a pipelined
        sweep leaves hundreds of complete frames buffered after one
        fill, and awaiting :meth:`frame` per request costs a coroutine
        per frame just to discover the bytes are already here.  Returns
        None when no complete frame is buffered — including a corrupt
        length, which is deferred to the awaited :meth:`frame` path so
        the error contract stays in one place.
        """
        if self._size < 4:
            return None
        length = self._peek4()
        if length < 0 or length > MAX_FRAME or self._size - 4 < length:
            return None
        self._skip(4)
        return self._take(length)

    async def read4(self) -> Optional[bytes]:
        """The stream's next 4 bytes (a frame length — or a 4lw command).
        Always real ``bytes`` (callers test set membership)."""
        if not await self._need(4):
            return None
        out = self._take(4)
        return out if type(out) is bytes else bytes(out)

    async def frame(self, header: Optional[bytes] = None):
        """The next complete frame payload; None on EOF or bad length.

        ``header`` supplies a 4-byte length already consumed via
        :meth:`read4` (the server handshake peeks it to disambiguate
        4lw admin commands from the ConnectRequest frame).
        """
        if header is not None:
            length = int.from_bytes(header, "big", signed=True)
        else:
            if not await self._need(4):
                return None
            length = self._peek4()
            self._skip(4)
        if length < 0 or length > MAX_FRAME:
            malformed.note("zk_framing")
            return None
        if not await self._need(length):
            return None
        return self._take(length)
