"""Length-prefixed frame buffering shared by the client and the server.

ZooKeeper's wire protocol frames every packet with a 4-byte big-endian
length (reference counterpart: the zkplus stack's socket framing; the
Apache client's ClientCnxnSocket does the same).  Both ends of this
rebuild read in bulk — one transport ``read()`` per TCP burst — and
carve complete frames out of a local buffer, instead of issuing two
awaited ``readexactly()`` calls per frame.  Pipelined storms (mkdirp,
heartbeat sweeps, registration fan-outs) land hundreds of frames per
segment, where the per-frame await overhead was a measurable slice of
the hot loops (docs/PERF.md).

Consumption is position-tracked, not sliced: a ``del buf[:n]`` per
frame would memmove the whole remaining burst for every request
(quadratic on large bursts); the consumed prefix is dropped once per
transport read instead.
"""

from __future__ import annotations

from typing import List, Optional

MAX_FRAME = 4 * 1024 * 1024  # matches real ZK's default jute.maxbuffer
_READ_SIZE = 65536


class FrameReader:
    """Buffered frame carving over an ``asyncio.StreamReader``."""

    __slots__ = ("_reader", "_buf", "_pos")

    def __init__(self, reader) -> None:
        self._reader = reader
        self._buf = bytearray()
        self._pos = 0  # consumed prefix; compacted at the next fill

    async def fill(self) -> bool:
        """Ingest the transport's whole buffered burst; False on EOF/error.

        The first ``read()`` may block; after it returns, everything the
        underlying ``StreamReader`` *already* holds is drained too —
        ``read()`` returns immediately (without suspending, so no new
        data can race in) while its buffer is non-empty, and each 64 KB
        read only takes part of a large burst.  Without the drain loop,
        ``pending()`` reports the burst exhausted at every 64 KB
        boundary and the reply batchers flush once per chunk instead of
        once per burst (ADVICE r5).  ``_buffer`` is asyncio private API:
        when absent, the loop degrades to the old one-read-per-fill
        behavior (64 KB batching granularity), never to an error.
        """
        if self._pos:
            del self._buf[: self._pos]
            self._pos = 0
        try:
            chunk = await self._reader.read(_READ_SIZE)
        except (ConnectionError, OSError):
            return False
        if not chunk:
            return False
        self._buf += chunk
        # StreamReader.read() consumes from this same bytearray in
        # place, so the live reference observes the drain's progress.
        buffered = getattr(self._reader, "_buffer", None)
        while buffered:
            try:
                chunk = await self._reader.read(_READ_SIZE)
            except (ConnectionError, OSError):
                break  # what was ingested so far still carves
            if not chunk:
                break
            self._buf += chunk
        return True

    def _available(self) -> int:
        return len(self._buf) - self._pos

    async def _need(self, n: int) -> bool:
        while self._available() < n:
            if not await self.fill():
                return False
        return True

    def _take(self, n: int) -> bytes:
        out = bytes(self._buf[self._pos : self._pos + n])
        self._pos += n
        return out

    def carve(self) -> List[bytes]:
        """Every complete frame payload currently buffered, in order.

        Raises ConnectionError on a corrupt length prefix — the stream
        has lost framing and cannot be resynchronized.
        """
        buf = self._buf
        pos = self._pos
        end = len(buf)
        out: List[bytes] = []
        while end - pos >= 4:
            length = int.from_bytes(buf[pos : pos + 4], "big", signed=True)
            if length < 0 or length > MAX_FRAME:
                self._pos = pos
                raise ConnectionError(f"bad frame length {length}")
            if end - pos - 4 < length:
                break
            out.append(bytes(buf[pos + 4 : pos + 4 + length]))
            pos += 4 + length
        self._pos = pos
        return out

    def pending(self) -> bool:
        """True when a complete frame is already buffered (reply batchers
        hold their flush until the input burst is exhausted)."""
        if self._available() < 4:
            return False
        p = self._pos
        length = int.from_bytes(self._buf[p : p + 4], "big", signed=True)
        return 0 <= length <= self._available() - 4

    async def read4(self) -> Optional[bytes]:
        """The stream's next 4 bytes (a frame length — or a 4lw command)."""
        if not await self._need(4):
            return None
        return self._take(4)

    async def frame(self, header: Optional[bytes] = None) -> Optional[bytes]:
        """The next complete frame payload; None on EOF or bad length.

        ``header`` supplies a 4-byte length already consumed via
        :meth:`read4` (the server handshake peeks it to disambiguate
        4lw admin commands from the ConnectRequest frame).
        """
        if header is not None:
            length = int.from_bytes(header, "big", signed=True)
        else:
            if not await self._need(4):
                return None
            length = int.from_bytes(self._take(4), "big", signed=True)
        if length < 0 or length > MAX_FRAME:
            return None
        if not await self._need(length):
            return None
        return self._take(length)
