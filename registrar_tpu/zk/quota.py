"""ZooKeeper quota layout and wire format (shared by server and CLI).

Real ZooKeeper 3.4 stores soft quotas as znodes:
``/zookeeper/quota/<target>/zookeeper_limits`` holds ``count=N,bytes=B``
(-1 = unlimited) and the server maintains live usage next to it in
``.../zookeeper_stats``.  Violations are logged, never enforced.  One
definition here keeps the test server and zkcli's
setquota/listquota/delquota agreeing on the format.
"""

from __future__ import annotations

from typing import Dict

#: root of ZooKeeper's bookkeeping subtree (pre-created like real ZK's
#: DataTree does)
QUOTA_ROOT = "/zookeeper/quota"
LIMITS_LEAF = "zookeeper_limits"
STATS_LEAF = "zookeeper_stats"


def parse_quota(data: bytes) -> Dict[str, int]:
    """Parse ``count=N,bytes=B`` (missing/garbled fields read as -1 =
    unlimited, matching StatsTrack's leniency)."""
    out = {"count": -1, "bytes": -1}
    for part in data.decode("utf-8", "replace").split(","):
        key, _, val = part.partition("=")
        key = key.strip()
        if key in out:
            try:
                out[key] = int(val)
            except ValueError:
                pass
    return out


def format_quota(count: int, nbytes: int) -> bytes:
    return f"count={count},bytes={nbytes}".encode()
