"""Asyncio ZooKeeper client — the rebuild's L1 transport.

Replaces the reference's zkplus dependency (reference lib/zk.js,
package.json:21) with a from-scratch client speaking the public ZooKeeper
3.4 wire protocol.  The surface mirrors what the upper layers of the
reference actually use (SURVEY.md §1 L1): ``put``, ``create`` (with
ephemeral-plus semantics), ``unlink``, ``mkdirp``, ``stat``, ``get``,
``get_children``, ``close``, events ``connect`` / ``close`` /
``session_expired``, plus the application-level ``heartbeat`` that the
reference monkey-patches onto the client (lib/zk.js:47-59).

Connection/session model:

  * :func:`create_zk_client` retries the initial connect forever with
    exponential backoff 1 s -> 90 s, logging each attempt and emitting
    ``attempt`` events (reference lib/zk.js:88-119).  Cancel the task to
    abort (the analog of the reference's ``retry.stop()``).
  * After a drop, the client reconnects with the same (session_id, passwd),
    re-arming watches via SetWatches.  If the server no longer knows the
    session it emits ``session_expired`` — the daemon's policy is to exit
    and let the supervisor restart it (reference main.js:141-144).
  * Session lifecycle supervisor (ISSUE 3, opt-in
    ``survive_session_expiry``): instead of the terminal
    ``session_expired``, an expiry resets the client to a fresh-session
    handshake (session_id 0, blank passwd, zxid 0) and the normal
    jittered reconnect machinery establishes a *new* session in-process,
    announced via ``session_reborn``.  The old session's ephemerals are
    gone — re-running the registration pipeline is the orchestrator's
    job (agent.py consumes the event).  A ``max_session_rebirths``-per-
    :data:`REBIRTH_WINDOW_S` circuit breaker guards against expiry
    storms (a flapping ensemble, a mis-sized session timeout): when it
    trips, the client falls back to the reference-exact terminal
    ``session_expired`` so the supervisor restart path still exists.
    Default off: expiry is terminal, byte-identical to the reference.
  * Network-fault armor (ISSUE 2): optional per-operation deadlines
    (``request_timeout_ms`` -> :class:`OperationTimeoutError`, connection
    torn down because a FIFO pipeline cannot skip a reply), a bounded
    whole-pass connect budget (``connect_pass_timeout_ms``), a liveness
    watchdog whose keepalive drain is itself deadline-bounded (a peer
    that stops *reading* must not wedge the watchdog), and jittered
    reconnect backoff by default (retry.RECONNECT_RETRY).  All proven
    against deterministic wire faults in
    :mod:`registrar_tpu.testing.netem` (tests/test_netem.py).
  * Ensemble awareness (ISSUE 10): ``can_be_read_only`` opts into
    attaching to a read-only member (minority partition / quorum loss)
    so reads and heartbeats keep serving while writes fail with the
    retryable ``NOT_READONLY``; a background ``isro`` probe fails the
    session over the moment a read-write member reappears.  Unexpected
    disconnects open a ``zk.failover`` span (old member -> new member,
    including any leader-election wait), and the connect-order shuffle
    is seedable (``rng=``) for deterministic failover tests.
  * ``ephemeral_plus`` creates (zkplus's flag, used at
    reference lib/register.js:157) are ephemeral creates that transparently
    mkdirp a missing parent.  Intentional divergence, documented: this
    client does NOT silently re-create ephemerals on session re-establishment
    — re-registration is the orchestrator's job (lib/index.js re-registers,
    and main.js exits on expiry), so hiding it in the transport would mask
    real failures.
"""

from __future__ import annotations

import asyncio
import logging
import random
import re
import time
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional, Sequence, Tuple

from registrar_tpu import trace
from registrar_tpu.events import EventEmitter
from registrar_tpu.retry import (
    CONNECT_RETRY,
    HEARTBEAT_RETRY,
    RECONNECT_RETRY,
    RetryPolicy,
    call_with_backoff,
)
from registrar_tpu.zk import protocol as proto
from registrar_tpu import malformed
from registrar_tpu.zk.framing import MAX_FRAME, FrameReader
from registrar_tpu.zk.jute import Reader, Writer
from registrar_tpu.zk.protocol import (
    CreateFlag,
    Err,
    OpCode,
    OPEN_ACL_UNSAFE,
    Stat,
    ZKError,
    check_path,
)

log = logging.getLogger("registrar_tpu.zk.client")

#: Sliding window (seconds) for the session-rebirth circuit breaker: more
#: than ``max_session_rebirths`` fresh sessions within it means expiry is
#: systemic (flapping ensemble, mis-sized session timeout) and in-process
#: recovery is just churning DNS — fall back to the reference's terminal
#: ``session_expired`` so the supervisor restart path takes over.
REBIRTH_WINDOW_S = 300.0

#: default ``max_session_rebirths`` (per :data:`REBIRTH_WINDOW_S`)
DEFAULT_MAX_SESSION_REBIRTHS = 5

#: op code -> span label for the ``zk.op`` spans (ISSUE 8); an op not
#: listed is traced under its numeric code, so a new op can never
#: silently vanish from the histograms
_OP_NAMES = {
    OpCode.CREATE: "create",
    OpCode.DELETE: "delete",
    OpCode.EXISTS: "exists",
    OpCode.GET_DATA: "getData",
    OpCode.SET_DATA: "setData",
    OpCode.GET_ACL: "getAcl",
    OpCode.SET_ACL: "setAcl",
    OpCode.GET_CHILDREN: "getChildren",
    OpCode.GET_CHILDREN2: "getChildren2",
    OpCode.SYNC: "sync",
    OpCode.CHECK: "check",
    OpCode.MULTI: "multi",
    OpCode.CLOSE_SESSION: "closeSession",
}


async def four_letter_word(
    host: str, port: int, word: bytes, timeout: float = 0.5
) -> bytes:
    """One connection-less admin "four letter word" probe (``isro``,
    ``srvr``, ``mntr``, ...): connect, write the 4 ASCII bytes, read the
    text answer, close.  The ONE copy of the probe dance — the client's
    rw-hunt and zkcli's role reporting both ride it.  Raises
    OSError/asyncio.TimeoutError on an unreachable or silent member.
    """
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout
    )
    try:
        writer.write(word)
        await writer.drain()
        return await asyncio.wait_for(reader.read(1 << 16), timeout)
    finally:
        writer.close()


def _parse_attach_preference(pref: str) -> Optional[Tuple[int, int]]:
    """Validate an ``attach_preference`` and extract the spread slot.

    Returns ``(k, n)`` for ``"spread:<k>-of-<n>"`` (k in [0, n)), None
    for ``"any"`` / ``"follower"``; raises ValueError on anything else —
    a typo'd hint must fail at construction, not silently mean "any".
    """
    if pref in ("any", "follower"):
        return None
    m = re.fullmatch(r"spread:(\d+)-of-(\d+)", pref or "")
    if m is None:
        raise ValueError(
            f"attach_preference must be 'any', 'follower', or "
            f"'spread:<k>-of-<n>' (got {pref!r})"
        )
    k, n = int(m.group(1)), int(m.group(2))
    if n < 1 or not 0 <= k < n:
        raise ValueError(
            f"attach_preference spread slot out of range: {pref!r}"
        )
    return (k, n)


_ROLE_RANK = {"follower": 0, "standalone": 1, "leader": 3}


async def _probe_roles(
    order: "List[Tuple[str, int]]", timeout: float
) -> "List[Tuple[str, int]]":
    """Stable-partition a candidate order by replication role, probed
    off each member's ``srvr`` 4lw concurrently: followers first, the
    leader last, unknown/unreachable members in place (rank 2 — ahead
    of the leader: an unanswered probe usually means a member mid-
    restart, still a better watch host than the leader).  Never raises:
    the hint must not make an unreachable ensemble less reachable."""

    async def role_rank(host: str, port: int) -> int:
        try:
            raw = await four_letter_word(host, port, b"srvr", timeout)
        except (OSError, ValueError, asyncio.TimeoutError):
            return 2
        for line in raw.decode("latin-1", "replace").splitlines():
            if line.startswith("Mode: "):
                return _ROLE_RANK.get(line[len("Mode: "):].strip(), 2)
        return 2

    ranks = await asyncio.gather(
        *(role_rank(h, p) for h, p in order)
    )
    return [
        server
        for _rank, _i, server in sorted(
            (rank, i, server)
            for i, (rank, server) in enumerate(zip(ranks, order))
        )
    ]


class ZKClient(EventEmitter):
    """One logical ZooKeeper session over a sequence of TCP connections.

    Events: ``connect`` (session (re)established), ``close`` (transport
    lost or client closed), ``session_expired`` (server disowned our
    session), ``state`` (every transition, with the state string).
    """

    def __init__(
        self,
        servers: Sequence[Tuple[str, int]],
        timeout_ms: int = 30000,
        connect_timeout_ms: int = 4000,
        reconnect: bool = True,
        reconnect_policy: Optional[RetryPolicy] = None,
        chroot: Optional[str] = None,
        request_timeout_ms: Optional[int] = None,
        connect_pass_timeout_ms: Optional[int] = None,
        survive_session_expiry: bool = False,
        max_session_rebirths: Optional[int] = None,
        can_be_read_only: bool = False,
        rng: Optional[random.Random] = None,
        attach_preference: str = "any",
        connect_race_stagger_ms: Optional[int] = None,
        ping_interval_ms: Optional[int] = None,
        dead_after_ms: Optional[int] = None,
    ):
        """``request_timeout_ms``: per-operation deadline.  When set, every
        awaited reply is bounded; on expiry the connection is torn down
        (ZooKeeper answers FIFO — one reply cannot be skipped without
        desynchronizing every later one, so the only safe recovery is a
        fresh connection) and the op raises
        :class:`OperationTimeoutError`, which
        :func:`registrar_tpu.retry.is_transient` classifies as retryable.
        Default None = wait forever (reference behavior), leaving stall
        detection to the session watchdog alone.

        ``connect_pass_timeout_ms``: bound on ONE whole pass of
        :meth:`connect` over the server list.  Without it, each candidate
        gets ``connect_timeout_ms`` and a long list of blackholed servers
        can stall a reconnect far past the session timeout; the default
        bound is the session timeout itself (``timeout_ms``).

        ``survive_session_expiry``: opt into the in-process session
        lifecycle supervisor (module docstring) — expiry resets to a
        fresh-session handshake and the reconnect machinery builds a new
        session, announced via ``session_reborn``, instead of the
        terminal ``session_expired``.  ``max_session_rebirths`` bounds
        rebirths per :data:`REBIRTH_WINDOW_S` (default
        :data:`DEFAULT_MAX_SESSION_REBIRTHS`); past it the breaker trips
        (``rebirth_breaker_tripped`` event) and expiry is terminal
        again.

        ``can_be_read_only`` (ISSUE 10; the Apache client's
        ``canBeReadOnly``, config ``zookeeper.canBeReadOnly``): opt into
        attaching to a read-only ensemble member (one partitioned to a
        minority, or riding out quorum loss) when no read-write member
        answers.  Reads and the heartbeat's EXISTS sweep keep working
        there; writes fail with the retryable ``NOT_READONLY`` (surfaced
        as the ``write_refused`` event) while a background probe polls
        the other members' ``isro`` 4lw and fails the session over the
        moment a read-write member appears (``rw_probe_interval_s``).
        Default False: the reference-exact wire bytes, and read-only
        members refuse us at the handshake.

        ``rng`` seeds the connect-order shuffle (and nothing else), so
        ensemble failover tests and chaos storms are deterministic per
        CHAOS_SEED; default is the module RNG (reference behavior).

        ``attach_preference`` (ISSUE 12): a connect-ORDER hint so a
        fleet of read-heavy clients (the sharded serve tier's workers)
        spreads its watch load across ensemble members instead of
        piling onto whichever member the shuffle favors:

          * ``"any"`` — the default: seeded shuffle, reference-exact
            behavior;
          * ``"follower"`` — shuffle first (``rng`` still honored),
            then probe each candidate's ``srvr`` 4lw concurrently and
            stable-partition the order so followers come first and the
            leader last (watch fan-out belongs on followers; the leader
            has writes to order).  Probe failures leave a candidate in
            place — the hint never makes an unreachable ensemble less
            reachable;
          * ``"spread:<k>-of-<n>"`` — worker k of n starts its pass at
            a deterministic rotation of the CONFIGURED server order
            (``rng`` is deliberately ignored: distinct workers must
            land on distinct members, which a per-process shuffle would
            undo).  Later candidates still serve as failover targets.

        It is a *hint*: reachability always wins over preference.

        ``connect_race_stagger_ms`` (ISSUE 20; RFC 8305's staggered
        "happy eyeballs" applied to the ensemble): when set, a connect
        pass races candidates — attempt k starts ``stagger`` ms after
        attempt k-1 (or immediately once an earlier attempt fails), and
        the FIRST successful read-write handshake wins while the losers
        are aborted cleanly (a loser that minted its own fresh session
        sends CLOSE_SESSION before hanging up, so raced fresh connects
        never orphan sessions).  A dead-or-blackholed first candidate
        therefore costs ~one stagger, not a full ``connect_timeout_ms``.
        Default None: the serial reference-exact pass.

        ``ping_interval_ms`` / ``dead_after_ms`` (ISSUE 20): override
        the keepalive/watchdog schedule.  The defaults are the Apache
        client's thirds rule — ping every negotiated/3, declare the
        server dead after 2/3 of the negotiated timeout with no frame —
        which ties blackhole detection to the session timeout.  Setting
        these detects a silent server in a fraction of that (the
        connection drops early and the reconnect machinery races to a
        healthy member while the session is still very much alive).
        ``dead_after_ms`` is floored at the effective ping interval.
        Default None/None: the reference-exact schedule."""
        super().__init__()
        servers = list(servers)
        if not servers:
            raise ValueError("servers must be non-empty")
        for host, port in servers:
            if not isinstance(host, str) or not isinstance(port, int):
                raise ValueError("servers must be (host, port) pairs")
        self.servers = servers
        # Validated-path cache, scoped to this client so its hot entries
        # (the instance's own znode paths, re-validated every heartbeat
        # sweep) can never be evicted by other clients' traffic — or by
        # the test server, which validates untrusted peer paths uncached
        # (see protocol.PathCache).
        self._path_cache = proto.PathCache()
        # Chroot: every path this client sends is prefixed with it and
        # every path the server returns (created paths, watch events) has
        # it stripped — the standard "host:port/app" suffix semantics of
        # the Apache client.  The chroot node itself must already exist
        # (like real clients, nothing is auto-created).
        if chroot in (None, "", "/"):
            self.chroot = ""
        else:
            check_path(chroot)
            self.chroot = chroot
        self.requested_timeout_ms = timeout_ms
        self.connect_timeout_ms = connect_timeout_ms
        self.request_timeout_ms = request_timeout_ms
        self.connect_pass_timeout_ms = connect_pass_timeout_ms
        self.reconnect = reconnect
        # Default reconnects use decorrelated jitter (RECONNECT_RETRY): a
        # fleet dropped by an ensemble restart must not retry in lockstep.
        self.reconnect_policy = reconnect_policy or RECONNECT_RETRY
        self.survive_session_expiry = survive_session_expiry
        self.can_be_read_only = can_be_read_only
        #: seeds the connect-order shuffle only (None = module RNG)
        self._rng = rng if rng is not None else random
        #: connect-order hint ("any" | "follower" | "spread:<k>-of-<n>")
        self.attach_preference = attach_preference
        self._attach_spread = _parse_attach_preference(attach_preference)
        if connect_race_stagger_ms is not None and connect_race_stagger_ms < 0:
            raise ValueError("connect_race_stagger_ms must be >= 0")
        for name, value in (
            ("ping_interval_ms", ping_interval_ms),
            ("dead_after_ms", dead_after_ms),
        ):
            if value is not None and value <= 0:
                raise ValueError(f"{name} must be > 0")
        #: None = serial reference pass; >=0 = raced connects (stagger)
        self.connect_race_stagger_ms = connect_race_stagger_ms
        #: None/None = the reference thirds-rule keepalive schedule
        self.ping_interval_ms = ping_interval_ms
        self.dead_after_ms = dead_after_ms
        #: raced-connect outcome (satellite: zkcli status / GET /status):
        #: wins counts passes the raced path decided; last_* describe the
        #: most recent pass (winning member, candidates dialed, losers
        #: aborted).  All zero/None under the serial reference path.
        self.race_stats = {
            "wins": 0,
            "last_winner": None,
            "last_candidates": 0,
            "last_aborted": 0,
        }
        #: seconds the last unexpected teardown -> reconnect took; None
        #: until the first failover completes
        self.last_failover_s: Optional[float] = None
        self._failover_started_at: Optional[float] = None
        #: connections dropped by the liveness watchdog / stalled-drain
        #: detector (the failure detector's suspicion count)
        self.watchdog_drops = 0
        #: True while the session is attached to a read-only member
        #: (ConnectResponse read_only flag); reads serve, writes refuse
        self.read_only = False
        #: cadence of the isro sweep hunting a read-write member while
        #: attached read-only (the Apache client's pingRwTimeout start)
        self.rw_probe_interval_s = 1.0
        #: rw member found by the probe — tried first on the next connect
        self._prefer_rw: Optional[Tuple[str, int]] = None
        self._rw_probe_task: Optional[asyncio.Task] = None
        #: open ``zk.failover`` span while the session is between
        #: members (unexpected teardown -> next successful connect)
        self._failover_span = None
        if max_session_rebirths is not None and max_session_rebirths < 1:
            raise ValueError("max_session_rebirths must be >= 1")
        self.max_session_rebirths = (
            max_session_rebirths
            if max_session_rebirths is not None
            else DEFAULT_MAX_SESSION_REBIRTHS
        )
        #: total fresh sessions established in-process after an expiry
        self.rebirths = 0
        #: monotonic stamps of recent rebirths (circuit-breaker window)
        self._rebirth_times: Deque[float] = deque()
        #: an expiry was absorbed; the next successful connect is a rebirth
        self._rebirth_pending = False
        #: a cross-process handoff resume is staged (seed_session); a
        #: refused reattach then degrades to a fresh-session handshake
        #: instead of the terminal session_expired
        self._resume_pending = False

        self.session_id = 0
        self.session_passwd = b"\x00" * 16
        self.negotiated_timeout_ms = timeout_ms
        self.last_zxid = 0
        #: (host, port) the session is currently attached through (the
        #: server list is shuffled on connect, so callers reporting "where
        #: am I connected" must read this, not servers[0])
        self.connected_server: Optional[Tuple[str, int]] = None

        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._xid = 0
        self._pending: Deque[Tuple[int, asyncio.Future]] = deque()
        self._corked: Optional[List[bytes]] = None
        self._read_task: Optional[asyncio.Task] = None
        self._ping_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._closed = False
        self._connected = False
        self._last_response = 0.0  # monotonic time of last server frame
        # one-shot watches to re-arm after reconnect: kind -> set of paths
        self._watch_paths = {"data": set(), "exist": set(), "child": set()}
        self._watch_emitter = EventEmitter()
        # credentials added via add_auth, replayed on every (re)connect the
        # way the Apache client replays its authInfo list
        self._auths: List[Tuple[str, bytes]] = []
        #: per-instance tracer override (ISSUE 8); None = the process
        #: default via trace.tracer_for — a disabled default makes every
        #: tracing branch below a no-op
        self.tracer = None
        #: in-flight ``zk.op`` spans by xid (only populated while a
        #: tracer is enabled; emptied by reply dispatch and teardown)
        self._op_spans: dict = {}
        #: xids posted since the last drain — their spans get the
        #: ``flushed`` mark (the queue/wire split) when the drain lands
        self._unflushed: List[int] = []

    # -- state --------------------------------------------------------------

    @property
    def connected(self) -> bool:
        return self._connected

    @property
    def closed(self) -> bool:
        return self._closed

    def __str__(self) -> str:
        hosts = ",".join(f"{h}:{p}" for h, p in self.servers)
        return f"ZKClient({hosts}, session=0x{self.session_id:x})"

    # -- connection management ----------------------------------------------

    def seed_session(
        self,
        session_id: int,
        passwd: bytes,
        negotiated_timeout_ms: Optional[int] = None,
        last_zxid: int = 0,
    ) -> None:
        """Stage a cross-process session resume (ISSUE 5 handoff).

        The next :meth:`connect` offers ``(session_id, passwd)`` to the
        server exactly as an in-process reconnect would, reattaching the
        predecessor's live session — its ephemerals never flickered.  If
        the server refuses (the session expired in the gap), the client
        resets to a fresh-session handshake and stays OPEN: the refusing
        attempt raises :class:`SessionExpiredError`, and the caller's
        retry loop establishes a brand-new session on the next attempt —
        never the terminal ``session_expired``.  Callers detect the
        outcome by comparing ``client.session_id`` to the seed after the
        connect lands (``resume_refused`` also fires on refusal).

        ``last_zxid`` seeds the ConnectRequest's ``last_zxid_seen``, so a
        server behind the predecessor's view refuses the reattach the
        same way it would refuse a too-new in-process reconnect.
        """
        if self._connected or self._closed:
            raise RuntimeError("seed_session requires a fresh, open client")
        if not isinstance(passwd, bytes) or len(passwd) != 16:
            raise ValueError("session passwd must be exactly 16 bytes")
        self.session_id = session_id
        self.session_passwd = passwd
        self.last_zxid = last_zxid
        if negotiated_timeout_ms is not None:
            # The predecessor's negotiated value sizes the watchdog and
            # ping cadence correctly from the first connection.
            self.negotiated_timeout_ms = negotiated_timeout_ms
        self._resume_pending = True

    async def detach(self) -> None:
        """Close the transport WITHOUT closing the session (handoff).

        The inverse of :meth:`close`: no CLOSE_SESSION is sent, so the
        server keeps the session — and every ephemeral it owns — alive
        for the rest of the negotiated timeout, long enough for a
        successor process to reattach it via :meth:`seed_session`.  The
        client object itself is finished (no reconnects, operations fail
        closed), exactly like close() from the caller's point of view.
        """
        if self._closed:
            return
        self._closed = True
        if self._reconnect_task:
            self._reconnect_task.cancel()
        self._abort_failover_span()
        await self._teardown(expected=True)

    async def _connect_order(self) -> List[Tuple[str, int]]:
        """Candidate order for one connect pass, per
        ``attach_preference`` (constructor docstring): seeded shuffle
        ("any"), shuffle + role-probed follower-first ("follower"), or
        a deterministic rotation of the configured order ("spread") so
        worker k of n starts at a distinct member."""
        order = list(self.servers)
        if self._attach_spread is not None:
            k, n = self._attach_spread
            # No shuffle: determinism IS the feature (two workers with
            # different slots must not converge by shuffle luck).
            start = (k * len(order)) // n % len(order)
            return order[start:] + order[:start]
        self._rng.shuffle(order)
        if self.attach_preference == "follower" and len(order) > 1:
            order = await _probe_roles(
                order, timeout=min(0.5, self.connect_timeout_ms / 1000.0)
            )
        return order

    async def connect(self) -> "ZKClient":
        """Connect (or reconnect) to the first reachable server.

        Single pass over the server list in random order; raises on total
        failure.  The WHOLE pass is bounded by ``connect_pass_timeout_ms``
        (default: the session timeout), not just each candidate by
        ``connect_timeout_ms`` — a long list of slow or blackholed servers
        must not stall one reconnect attempt past the point where the
        session it is trying to save has already expired.  Use
        :func:`create_zk_client` for the reference's infinite-backoff
        behavior.

        With ``can_be_read_only``, read-write members are preferred: a
        member that answers the handshake read-only is noted and the
        pass keeps looking; only when no read-write member answered is
        the read-only fallback reattached (degraded: reads serve, writes
        refuse until the rw-probe finds a majority member).
        """
        if self._closed:
            raise ZKError(Err.SESSION_EXPIRED, None)
        last_err: Optional[Exception] = None
        order = await self._connect_order()
        prefer, self._prefer_rw = self._prefer_rw, None
        if prefer is not None and prefer in order:
            # The rw-probe found a read-write member: leave read-only
            # mode for it deterministically, not by shuffle luck.
            order.remove(prefer)
            order.insert(0, prefer)
        pass_timeout_ms = (
            self.connect_pass_timeout_ms
            if self.connect_pass_timeout_ms is not None
            else self.requested_timeout_ms
        )
        deadline = time.monotonic() + pass_timeout_ms / 1000.0
        if self.connect_race_stagger_ms is not None:
            # ISSUE 20: staggered raced connects — opt-in; the serial
            # reference-exact pass below runs when the knob is absent.
            return await self._connect_raced(order, deadline)
        ro_fallback: Optional[Tuple[str, int]] = None
        for host, port in order:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                await self._connect_one(
                    host, port, max_wait=remaining, allow_read_only=False
                )
                return self
            except _ReadOnlyMember:
                # Keep hunting for a read-write member; come back to
                # this one only if the whole pass finds none.
                if ro_fallback is None:
                    ro_fallback = (host, port)
                log.debug("%s:%d is read-only; continuing the pass", host, port)
            except SessionExpiredError:
                raise
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001 - try next server
                last_err = err
                log.debug("connect to %s:%d failed: %r", host, port, err)
        if ro_fallback is not None:
            remaining = deadline - time.monotonic()
            try:
                await self._connect_one(
                    ro_fallback[0], ro_fallback[1],
                    max_wait=max(remaining, 0.05), allow_read_only=True,
                )
                return self
            except SessionExpiredError:
                raise
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001 - fall through to raise
                last_err = err
        raise (
            last_err
            if last_err
            else ConnectionError("no servers within the connect pass budget")
        )

    async def _connect_raced(
        self, order: List[Tuple[str, int]], deadline: float
    ) -> "ZKClient":
        """Happy-eyeballs connect pass (ISSUE 20, RFC 8305 shape).

        Candidates start ``connect_race_stagger_ms`` apart (a failure
        releases the next immediately); the first successful read-write
        handshake wins and every other attempt is aborted.  A loser that
        completed a handshake on a session OTHER than the winner's (a
        fresh client races fresh-session handshakes, each minting its
        own) best-effort sends CLOSE_SESSION before hanging up, so the
        race never strands orphan sessions on the ensemble.  A read-only
        handshake is HELD open as the fallback while the race keeps
        hunting read-write — adopted directly if nothing better lands
        (one dial cheaper than the serial pass's re-dial)."""
        stagger_s = self.connect_race_stagger_ms / 1000.0
        pending = list(order)
        tasks: Dict[asyncio.Task, Tuple[str, int]] = {}
        attempted = 0
        last_err: Optional[Exception] = None
        #: held read-only fallback: (host, port, reader, writer, resp)
        ro_held: Optional[tuple] = None
        #: completed-but-unadopted handshakes needing loser cleanup
        losers: List[tuple] = []
        adopted = False

        def spawn() -> None:
            nonlocal attempted
            host, port = pending.pop(0)
            remaining = deadline - time.monotonic()
            task = asyncio.create_task(
                self._dial_handshake(host, port, max_wait=remaining)
            )
            tasks[task] = (host, port)
            attempted += 1

        try:
            spawn()
            next_spawn = time.monotonic() + stagger_s
            while tasks:
                timeout = deadline - time.monotonic()
                if pending:
                    timeout = min(timeout, next_spawn - time.monotonic())
                done, _ = await asyncio.wait(
                    set(tasks),
                    timeout=max(timeout, 0.0),
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done:
                    if time.monotonic() >= deadline:
                        break
                    if pending:
                        spawn()
                        next_spawn = time.monotonic() + stagger_s
                    continue
                winner: Optional[tuple] = None
                for task in done:
                    host, port = tasks.pop(task)
                    if task.cancelled():
                        continue
                    try:
                        reader, writer, resp = task.result()
                    except SessionExpiredError:
                        raise
                    except Exception as err:  # noqa: BLE001 - next candidate
                        last_err = err
                        log.debug(
                            "raced connect to %s:%d failed: %r",
                            host, port, err,
                        )
                        # A fast failure frees the slot: the next
                        # candidate starts now, not at the stagger mark.
                        next_spawn = time.monotonic()
                        continue
                    if resp.read_only:
                        if ro_held is None:
                            # ADOPT the session the handshake minted (the
                            # serial pass does the same — see _connect_one's
                            # orphan rationale) and keep the live transport
                            # as the fallback while the race keeps hunting.
                            self.session_id = resp.session_id
                            self.session_passwd = resp.passwd
                            self.negotiated_timeout_ms = resp.timeout_ms
                            ro_held = (host, port, reader, writer, resp)
                        else:
                            losers.append((reader, writer, resp))
                        continue
                    if winner is None:
                        winner = (host, port, reader, writer, resp)
                    else:
                        losers.append((reader, writer, resp))
                if winner is not None:
                    host, port, reader, writer, resp = winner
                    if ro_held is not None:
                        losers.append(ro_held[2:])
                        ro_held = None
                    adopted = True
                    self.race_stats["wins"] += 1
                    self.race_stats["last_winner"] = f"{host}:{port}"
                    self.race_stats["last_candidates"] = attempted
                    await self._adopt_connection(host, port, reader, writer, resp)
                    return self
                while (
                    pending
                    and time.monotonic() >= next_spawn
                    and time.monotonic() < deadline
                ):
                    spawn()
                    next_spawn = time.monotonic() + stagger_s
            if ro_held is not None:
                # No read-write member answered: degrade onto the held
                # read-only handshake (reads serve; the rw-probe loop
                # fails over the moment quorum returns).
                host, port, reader, writer, resp = ro_held
                ro_held = None
                adopted = True
                self.race_stats["wins"] += 1
                self.race_stats["last_winner"] = f"{host}:{port}"
                self.race_stats["last_candidates"] = attempted
                await self._adopt_connection(host, port, reader, writer, resp)
                return self
            raise (
                last_err
                if last_err
                else ConnectionError("no servers within the connect pass budget")
            )
        finally:
            for task in tasks:
                task.cancel()
            if tasks:
                stragglers = await asyncio.gather(
                    *tasks, return_exceptions=True
                )
                losers.extend(
                    r for r in stragglers if isinstance(r, tuple)
                )
            if ro_held is not None and not adopted:
                losers.append(ro_held[2:])
            if losers:
                keep = self.session_id if adopted else 0
                self.race_stats["last_aborted"] = len(losers)
                await self._abort_losers(losers, keep_session=keep)
            elif adopted:
                self.race_stats["last_aborted"] = 0

    async def _abort_losers(
        self, losers: List[tuple], keep_session: int
    ) -> None:
        """Close out raced handshakes that lost.

        A loser attached to the SAME session as the winner (a reconnect
        race: every attempt offered our existing session) just drops its
        transport — CLOSE_SESSION there would kill the session the
        winner is using.  A loser on a DIFFERENT session (fresh-session
        races mint one per handshake) closes it first, so the ensemble
        never accumulates orphans that, under quorum loss, could not
        even expire."""
        for reader, writer, resp in losers:
            try:
                if resp.session_id != keep_session:
                    writer.write(
                        proto.encode_request(1, OpCode.CLOSE_SESSION)
                    )
                    await asyncio.wait_for(writer.drain(), timeout=0.25)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _connect_one(
        self,
        host: str,
        port: int,
        max_wait: Optional[float] = None,
        allow_read_only: bool = True,
    ) -> None:
        reader, writer, resp = await self._dial_handshake(
            host, port, max_wait=max_wait
        )
        if resp.read_only and not allow_read_only:
            # A read-only member while the pass is still hunting for a
            # read-write one: drop the TRANSPORT only (no CLOSE_SESSION
            # — the session stays alive server-side, exactly like a
            # reconnect) and let connect() note the fallback.  ADOPT the
            # session the handshake just established/attached first: a
            # fresh client that hunted past N read-only members would
            # otherwise mint a new session per refused handshake —
            # orphans that, under quorum loss (leader-only expiry),
            # could never be reaped.  The fallback (or the next pass)
            # reattaches this same session instead.
            self.session_id = resp.session_id
            self.session_passwd = resp.passwd
            self.negotiated_timeout_ms = resp.timeout_ms
            writer.close()
            raise _ReadOnlyMember()
        await self._adopt_connection(host, port, reader, writer, resp)

    async def _dial_handshake(
        self,
        host: str,
        port: int,
        max_wait: Optional[float] = None,
    ) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter,
               "proto.ConnectResponse"]:
        """Dial one candidate and run the ConnectRequest handshake.

        State-free with respect to the client's connection fields: the
        returned transport has NOT been installed (no read loop, no ping
        loop, session fields untouched) — :meth:`_adopt_connection`
        does that for whichever handshake the caller picks.  Shared
        byte-for-byte by the serial pass and the raced pass, so the two
        connect modes cannot drift apart on the wire."""
        per_step = self.connect_timeout_ms / 1000.0
        # The pass budget is CUMULATIVE across the dial/handshake steps: a
        # server that trickles — dial completes just in time, then the
        # header, then never the payload — must not get a fresh allowance
        # per step, or one candidate overshoots the whole-pass bound by
        # the number of steps (see connect()).
        deadline = None if max_wait is None else time.monotonic() + max_wait

        def step_timeout() -> float:
            if deadline is None:
                return per_step
            return min(per_step, max(deadline - time.monotonic(), 0.001))

        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), step_timeout()
        )
        try:
            req = proto.ConnectRequest(
                protocol_version=0,
                last_zxid_seen=self.last_zxid,
                timeout_ms=self.requested_timeout_ms,
                session_id=self.session_id,
                passwd=self.session_passwd,
                # The 3.4 wire flag: without it a read-only member
                # refuses the handshake outright (and with the default
                # can_be_read_only=False the bytes stay reference-exact).
                read_only=self.can_be_read_only,
            )
            w = Writer()
            req.write(w)
            writer.write(proto.frame(w.to_bytes()))
            await asyncio.wait_for(writer.drain(), step_timeout())
            hdr = await asyncio.wait_for(reader.readexactly(4), step_timeout())
            length = int.from_bytes(hdr, "big", signed=True)
            if length < 0 or length > MAX_FRAME:
                # A garbage length prefix here is pre-session: nothing
                # to resynchronize against, so drop the connection (the
                # reconnect loop owns the retry).
                malformed.note("zk_client")
                raise ConnectionError(f"bad handshake frame length {length}")
            payload = await asyncio.wait_for(
                reader.readexactly(length), step_timeout()
            )
            resp = proto.ConnectResponse.read(Reader(payload))
        except BaseException:
            # BaseException, not Exception: a raced attempt that loses
            # gets CancelledError mid-handshake and must still close its
            # half-open socket.
            writer.close()
            raise

        if resp.session_id == 0 or resp.timeout_ms <= 0:
            # Server refused to (re)attach the session: it has expired.
            writer.close()
            self._emit_expired()
            raise SessionExpiredError()
        return reader, writer, resp

    async def _adopt_connection(
        self,
        host: str,
        port: int,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        resp: "proto.ConnectResponse",
    ) -> None:
        """Install a successful handshake as THE connection: session
        fields, read/ping loops, auth replay, watch re-arm, events."""
        reattached = self.session_id == resp.session_id and self.session_id != 0
        # NOT consumed yet: the handshake tail below (auth replay, watch
        # re-arm) still awaits, and a drop there aborts this attempt —
        # the flag must survive so the NEXT attempt (which will reattach
        # the fresh session, session_id != 0 now) still announces the
        # rebirth.  Consuming early silently loses session_reborn and
        # the agent never re-registers (a live session with no
        # registration — the outage this feature exists to prevent).
        reborn = self._rebirth_pending
        self.session_id = resp.session_id
        self.session_passwd = resp.passwd
        self.negotiated_timeout_ms = resp.timeout_ms
        self.connected_server = (host, port)
        self.read_only = bool(resp.read_only)
        self._reader = reader
        self._writer = writer
        self._connected = True
        self._last_response = time.monotonic()
        self._read_task = asyncio.create_task(self._read_loop())
        self._ping_task = asyncio.create_task(self._ping_loop())
        await self._replay_auths()
        if reattached or reborn:
            # A reborn session re-arms its watch registrations too: the
            # listeners are still alive and SetWatches with zxid 0 makes
            # the server deliver (conservatively) any transition the
            # watched paths saw, so no watcher silently goes dead across
            # the session boundary.
            await self._rearm_watches()
        log.debug(
            "connected to %s:%d session=0x%x timeout=%dms%s",
            host, port, self.session_id, self.negotiated_timeout_ms,
            " (read-only)" if self.read_only else "",
        )
        if self._failover_started_at is not None:
            # The whole between-members window (teardown -> this
            # handshake), surfaced via GET /status for the "why was
            # recovery slow" runbook question.
            self.last_failover_s = time.monotonic() - self._failover_started_at
            self._failover_started_at = None
        if self._failover_span is not None:
            # Failover complete: the span's duration is the whole
            # between-members window (including any election wait).
            sp, self._failover_span = self._failover_span, None
            sp.set_attr("to", f"{host}:{port}")
            sp.set_attr("read_only", self.read_only)
            sp.finish()
        self.emit(
            "state", "connected_read_only" if self.read_only else "connected"
        )
        self.emit("connect")
        if self.read_only:
            # Degraded attach: serve reads here while a background isro
            # sweep hunts for a read-write member to fail writes over to
            # (the Apache client's "Majority server found" probe).
            # Started only now — after the handshake tail (auth replay,
            # watch re-arm) — and the loop sleeps before its first poll,
            # so the probe's teardown can never race the connect it
            # rides on.
            self._rw_probe_task = asyncio.create_task(self._rw_probe_loop())
        if self._resume_pending:
            # Consumed only on full success, like the rebirth marker
            # above: a drop in the handshake tail leaves the next
            # attempt still counting as the staged resume.
            self._resume_pending = False
            log.warning(
                "session 0x%x resumed across a process boundary "
                "(handoff state file)", self.session_id,
            )
            trace.tracer_for(self).event(
                "zk.session_resumed", session=f"0x{self.session_id:x}"
            )
            self.emit("session_resumed", self.session_id)
        if reborn:
            self._rebirth_pending = False  # consumed only on full success
            self.rebirths += 1
            log.warning(
                "session reborn: fresh session 0x%x established in-process "
                "(rebirth %d)", self.session_id, self.rebirths,
            )
            trace.tracer_for(self).event(
                "zk.session_reborn",
                session=f"0x{self.session_id:x}", rebirth=self.rebirths,
            )
            self.emit("session_reborn", self.session_id)

    async def _replay_auths(self) -> None:
        """Re-send stored credentials on a fresh connection.

        Auth state is per-connection server-side, so every (re)connect must
        replay it before any ACL-guarded operation runs (the Apache client
        does the same with its authInfo list in primeConnection).

        A credential the server rejects (AUTH_FAILED) is dropped from the
        stored list: the server hangs up after answering AUTH_FAILED, and
        replaying the same rejected credential on every reconnect would
        turn the reconnect loop into a permanent connect/reject cycle.
        Subsequent ACL-guarded operations then fail with NO_AUTH, which is
        visible to the caller (the Apache client instead parks the whole
        session in a terminal AUTH_FAILED state; keeping the session
        usable for the un-authed surface suits a daemon whose core
        registration traffic never uses ACLs)."""
        rejected = []
        for scheme, auth in self._auths:
            try:
                await self._submit(
                    proto.XID_AUTH,
                    OpCode.AUTH,
                    proto.AuthPacket(type=0, scheme=scheme, auth=auth),
                )
            except ZKError as err:
                log.warning("replaying %s auth failed: %s", scheme, err)
                if err.code == Err.AUTH_FAILED:
                    rejected.append((scheme, auth))
                    self.emit("auth_failed", scheme)
        for cred in rejected:
            self._auths.remove(cred)
            log.error(
                "dropped rejected %s credential; ACL-guarded ops will fail "
                "with NO_AUTH until add_auth() succeeds again", cred[0],
            )

    async def _rearm_watches(self) -> None:
        if not any(self._watch_paths.values()):
            return
        body = proto.SetWatches(
            relative_zxid=self.last_zxid,
            data_watches=sorted(map(self._abs, self._watch_paths["data"])),
            exist_watches=sorted(map(self._abs, self._watch_paths["exist"])),
            child_watches=sorted(map(self._abs, self._watch_paths["child"])),
        )
        try:
            await self._submit(
                proto.XID_SET_WATCHES, OpCode.SET_WATCHES, body
            )
        except ZKError as err:
            log.warning("re-arming watches failed: %s", err)
            # Watch-dependent consumers (the zkcache invalidation layer)
            # must know their coherence signal may now be broken on this
            # connection: a cache serving entries whose watches never got
            # re-armed would serve stale answers forever.
            self.emit("watch_rearm_failed", err)

    async def close(self) -> None:
        """Gracefully end the session (ephemerals are dropped server-side)."""
        if self._closed:
            return
        self._closed = True
        if self._reconnect_task:
            self._reconnect_task.cancel()
        self._abort_failover_span()
        if self._connected:
            try:
                await asyncio.wait_for(
                    self._submit(self._next_xid(), OpCode.CLOSE_SESSION, None),
                    timeout=2.0,
                )
            except Exception:  # noqa: BLE001 - best-effort close
                pass
        await self._teardown(expected=True)

    async def _teardown(self, expected: bool) -> None:
        was_connected = self._connected
        self._connected = False
        self.read_only = False
        for task in (self._read_task, self._ping_task, self._rw_probe_task):
            if task is not None and task is not asyncio.current_task():
                task.cancel()
        self._rw_probe_task = None
        if self._writer is not None:
            try:
                transport = getattr(self._writer, "transport", None)
                if not expected and transport is not None:
                    # Abort, don't close: close() flushes the send buffer
                    # first, and on a connection being torn down *because*
                    # the peer stopped reading that flush never completes —
                    # connection_lost never fires and every coroutine
                    # parked in drain() stays parked forever.  abort()
                    # discards the buffer and wakes them with a
                    # ConnectionResetError immediately.
                    transport.abort()
                else:
                    self._writer.close()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None
            self._reader = None
        err = ZKError(Err.CONNECTION_LOSS)
        while self._pending:
            _, fut = self._pending.popleft()
            if not fut.done():
                fut.set_exception(err)
        if self._op_spans:
            # Replies that will never come: close their spans with the
            # same verdict their futures just got.
            for sp in self._op_spans.values():
                sp.finish("error", err=Err.CONNECTION_LOSS)
            self._op_spans.clear()
        self._unflushed.clear()
        if was_connected:
            self.emit("state", "disconnected")
            self.emit("close")
        if not expected and not self._closed and self.reconnect:
            if was_connected and self._failover_started_at is None:
                # Failover clock: closed by the next successful
                # _adopt_connection (last_failover_s).
                self._failover_started_at = time.monotonic()
            tr = trace.tracer_for(self)
            if tr.enabled and was_connected and self._failover_span is None:
                # The session is now between members: one zk.failover
                # span covers the whole gap — teardown, reconnect
                # attempts, any leader-election wait — and closes on the
                # next successful handshake (old member -> new member).
                old = self.connected_server
                self._failover_span = tr.start_span(
                    "zk.failover",
                    **{"from": f"{old[0]}:{old[1]}" if old else "?"},
                )
            if self._reconnect_task is None or self._reconnect_task.done():
                self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    def _abort_failover_span(self) -> None:
        """Close an open ``zk.failover`` span on a terminal path (client
        closed / session expired for good): the failover never landed."""
        if self._failover_span is not None:
            sp, self._failover_span = self._failover_span, None
            sp.finish("error")

    async def _rw_probe_loop(self) -> None:
        """While attached to a read-only member, poll the other members'
        ``isro`` admin word and fail over the moment one answers ``rw``
        (quorum returned, or the partition healed).  The teardown path
        is the ordinary unexpected-disconnect machinery, so the session
        reattaches through the preferred read-write member with watches
        re-armed — writes resume without operator action.
        """
        try:
            while self._connected and self.read_only and not self._closed:
                await asyncio.sleep(self.rw_probe_interval_s)
                if not (self._connected and self.read_only):
                    return
                found = await self._find_rw_server()
                if found is not None:
                    log.warning(
                        "read-write member %s:%d available; failing over "
                        "from read-only %s", found[0], found[1],
                        self.connected_server,
                    )
                    self._prefer_rw = found
                    await self._teardown(expected=False)
                    return
        except asyncio.CancelledError:
            raise

    async def _find_rw_server(self) -> Optional[Tuple[str, int]]:
        """First server in the list (excluding the one we're on) whose
        ``isro`` probe answers ``rw``; None when none does."""
        for host, port in self.servers:
            if (host, port) == self.connected_server:
                continue
            try:
                answer = await four_letter_word(host, port, b"isro")
                if answer.startswith(b"rw"):
                    return (host, port)
            except asyncio.CancelledError:
                raise
            except (OSError, asyncio.TimeoutError):
                continue
        return None

    async def _reconnect_loop(self) -> None:
        try:
            await call_with_backoff(
                self.connect,
                self.reconnect_policy,
                on_backoff=lambda n, delay, err: log.warning(
                    "reconnect attempt %d failed (%r); retrying in %.1fs",
                    n, err, delay,
                ),
                # A terminally expired/closed session cannot be resurrected
                # by retrying — but a SURVIVED expiry leaves the client
                # open (session reset to 0 by _emit_expired), and the next
                # attempt performs the fresh-session handshake, so only
                # _closed gates here.
                retryable=lambda err: not self._closed,
            )
        except SessionExpiredError:
            pass  # _emit_expired already fired
        except asyncio.CancelledError:
            # close() cancelled us; re-raise so the task finishes as
            # *cancelled* instead of silently completing (nothing awaits
            # it, but a swallowed cancel here would mask a stuck close).
            raise
        except Exception:  # noqa: BLE001
            log.exception("reconnect loop gave up")
            # The failover this span was timing never landed (a finite
            # reconnect policy exhausted); leaving it open would hold a
            # forever-pending span in the recorder.
            self._abort_failover_span()

    def _emit_expired(self) -> None:
        """The server disowned our session: rebirth or terminal expiry.

        With ``survive_session_expiry`` (and the circuit breaker not
        tripped) the client resets to a fresh-session handshake — the
        caller still raises :class:`SessionExpiredError` for the attempt
        in flight, but the client stays open and the reconnect loop's
        next attempt connects with session_id 0, establishing a new
        session (``session_reborn`` fires from _connect_one).  Otherwise:
        the reference-exact terminal path — closed + ``session_expired``.
        """
        if self._resume_pending and not self._closed:
            # A staged handoff resume the server refused: the session
            # died between the predecessor's detach and now.  Not a
            # rebirth (this client never held a session), not terminal —
            # reset to the fresh-session handshake and let the caller's
            # retry loop register from scratch, the documented fallback.
            self._resume_pending = False
            self.session_id = 0
            self.session_passwd = b"\x00" * 16
            self.last_zxid = 0
            log.warning(
                "handoff session resume refused by the server (session "
                "expired); falling back to a fresh session"
            )
            self.emit("state", "resume_refused")
            self.emit("resume_refused")
            return
        if self.survive_session_expiry and not self._closed:
            now = time.monotonic()
            while (
                self._rebirth_times
                and now - self._rebirth_times[0] > REBIRTH_WINDOW_S
            ):
                self._rebirth_times.popleft()
            if len(self._rebirth_times) < self.max_session_rebirths:
                self._rebirth_times.append(now)
                old = self.session_id
                self.session_id = 0
                self.session_passwd = b"\x00" * 16
                self.last_zxid = 0
                self._rebirth_pending = True
                log.warning(
                    "session 0x%x expired; rebuilding a fresh session "
                    "in-process (surviveSessionExpiry)", old,
                )
                trace.tracer_for(self).event(
                    "zk.session_lost", session=f"0x{old:x}"
                )
                self.emit("state", "session_lost")
                return
            log.error(
                "session rebirth circuit breaker tripped (%d rebirths in "
                "%.0fs); falling back to terminal session_expired",
                len(self._rebirth_times), REBIRTH_WINDOW_S,
            )
            self.emit("rebirth_breaker_tripped", len(self._rebirth_times))
        self._closed = True
        self._abort_failover_span()
        trace.tracer_for(self).event(
            "zk.session_expired", session=f"0x{self.session_id:x}"
        )
        self.emit("state", "session_expired")
        self.emit("session_expired")

    # -- path validation ------------------------------------------------------

    def _check_path(self, path: str) -> str:
        """Validate through this client's PathCache — the ONE place the
        cache is wired in, so a new op cannot silently fall back to
        uncached validation."""
        return check_path(path, self._path_cache)

    # -- chroot mapping -------------------------------------------------------

    def _abs(self, path: str) -> str:
        """Client path -> server path (prefix the chroot)."""
        if not self.chroot:
            return path
        return self.chroot if path == "/" else self.chroot + path

    def _rel(self, path: str) -> str:
        """Server path -> client path (strip the chroot)."""
        if not self.chroot:
            return path
        if path == self.chroot:
            return "/"
        if path.startswith(self.chroot + "/"):
            return path[len(self.chroot):]
        return path  # outside the chroot (shouldn't happen)

    # -- wire I/O -----------------------------------------------------------

    def _next_xid(self) -> int:
        self._xid += 1
        return self._xid

    async def _read_loop(self) -> None:
        # Bulk-buffered framing (registrar_tpu/zk/framing.py): one
        # transport read per TCP burst, then dispatch every complete
        # frame carved zero-copy out of the receive chunks.  Liveness is
        # stamped once per burst, not per frame — the watchdog's
        # granularity is seconds, and a 10k-reply sweep cost 10k clock
        # reads.
        frames = FrameReader(self._reader)
        try:
            while True:
                if not await frames.fill():
                    raise ConnectionError("connection closed by server")
                self._last_response = time.monotonic()
                for payload in frames.carve():
                    self._dispatch_frame(payload)
        except asyncio.CancelledError:
            raise
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as err:
            log.debug("connection lost: %r", err)
            await self._teardown(expected=False)
        except Exception:  # noqa: BLE001 - malformed frame: treat as conn loss
            log.exception("protocol error on connection; tearing down")
            await self._teardown(expected=False)

    def _dispatch_frame(self, payload) -> None:
        # Header unpacked in place (no ReplyHeader dataclass, no Reader
        # for error/ping frames): this runs once per received frame.
        xid, zxid, err = proto.unpack_reply_header(payload)
        if zxid > 0:
            self.last_zxid = zxid
        if xid == proto.XID_NOTIFICATION:
            event = proto.WatcherEvent.read(
                Reader(payload, proto.REPLY_HDR_SIZE)
            )
            self._on_watch_event(event)
            return
        if xid == proto.XID_PING:
            # Pings are fire-and-forget (no _pending entry); their replies
            # matter only as liveness, recorded in _last_response by the
            # read loop.
            return
        if self._op_spans:
            sp = self._op_spans.pop(xid, None)
            if sp is not None:
                if err != Err.OK:
                    sp.finish("error", err=err)
                else:
                    sp.finish()
        if not self._pending:
            log.warning("unmatched reply xid=%d", xid)
            return
        expected_xid, fut = self._pending.popleft()
        if expected_xid != xid:
            # FIFO pairing is broken: the connection is permanently
            # desynchronized.  Raise so _read_loop tears it down and the
            # reconnect machinery takes over (a fresh connection resets the
            # xid stream); limping on would turn every later op into a
            # mismatched zombie reply.
            if not fut.done():
                fut.set_exception(ZKError(Err.CONNECTION_LOSS))
            raise ConnectionError(
                f"xid mismatch: expected {expected_xid} got {xid}"
            )
        if fut.done():
            return
        if err != Err.OK:
            if err == Err.NOT_READONLY:
                # A write reached a read-only (minority) member: the
                # caller gets the retryable error; observers (metrics:
                # registrar_write_refusals_total) get the event.
                self.emit("write_refused", "read_only")
            fut.set_exception(ZKError(err))
        else:
            fut.set_result(Reader(payload, proto.REPLY_HDR_SIZE))

    #: which client-side watch registrations each event type consumes
    #: (matching real ZK: data/exist watches fire on created/deleted/
    #: dataChanged; child watches fire on childrenChanged and deleted).
    _EVENT_CLEARS = {
        proto.EventType.NODE_CREATED: ("data", "exist"),
        proto.EventType.NODE_DATA_CHANGED: ("data", "exist"),
        proto.EventType.NODE_DELETED: ("data", "exist", "child"),
        proto.EventType.NODE_CHILDREN_CHANGED: ("child",),
    }

    def _on_watch_event(self, event: proto.WatcherEvent) -> None:
        if event.type == proto.EventType.NONE:
            # Server-side session event (e.g. expiry notification).
            return
        if self.chroot:
            # Server notifications carry absolute paths; listeners (and the
            # re-arm bookkeeping) live in client coordinates.
            event = proto.WatcherEvent(
                type=event.type, state=event.state, path=self._rel(event.path)
            )
        for kind in self._EVENT_CLEARS.get(event.type, ()):
            self._watch_paths[kind].discard(event.path)
        self.emit("watch", event)
        self._watch_emitter.emit(event.path, event)

    def _post(self, xid: int, op: int, body, tr=None) -> asyncio.Future:
        """Queue one request on the wire without awaiting anything.

        The pipelining primitive: callers fan out many posts back to back
        (one buffered write each), drain once, then await the futures —
        avoiding a Task per operation for large fan-outs like the
        heartbeat's stat sweep.  ``tr`` lets a pipelined burst resolve
        the tracer once instead of per post (10k lookups per 10k-node
        sweep otherwise)."""
        if not self._connected or self._writer is None:
            raise ZKError(Err.CONNECTION_LOSS)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append((xid, fut))
        if tr is None:
            tr = trace.tracer_for(self)
        if tr.enabled and xid > 0:
            # One span per request, split submit -> flushed -> reply
            # (queue time vs wire time).  Reserved xids (auth replay,
            # SetWatches, pings) stay untraced: they are connection
            # plumbing, not operations the caller issued.
            self._op_spans[xid] = tr.start_span(
                "zk.op", op=_OP_NAMES.get(op, str(op)), xid=xid
            )
            self._unflushed.append(xid)
        encoded = proto.encode_request(xid, op, body)
        if self._corked is not None:
            self._corked.append(encoded)
        else:
            self._writer.write(encoded)
        return fut

    def _mark_flushed(self) -> None:
        """Stamp the queue->wire boundary on every span posted since the
        last drain (called right after a drain() completes: the bytes
        are out of our buffer, the remaining wait is the server+wire)."""
        if self._unflushed:
            for xid in self._unflushed:
                sp = self._op_spans.get(xid)
                if sp is not None:
                    sp.mark("flushed")
            self._unflushed.clear()

    def _cork(self) -> None:
        """Hold posted frames in a local list instead of writing each one.

        ``transport.write`` eagerly issues a send() syscall per call when
        its buffer is empty, so a 1000-post pipeline burst costs a
        thousand syscalls; corking joins the burst into one write (one
        or a few segments).  Callers must :meth:`_uncork` in a finally.
        """
        if self._corked is None:
            self._corked = []

    def _uncork(self) -> None:
        chunks, self._corked = self._corked, None
        if chunks and self._writer is not None:
            self._writer.write(b"".join(chunks))

    async def _post_pipeline(
        self, requests: Iterable[Tuple[int, object]]
    ) -> Tuple[List[asyncio.Future], Optional[BaseException]]:
        """Cork-post a burst of ``(op, record)`` requests with one drain.

        The pipelining skeleton shared by :meth:`mkdirp`,
        :meth:`get_many`, and the heartbeat sweep.  Returns the reply
        futures (FIFO, one per request) plus the not-connected ZKError
        raised while posting, if any — by then earlier posts hold
        pending futures the read loop will resolve (to CONNECTION_LOSS
        on teardown), so callers must gather the futures first and then
        decide how to rank the returned error against gathered ones.
        """
        futs: List[asyncio.Future] = []
        post_err: Optional[BaseException] = None
        tr = trace.tracer_for(self)
        try:
            self._cork()
            try:
                for op, body in requests:
                    futs.append(self._post(self._next_xid(), op, body, tr))
            finally:
                self._uncork()
            if futs and self._writer is not None:
                await self._writer.drain()
                self._mark_flushed()
        except (ConnectionError, OSError):
            await self._teardown(expected=False)
        except ZKError as e:  # not connected: fail after draining futs
            post_err = e
        return futs, post_err

    async def _submit(self, xid: int, op: int, body) -> Optional[Reader]:
        fut = self._post(xid, op, body)
        try:
            await self._writer.drain()
            self._mark_flushed()
        except (ConnectionError, OSError):
            await self._teardown(expected=False)
        return await self._await_reply(fut)

    async def _call(self, op: int, body) -> Reader:
        return await self._submit(self._next_xid(), op, body)

    async def _await_reply(self, awaitable):
        """Bound one awaited reply (or a gathered burst of them) by the
        per-operation deadline.

        On expiry the connection is torn down before raising: ZooKeeper
        answers strictly FIFO, so a reply cannot be skipped — if op N's
        answer never comes, neither does N+1's, and the only way to
        recover the pipeline is a fresh connection (which also resolves
        every other pending future to CONNECTION_LOSS).  The caller gets
        :class:`OperationTimeoutError`, which
        :func:`registrar_tpu.retry.is_transient` marks retryable.
        """
        if self.request_timeout_ms is None:
            return await awaitable
        try:
            return await asyncio.wait_for(
                awaitable, self.request_timeout_ms / 1000.0
            )
        except asyncio.TimeoutError:
            log.warning(
                "no reply within request_timeout (%d ms); dropping connection",
                self.request_timeout_ms,
            )
            await self._teardown(expected=False)
            raise OperationTimeoutError() from None

    async def _gather_replies(self, futs: Sequence[asyncio.Future]) -> List:
        """Deadline-bounded collection of a pipelined burst's reply
        futures (one shared deadline for the whole burst: the replies
        ride one FIFO connection, so the burst is one wire operation
        from the deadline's point of view).

        FIFO makes the LAST future the barrier: replies resolve in
        submission order, and a teardown fails every pending future at
        once — so when ``futs[-1]`` is done, all are.  Waiting on that
        one future costs one done-callback instead of the one-per-future
        a ``gather`` would register (10k registrations per 10k-znode
        sweep, ISSUE 11).  Exceptions are retrieved from every future on
        every path, including the timeout teardown, so no "exception was
        never retrieved" noise can escape."""
        if not futs:
            return []
        try:
            await self._await_reply(asyncio.wait([futs[-1]]))
        except OperationTimeoutError:
            # _await_reply already tore the connection down, which
            # resolved every pending future to CONNECTION_LOSS; mark
            # them retrieved before surfacing the deadline.
            for fut in futs:
                if fut.done() and not fut.cancelled():
                    fut.exception()
            raise
        out: List = []
        for fut in futs:
            err = fut.exception()
            out.append(fut.result() if err is None else err)
        return out

    def _ping_schedule(self) -> Tuple[float, float]:
        """(ping interval, dead-after) seconds for the current session.

        The default is the Apache client's thirds rule off the
        NEGOTIATED timeout: ping every third, declare the server dead
        after two-thirds of silence.  ``ping_interval_ms`` /
        ``dead_after_ms`` override each half independently (ISSUE 20's
        sub-session-timeout failure detection); an overridden dead-after
        is floored at the effective interval so the watchdog can never
        fire between its own pings."""
        if self.ping_interval_ms is not None:
            interval = self.ping_interval_ms / 1000.0
        else:
            interval = max(self.negotiated_timeout_ms / 3000.0, 0.02)
        if self.dead_after_ms is not None:
            dead_after = max(self.dead_after_ms / 1000.0, interval)
        else:
            dead_after = max(
                self.negotiated_timeout_ms * 2 / 3000.0, 2 * interval
            )
        return interval, dead_after

    async def _ping_loop(self) -> None:
        """Session keepalive + server-liveness watchdog.

        Pings every timeout/3.  If the server has produced *no* frame for
        more than 2/3 of the session timeout — TCP alive but unresponsive —
        the connection is torn down so the reconnect machinery can find a
        working server before the session expires (the same policy as the
        Apache ZooKeeper client's readTimeout).  Both knobs are tunable:
        :meth:`_ping_schedule`."""
        interval, dead_after = self._ping_schedule()
        try:
            while self._connected:
                await asyncio.sleep(interval)
                if not self._connected:
                    return
                if time.monotonic() - self._last_response > dead_after:
                    log.warning(
                        "no server response in %.1fs; dropping connection",
                        dead_after,
                    )
                    self.watchdog_drops += 1
                    await self._teardown(expected=False)
                    return
                try:
                    # Fire-and-forget: the reply (whenever it arrives)
                    # refreshes _last_response via _dispatch_frame; awaiting
                    # it here would wedge the watchdog behind the very
                    # stall it exists to detect.
                    if self._writer is not None:
                        self._writer.write(
                            proto.encode_request(proto.XID_PING, OpCode.PING)
                        )
                        # drain() itself can block indefinitely: a peer
                        # that stops READING (slow-loris) fills the kernel
                        # send buffer, the transport buffer rises past its
                        # high-water mark, and an unbounded drain parks
                        # the watchdog behind the exact stall it exists to
                        # detect (the pre-fix wedge; regression test:
                        # tests/test_netem.py drain-wedge).  Bound it by
                        # what is left of the dead-after budget, then
                        # declare the connection dead ourselves.
                        budget = dead_after - (
                            time.monotonic() - self._last_response
                        )
                        await asyncio.wait_for(
                            self._writer.drain(), timeout=max(budget, 0.01)
                        )
                except asyncio.TimeoutError:
                    log.warning(
                        "send buffer stalled for the remaining dead-after "
                        "budget (peer stopped reading); dropping connection",
                    )
                    self.watchdog_drops += 1
                    await self._teardown(expected=False)
                    return
                except (ConnectionError, OSError):
                    await self._teardown(expected=False)
                    return
        except asyncio.CancelledError:
            raise

    # -- znode operations (the reference's call surface) ---------------------

    async def create(
        self,
        path: str,
        data: bytes = b"",
        flags: int = CreateFlag.PERSISTENT,
        acls=None,
    ) -> str:
        """Create a znode; returns the created path."""
        self._check_path(path)
        r = await self._call(
            OpCode.CREATE,
            proto.CreateRequest(
                path=self._abs(path),
                data=data,
                acls=list(acls) if acls is not None else list(OPEN_ACL_UNSAFE),
                flags=flags,
            ),
        )
        return self._rel(proto.CreateResponse.read(r).path)

    async def create_ephemeral_plus(self, path: str, data: bytes = b"") -> str:
        """Ephemeral create that transparently creates missing parents.

        The zkplus 'ephemeral_plus' flag used by the reference's
        registerEntries stage (lib/register.js:156-158).  The registration
        pipeline mkdirps parents beforehand, so the fallback here only
        triggers when racing a concurrent cleanup.
        """
        try:
            return await self.create(path, data, CreateFlag.EPHEMERAL)
        except ZKError as err:
            if err.code != Err.NO_NODE:
                raise
        parent = path.rsplit("/", 1)[0] or "/"
        await self.mkdirp(parent)
        return await self.create(path, data, CreateFlag.EPHEMERAL)

    async def put(self, path: str, data: bytes) -> Stat:
        """Set a node's data, creating it (persistent) when missing.

        zkplus ``put`` semantics, used for the persistent service record
        (reference lib/register.js:62).
        """
        self._check_path(path)
        try:
            return await self.set_data(path, data)
        except ZKError as err:
            if err.code != Err.NO_NODE:
                raise
        parent = path.rsplit("/", 1)[0] or "/"
        await self.mkdirp(parent)
        try:
            await self.create(path, data, CreateFlag.PERSISTENT)
        except ZKError as err:
            if err.code != Err.NODE_EXISTS:
                raise
            return await self.set_data(path, data)  # lost the create race
        return (await self.stat(path))

    async def set_data(
        self, path: str, data: bytes, version: int = -1
    ) -> Stat:
        """Plain setData: NO_NODE if absent, BAD_VERSION on mismatch.

        Unlike :meth:`put` (zkplus semantics: create-if-missing), this is
        the raw ZooKeeper op — the right primitive for conditional writes.
        """
        self._check_path(path)
        r = await self._call(
            OpCode.SET_DATA,
            proto.SetDataRequest(
                path=self._abs(path), data=data, version=version
            ),
        )
        return proto.SetDataResponse.read(r).stat

    async def unlink(self, path: str, version: int = -1) -> None:
        """Delete a znode (zkplus name, reference lib/register.js:87)."""
        self._check_path(path)
        await self._call(
            OpCode.DELETE,
            proto.DeleteRequest(path=self._abs(path), version=version),
        )

    async def stat(self, path: str, watch: bool = False) -> Stat:
        """Stat a znode; raises NO_NODE when absent (heartbeat primitive)."""
        self._check_path(path)
        try:
            r = await self._call(
                OpCode.EXISTS,
                proto.ExistsRequest(path=self._abs(path), watch=watch),
            )
        except ZKError as err:
            if watch and err.code == Err.NO_NODE:
                self._watch_paths["exist"].add(path)
            raise
        if watch:
            self._watch_paths["data"].add(path)
        return proto.ExistsResponse.read(r).stat

    async def exists(self, path: str, watch: bool = False) -> Optional[Stat]:
        """Like :meth:`stat` but returns None instead of raising NO_NODE."""
        try:
            return await self.stat(path, watch=watch)
        except ZKError as err:
            if err.code == Err.NO_NODE:
                return None
            raise

    async def get(self, path: str, watch: bool = False) -> Tuple[bytes, Stat]:
        self._check_path(path)
        r = await self._call(
            OpCode.GET_DATA,
            proto.GetDataRequest(path=self._abs(path), watch=watch),
        )
        if watch:
            self._watch_paths["data"].add(path)
        resp = proto.GetDataResponse.read(r)
        return (resp.data or b"", resp.stat)

    async def get_many(
        self, paths: Iterable[str], watch: bool = False
    ) -> List[Optional[Tuple[bytes, Stat]]]:
        """Pipelined getData fan-out: one corked write, one drain, replies
        collected in order.  Returns one entry per path — ``(data, stat)``,
        or None where the node does not exist (NO_NODE is an expected
        answer for a fan-out over a changing tree, e.g. the Binder-view
        resolver reading a service's instances while members churn); any
        other error propagates.

        ``watch=True`` leaves a one-shot data watch on every path that
        exists (like real getData, NO_NODE leaves nothing behind — the
        zkcache refill path relies on that asymmetry and negative-caches
        only through explicit exists-watches).
        """
        paths = list(paths)
        for p in paths:
            self._check_path(p)
        futs, post_err = await self._post_pipeline(
            (
                OpCode.GET_DATA,
                proto.GetDataRequest(path=self._abs(p), watch=watch),
            )
            for p in paths
        )
        results = await self._gather_replies(futs)
        out: List[Optional[Tuple[bytes, Stat]]] = []
        for path, res in zip(paths, results):
            if isinstance(res, ZKError) and res.code == Err.NO_NODE:
                out.append(None)
                continue
            if isinstance(res, BaseException):
                raise res
            if watch:
                self._watch_paths["data"].add(path)
            resp = proto.GetDataResponse.read(res)
            out.append((resp.data or b"", resp.stat))
        if post_err is not None:
            raise post_err
        return out

    async def read_node(
        self, path: str, watch: bool = False
    ) -> Optional[Tuple[bytes, Stat, List[str]]]:
        """Read a node's data AND children in one pipelined flush.

        The Binder-view resolver's first two waits — ``get(path)`` then
        ``get_children(path)`` — ride one FIFO connection anyway, so
        corking them into a single write/drain saves a full round trip on
        every resolve (and every zkcache miss).  Returns ``(data, stat,
        children)``, or None when the node does not exist — including the
        narrow race where another session deletes it between the two
        server-side ops (the getData succeeded, the getChildren saw
        NO_NODE; the armed data watch then fires NODE_DELETED, so a cache
        holding the None is still invalidated).

        ``watch=True`` arms one-shot data + child watches on success,
        exactly as ``get(watch=True)`` + ``get_children(watch=True)``
        would; NO_NODE leaves nothing armed (negative caching is the
        caller's job, via :meth:`exists` and its exists-watch).
        """
        self._check_path(path)
        abs_path = self._abs(path)
        futs, post_err = await self._post_pipeline(
            (
                (
                    OpCode.GET_DATA,
                    proto.GetDataRequest(path=abs_path, watch=watch),
                ),
                (
                    OpCode.GET_CHILDREN2,
                    proto.GetChildrenRequest(path=abs_path, watch=watch),
                ),
            )
        )
        results = await self._gather_replies(futs)
        if post_err is not None or len(results) != 2:
            # Not connected mid-post: earlier futures (if any) were
            # gathered above so the read loop owes nothing; surface the
            # posting error.
            raise post_err if post_err is not None else ZKError(
                Err.CONNECTION_LOSS
            )
        data_res, child_res = results
        for res in (data_res, child_res):
            if isinstance(res, BaseException) and not (
                isinstance(res, ZKError) and res.code == Err.NO_NODE
            ):
                raise res
        if isinstance(data_res, ZKError) or isinstance(child_res, ZKError):
            # Absent (or deleted mid-burst).  When the getData half
            # succeeded, the server DID arm its data watch; record it so
            # a reconnect's SetWatches re-arm keeps parity with the
            # server-side state (the pending NODE_DELETED event resolves
            # both sides).
            if watch and not isinstance(data_res, ZKError):
                self._watch_paths["data"].add(path)
            return None
        if watch:
            self._watch_paths["data"].add(path)
            self._watch_paths["child"].add(path)
        data = proto.GetDataResponse.read(data_res)
        children = proto.GetChildren2Response.read(child_res).children
        return (data.data or b"", data.stat, children)

    async def get_children(self, path: str, watch: bool = False) -> List[str]:
        self._check_path(path)
        r = await self._call(
            OpCode.GET_CHILDREN2,
            proto.GetChildrenRequest(path=self._abs(path), watch=watch),
        )
        if watch:
            self._watch_paths["child"].add(path)
        return proto.GetChildren2Response.read(r).children

    async def mkdirp(self, path: str) -> None:
        """Create ``path`` and any missing ancestors (persistent, empty).

        Pipelined: one create per ancestor posted back-to-back on the
        single FIFO connection, one drain, replies collected in order.
        The server applies them in submission order, so each create sees
        its parent already made (or NODE_EXISTS) — the znode outcome is
        identical to the sequential walk at one round trip of latency
        instead of one per component (the registration pipeline's
        stage-3 hot path, 4-6 components per domain).  NODE_EXISTS is
        ignored per component; the first other error propagates (a
        failed ancestor cascades NO_NODE onto its descendants, so the
        root cause is the error reported).
        """
        self._check_path(path)
        if path == "/":
            return

        def requests():
            current = ""
            for comp in path.strip("/").split("/"):
                current += "/" + comp
                yield (
                    OpCode.CREATE,
                    proto.CreateRequest(
                        path=self._abs(current),
                        data=b"",
                        acls=list(OPEN_ACL_UNSAFE),
                        flags=CreateFlag.PERSISTENT,
                    ),
                )

        futs, post_err = await self._post_pipeline(requests())
        first_err: Optional[BaseException] = post_err
        for res in await self._gather_replies(futs):
            if (
                isinstance(res, BaseException)
                and not (isinstance(res, ZKError) and res.code == Err.NODE_EXISTS)
                and first_err is None
            ):
                first_err = res
        if first_err is not None:
            raise first_err

    def watch(self, path: str, listener) -> None:
        """Register a listener for one-shot watch events on ``path``."""
        self._watch_emitter.on(path, listener)

    def unwatch(self, path: str, listener) -> None:
        """Remove a listener previously registered with :meth:`watch`."""
        self._watch_emitter.off(path, listener)

    def forget_watches(self, path: str) -> None:
        """Drop ``path`` from the re-arm bookkeeping (client-side only).

        The server-side one-shot watch, if still armed, fires once more
        and is then gone; what this prevents is the reconnect-time
        SetWatches re-arm resurrecting a registration nobody listens to.
        Used by cache eviction, where the entry is gone and a future
        event for the path would be a harmless no-op invalidation.
        """
        for kind in self._watch_paths.values():
            kind.discard(path)

    # -- transactions / sync (full ZooKeeper 3.4 surface) --------------------

    async def sync(self, path: str) -> str:
        """Flush the server's commit pipeline for ``path`` (read barrier).

        A follower answers reads from possibly-stale local state; sync
        forces it to catch up with the leader first.  Beyond the reference's
        surface (zkplus never exposed it) — useful before read-backs in
        multi-server deployments.
        """
        self._check_path(path)
        r = await self._call(
            OpCode.SYNC, proto.SyncRequest(path=self._abs(path))
        )
        return self._rel(proto.SyncResponse.read(r).path)

    async def multi(self, ops: Sequence[Tuple[int, object]]) -> List[object]:
        """Atomically apply a transaction of :class:`Op` operations.

        Returns per-op results (created path str, :class:`Stat`, or None for
        delete/check).  On abort nothing is applied and :class:`MultiError`
        is raised carrying per-op error codes.  Beyond the reference's
        surface; enables e.g. atomic unregistration
        (:func:`registrar_tpu.registration.unregister` ``atomic=True``).
        """
        import dataclasses

        ops = list(ops)
        if not ops:
            return []
        for _, record in ops:
            self._check_path(record.path)
        if self.chroot:
            ops = [
                (t, dataclasses.replace(rec, path=self._abs(rec.path)))
                for t, rec in ops
            ]
        r = await self._call(OpCode.MULTI, proto.MultiRequest(ops=ops))
        resp = proto.MultiResponse.read(r)
        if any(isinstance(res, proto.ErrorResult) for res in resp.results):
            raise MultiError([res.err for res in resp.results])
        out: List[object] = []
        for res in resp.results:
            if isinstance(res, proto.CreateResponse):
                out.append(self._rel(res.path))
            elif isinstance(res, proto.SetDataResponse):
                out.append(res.stat)
            else:
                out.append(None)
        return out

    # -- auth / ACLs (full ZooKeeper 3.4 surface) ----------------------------

    async def add_auth(self, scheme: str, auth: bytes) -> None:
        """Authenticate this session's connection (``addauth`` in zkCli.sh).

        For the digest scheme ``auth`` is ``b"user:password"`` — the server
        hashes it and matches ACL ids of the form
        :func:`registrar_tpu.zk.protocol.digest_auth_id`.  The credential is
        remembered and replayed automatically after every reconnect.  Raises
        ``ZKError(AUTH_FAILED)`` (and the server drops the connection) for an
        unknown scheme or malformed credential.  Beyond the reference's
        surface: zkplus never exposed auth, and the reference creates every
        node world-writable (lib/register.js never passes ACLs).
        """
        if not isinstance(scheme, str) or not scheme:
            raise ValueError("scheme must be a non-empty string")
        await self._submit(
            proto.XID_AUTH,
            OpCode.AUTH,
            proto.AuthPacket(type=0, scheme=scheme, auth=auth),
        )
        if (scheme, auth) not in self._auths:
            self._auths.append((scheme, auth))

    async def get_acl(self, path: str) -> Tuple[List[proto.ACL], Stat]:
        """Read a node's ACL list and stat (aversion lives in the stat)."""
        self._check_path(path)
        r = await self._call(
            OpCode.GET_ACL, proto.GetACLRequest(path=self._abs(path))
        )
        resp = proto.GetACLResponse.read(r)
        return (resp.acls, resp.stat)

    async def set_acl(
        self, path: str, acls: Sequence[proto.ACL], version: int = -1
    ) -> Stat:
        """Replace a node's ACL list.

        ``version`` is compared against the node's **aversion** (not the data
        version); pass -1 to skip the check.  Requires ADMIN permission on
        the node.
        """
        self._check_path(path)
        r = await self._call(
            OpCode.SET_ACL,
            proto.SetACLRequest(
                path=self._abs(path), acls=list(acls), version=version
            ),
        )
        return proto.SetACLResponse.read(r).stat

    # -- application heartbeat (reference lib/zk.js:21-59) -------------------

    async def heartbeat(
        self, nodes: Iterable[str], retry: Optional[RetryPolicy] = None
    ) -> None:
        """Probe liveness of owned znodes: parallel stat with bounded retry.

        Retry policy: 5 attempts, exponential 1 s -> 30 s (reference
        lib/zk.js:37-43).  Raises the final error when all attempts fail.
        Note this is an *application-level* probe of the znodes; the session
        keepalive pings are handled inside the client (reference README:56-58
        makes the same distinction).

        One-group front of :meth:`heartbeat_many` — the per-group
        contract (pipelined EXISTS flush, NO_NODE retried through the
        bounded policy, :class:`OwnershipError`/SESSION_EXPIRED fatal)
        lives there in ONE copy.
        """
        err = (await self.heartbeat_many([nodes], retry=retry))[0]
        if err is not None:
            raise err

    async def heartbeat_many(
        self,
        groups: Sequence[Iterable[str]],
        retry: Optional[RetryPolicy] = None,
        on_outcome=None,
    ) -> List[Optional[BaseException]]:
        """Coalesced heartbeat: several services' owned-znode sweeps in
        ONE pipelined EXISTS flush per attempt (ISSUE 11 tentpole).

        ``groups`` is one node list per service; the return value is one
        entry per group — None on success, or the exception a solo
        :meth:`heartbeat` over that group would have raised.  Per-group
        behavior is contract-identical to N independent heartbeat calls
        sharing a deterministic retry schedule: a NO_NODE in group A
        burns A's attempts only, group B's sweep neither waits for nor
        fails with it; OwnershipError and SESSION_EXPIRED are final
        immediately (non-retryable); transient wire errors retry every
        still-undecided group together.  What coalescing changes is
        only the wire shape: all groups' EXISTS requests ride one
        corked write + one drain + one shared reply deadline
        (:meth:`_gather_replies`) instead of one flush per service.

        ``on_outcome(index, err_or_none)`` fires the moment a group's
        verdict is final — so a healthy service is released after the
        first attempt while a failing one is still riding the backoff
        schedule (the agent's coalescer resolves per-service futures
        from it).
        """
        groups = [list(g) for g in groups]
        for g in groups:
            for n in g:
                self._check_path(n)
        policy = retry or HEARTBEAT_RETRY
        pending = object()  # sentinel: group not yet decided
        outcomes: List[object] = [pending] * len(groups)

        def settle(i: int, err: Optional[BaseException]) -> None:
            outcomes[i] = err
            if on_outcome is not None:
                on_outcome(i, err)

        delays = policy.schedule()
        attempt = 0
        while True:
            live = [i for i, o in enumerate(outcomes) if o is pending]
            if not live:
                break
            errs = await self._exists_sweep(groups, live)
            retrying = False
            for i in live:
                err = errs[i]
                if err is None:
                    settle(i, None)
                    continue
                # An expired session cannot heartbeat its way back:
                # retrying just burns the bounded attempts while the
                # daemon should already be exiting for its supervisor
                # restart.  A foreign-owned ephemeral is just as
                # un-retryable — the other session holds it until IT
                # dies.  Everything else keeps the reference's
                # retry-all behavior.
                fatal = isinstance(err, OwnershipError) or (
                    isinstance(err, ZKError)
                    and err.code == Err.SESSION_EXPIRED
                )
                if fatal or attempt + 1 >= policy.max_attempts:
                    settle(i, err)
                else:
                    retrying = True
            if not retrying:
                break
            await asyncio.sleep(next(delays))
            attempt += 1
        return list(outcomes)  # type: ignore[arg-type]

    async def _exists_sweep(self, groups, idxs) -> dict:
        """One corked EXISTS flush over ``groups[i] for i in idxs``;
        returns ``{i: first error for that group, or None}``.

        Pipelined: post every exists request (buffered writes), one
        drain, then collect replies in order — no per-node Task, so a
        10k-znode sweep is one scheduling round, not ten thousand.  The
        ownership check (ISSUE 3 satellite) rides the same replies: the
        EXISTS stats already carry each node's ``ephemeralOwner``, and a
        bare existence probe passed forever on an ephemeral held by a
        FOREIGN session — a zombie predecessor's stale znode, or a
        hijacking duplicate registering our hostname.  Persistent nodes
        (the service record, owner 0) are exempt.  Decoded via
        :func:`protocol.stat_owner_from_reply` — one field, no Stat
        dataclass per reply (docs/PERF.md round 8).
        """
        flat: List[str] = []
        bounds = []  # (group index, start, end) into flat
        for i in idxs:
            start = len(flat)
            flat.extend(groups[i])
            bounds.append((i, start, len(flat)))
        try:
            futs, post_err = await self._post_pipeline(
                (
                    OpCode.EXISTS,
                    proto.ExistsRequest(path=self._abs(n), watch=False),
                )
                for n in flat
            )
            results = await self._gather_replies(futs)
        except asyncio.CancelledError:
            raise
        except Exception as sweep_err:  # noqa: BLE001 - timeout/conn loss
            return {i: sweep_err for i in idxs}
        if post_err is not None:
            # Posts after the failure point never got futures; their
            # groups fail with the posting error, ranked after any real
            # replies the earlier posts collected.
            results = results + [post_err] * (len(flat) - len(results))
        out = {}
        for i, start, end in bounds:
            err: Optional[BaseException] = None
            for res in results[start:end]:
                if isinstance(res, BaseException):
                    err = res
                    break
            if err is None:
                for node, res in zip(groups[i], results[start:end]):
                    try:
                        owner = proto.stat_owner_from_reply(res)
                    except Exception as decode_err:  # noqa: BLE001
                        err = decode_err  # malformed stat: same verdict
                        break  # as the old full-decode path
                    if owner and owner != self.session_id:
                        err = OwnershipError(node, owner, self.session_id)
                        break
            out[i] = err
        return out


class Op:
    """Operation constructors for :meth:`ZKClient.multi`."""

    @staticmethod
    def create(
        path: str,
        data: bytes = b"",
        flags: int = CreateFlag.PERSISTENT,
        acls=None,
    ) -> Tuple[int, proto.CreateRequest]:
        return (
            OpCode.CREATE,
            proto.CreateRequest(
                path=path,
                data=data,
                acls=list(acls) if acls is not None else list(OPEN_ACL_UNSAFE),
                flags=flags,
            ),
        )

    @staticmethod
    def delete(path: str, version: int = -1) -> Tuple[int, proto.DeleteRequest]:
        return (OpCode.DELETE, proto.DeleteRequest(path=path, version=version))

    @staticmethod
    def set_data(
        path: str, data: bytes, version: int = -1
    ) -> Tuple[int, proto.SetDataRequest]:
        return (
            OpCode.SET_DATA,
            proto.SetDataRequest(path=path, data=data, version=version),
        )

    @staticmethod
    def check(path: str, version: int) -> Tuple[int, proto.CheckVersionRequest]:
        return (
            OpCode.CHECK,
            proto.CheckVersionRequest(path=path, version=version),
        )


class MultiError(ZKError):
    """An aborted transaction: ``results`` holds each op's error code
    (the failing op's real code; RUNTIME_INCONSISTENCY for the rest)."""

    def __init__(self, results: List[int]):
        self.results = results
        first = next(
            (
                code for code in results
                if code not in (Err.OK, Err.RUNTIME_INCONSISTENCY)
            ),
            results[0] if results else Err.SYSTEM_ERROR,
        )
        super().__init__(first)


class _ReadOnlyMember(Exception):
    """Internal connect-pass signal: the handshake landed on a read-only
    member while the pass was still hunting for a read-write one.  Never
    escapes :meth:`ZKClient.connect` (the member is kept as the pass's
    fallback)."""


class SessionExpiredError(ZKError):
    def __init__(self) -> None:
        super().__init__(Err.SESSION_EXPIRED)


class OwnershipError(ZKError):
    """An owned znode's ephemeral is held by a FOREIGN session.

    Raised by the heartbeat sweep (ISSUE 3 satellite): the node exists —
    so a bare existence probe reads it as alive forever — but its
    ``ephemeralOwner`` is not our session, meaning this registrar does
    not control its lifetime (a zombie predecessor's stale znode, an
    operator's hand-made node, a duplicate instance claiming the same
    hostname).  Not retryable (the foreign session holds the node until
    it dies), and deliberately never "repaired" by deleting the node:
    two live claimants for one hostname is an operator problem — see
    docs/DESIGN.md "Why repair never steals".
    """

    def __init__(self, path: str, owner: int, session_id: int):
        self.owner = owner
        self.session = session_id
        super().__init__(Err.RUNTIME_INCONSISTENCY, path)
        # Repeatable diagnosis beats the generic code string: name both
        # sessions in the message operators will grep for.
        self.args = (
            f"{path} ephemeral is owned by foreign session 0x{owner:x} "
            f"(ours: 0x{session_id:x})",
        )


class OperationTimeoutError(ZKError):
    """A per-operation deadline (``request_timeout_ms``) expired.

    The connection was already torn down when this is raised (FIFO
    pipeline — see :meth:`ZKClient._await_reply`), so the session is on
    its way back up via the reconnect machinery; retrying the operation
    is the right move (:func:`registrar_tpu.retry.is_transient` → True).
    """

    def __init__(self) -> None:
        super().__init__(Err.OPERATION_TIMEOUT)


async def create_zk_client(
    servers: Sequence[Tuple[str, int]],
    timeout_ms: int = 30000,
    connect_timeout_ms: int = 4000,
    on_attempt=None,
    retry_policy: Optional[RetryPolicy] = None,
    chroot: Optional[str] = None,
    request_timeout_ms: Optional[int] = None,
    survive_session_expiry: bool = False,
    max_session_rebirths: Optional[int] = None,
    can_be_read_only: bool = False,
    rng: Optional[random.Random] = None,
    attach_preference: str = "any",
    connect_race_stagger_ms: Optional[int] = None,
    ping_interval_ms: Optional[int] = None,
    dead_after_ms: Optional[int] = None,
) -> ZKClient:
    """Create and connect a client, retrying forever (reference lib/zk.js:62-127).

    The reference wraps zkplus connect in backoff with failAfter(Infinity)
    and exponential 1 s -> 90 s, logging attempt 0 at info, attempts < 5 at
    warn, then error, and re-emitting 'attempt' events.  Here
    ``on_attempt(number, delay, err)`` receives the same information; abort
    by cancelling the awaiting task (the analog of ``retry.stop()``).
    """
    client = ZKClient(
        servers,
        timeout_ms=timeout_ms,
        connect_timeout_ms=connect_timeout_ms,
        reconnect_policy=retry_policy,  # None -> jittered RECONNECT_RETRY
        chroot=chroot,
        request_timeout_ms=request_timeout_ms,
        survive_session_expiry=survive_session_expiry,
        max_session_rebirths=max_session_rebirths,
        can_be_read_only=can_be_read_only,
        rng=rng,
        attach_preference=attach_preference,
        connect_race_stagger_ms=connect_race_stagger_ms,
        ping_interval_ms=ping_interval_ms,
        dead_after_ms=dead_after_ms,
    )
    return await connect_with_backoff(
        client, on_attempt=on_attempt, retry_policy=retry_policy
    )


async def connect_with_backoff(
    client: ZKClient,
    on_attempt=None,
    retry_policy: Optional[RetryPolicy] = None,
) -> ZKClient:
    """The reference's infinite-backoff initial connect, over an existing
    (possibly :meth:`ZKClient.seed_session`-staged) client.

    Split out of :func:`create_zk_client` for the handoff-resume path
    (ISSUE 5): the successor constructs and seeds the client itself, but
    the retry/backoff/logging envelope must be the daemon's usual one —
    including the case where the seeded reattach is refused mid-pass (the
    client resets to a fresh handshake and the NEXT attempt here builds
    the new session; ``SessionExpiredError`` is retryable for an open
    client).
    """

    def backoff_log(number: int, delay: float, err: Exception) -> None:
        level = (
            logging.INFO if number == 0
            else logging.WARNING if number < 5
            else logging.ERROR
        )
        log.log(
            level,
            "zookeeper: connection attempted (failed): attempt=%d delay=%.1fs err=%r",
            number, delay, err,
        )
        if on_attempt is not None:
            on_attempt(number, delay, err)

    await call_with_backoff(
        client.connect,
        retry_policy or CONNECT_RETRY,
        on_backoff=backoff_log,
        # A refused handoff resume raises SessionExpiredError but leaves
        # the client OPEN and reset to a fresh handshake: retry.  Only a
        # closed client (terminal expiry, close()) is unrecoverable.
        retryable=lambda err: not client.closed,
    )
    log.info("ZK: connected: %s", client)
    return client
