"""ZooKeeper 3.4 client protocol records, opcodes, and error codes.

The subset of the protocol registrar needs (reference lib/zk.js call surface:
connect, create-ephemeral, setData/put, delete, exists/stat, getData,
getChildren for tooling, ping, closeSession — see SURVEY.md §1 L1), encoded
with :mod:`registrar_tpu.zk.jute`.

Framing: every message on the wire is a 4-byte big-endian length followed by
that many payload bytes.  The first client message of a connection is a
ConnectRequest (no header); afterwards each request is
RequestHeader + op-specific body, each response ReplyHeader + body.
Server-initiated watch notifications arrive with xid == -1.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import struct

from registrar_tpu.zk.jute import JuteError, Reader, Writer

# Fixed-layout records pack/unpack their whole field list in one struct
# call — the per-field jute walk was the hottest encode/decode path in
# the wire stack (a Stat rides every exists/getData/setData reply).
_REQ_HDR = struct.Struct(">ii")    # xid, type
_REPLY_HDR = struct.Struct(">iqi")  # xid, zxid, err
_STAT = struct.Struct(">qqqqiiiqiiq")
_LEN = struct.Struct(">i")
_PW_HDR = struct.Struct(">iiii")   # frame len, xid, type, path len


# --- opcodes ---------------------------------------------------------------

class OpCode:
    NOTIFICATION = 0
    CREATE = 1
    DELETE = 2
    EXISTS = 3
    GET_DATA = 4
    SET_DATA = 5
    GET_ACL = 6
    SET_ACL = 7
    GET_CHILDREN = 8
    SYNC = 9
    PING = 11
    GET_CHILDREN2 = 12
    CHECK = 13
    MULTI = 14
    AUTH = 100
    SET_WATCHES = 101
    SASL = 102
    CREATE_SESSION = -10
    CLOSE_SESSION = -11
    ERROR = -1


# Reserved xids (client/server agreed sentinels).
XID_NOTIFICATION = -1
XID_PING = -2
XID_AUTH = -4
XID_SET_WATCHES = -8


# --- error codes -----------------------------------------------------------

class Err:
    OK = 0
    SYSTEM_ERROR = -1
    RUNTIME_INCONSISTENCY = -2
    DATA_INCONSISTENCY = -3
    CONNECTION_LOSS = -4
    MARSHALLING_ERROR = -5
    UNIMPLEMENTED = -6
    OPERATION_TIMEOUT = -7
    BAD_ARGUMENTS = -8
    API_ERROR = -100
    NO_NODE = -101
    NO_AUTH = -102
    BAD_VERSION = -103
    NO_CHILDREN_FOR_EPHEMERALS = -108
    NODE_EXISTS = -110
    NOT_EMPTY = -111
    SESSION_EXPIRED = -112
    INVALID_CALLBACK = -113
    INVALID_ACL = -114
    AUTH_FAILED = -115
    SESSION_MOVED = -118
    #: a state-changing request reached a read-only (minority/quorum-loss)
    #: member — ZooKeeper 3.4's NotReadOnlyException.  Transient by
    #: classification (retry.is_transient): the write succeeds once the
    #: client fails over to a read-write member or quorum returns.
    NOT_READONLY = -119

#: error code -> symbolic name, mirroring the names upper layers match on
#: (the reference matches `err.name !== 'NO_NODE'`, lib/register.js:88).
ERR_NAMES = {
    value: name
    for name, value in vars(Err).items()
    if not name.startswith("_")
}


# --- node create flags / ACL ----------------------------------------------

class CreateFlag:
    PERSISTENT = 0
    EPHEMERAL = 1
    PERSISTENT_SEQUENTIAL = 2
    EPHEMERAL_SEQUENTIAL = 3


class Perms:
    READ = 1
    WRITE = 2
    CREATE = 4
    DELETE = 8
    ADMIN = 16
    ALL = 31


@dataclass(frozen=True)
class ACL:
    perms: int
    scheme: str
    id: str

    def write(self, w: Writer) -> None:
        w.write_int(self.perms)
        w.write_ustring(self.scheme)
        w.write_ustring(self.id)

    @classmethod
    def read(cls, r: Reader) -> "ACL":
        return cls(perms=r.read_int(), scheme=r.read_ustring(), id=r.read_ustring())


#: world:anyone with all permissions — what zkplus (and thus the reference)
#: uses for every node it creates.
OPEN_ACL_UNSAFE = [ACL(Perms.ALL, "world", "anyone")]


def _encode_acl_vector(acls) -> bytes:
    w = Writer()
    w.write_vector(acls, lambda ww, a: a.write(ww))
    return w.to_bytes()


#: The default ACL vector's wire bytes — constant, so the CREATE fast
#: path in encode_request can append it without re-encoding.  The gate
#: compares against a snapshot taken at the same moment the blob was
#: encoded: if anything ever mutated OPEN_ACL_UNSAFE in place, creates
#: would fall back to the general path and still encode correctly.
_OPEN_ACLS_SNAPSHOT = [ACL(a.perms, a.scheme, a.id) for a in OPEN_ACL_UNSAFE]
_OPEN_ACL_BLOB = _encode_acl_vector(_OPEN_ACLS_SNAPSHOT)

#: read-only for everyone (ZooKeeper's ZooDefs.Ids.READ_ACL_UNSAFE).
READ_ACL_UNSAFE = [ACL(Perms.READ, "world", "anyone")]


def digest_auth_id(user: str, password: str) -> str:
    """``user:base64(sha1(user:password))`` — the id stored in digest ACLs.

    Matches ZooKeeper's DigestAuthenticationProvider.generateDigest, so
    ACLs minted here are interchangeable with ones from zkCli.sh.
    """
    import base64
    import hashlib

    digest = hashlib.sha1(f"{user}:{password}".encode()).digest()
    return f"{user}:{base64.b64encode(digest).decode('ascii')}"


def creator_all_acl(user: str, password: str) -> List[ACL]:
    """ALL perms for one digest identity (ZooDefs.Ids.CREATOR_ALL_ACL shape)."""
    return [ACL(Perms.ALL, "digest", digest_auth_id(user, password))]


# --- watch events ----------------------------------------------------------

class EventType:
    NONE = -1
    NODE_CREATED = 1
    NODE_DELETED = 2
    NODE_DATA_CHANGED = 3
    NODE_CHILDREN_CHANGED = 4


class KeeperState:
    DISCONNECTED = 0
    SYNC_CONNECTED = 3
    AUTH_FAILED = 4
    CONNECTED_READ_ONLY = 5
    EXPIRED = -112


# --- records ---------------------------------------------------------------

@dataclass
class ConnectRequest:
    protocol_version: int = 0
    last_zxid_seen: int = 0
    timeout_ms: int = 30000
    session_id: int = 0
    passwd: bytes = b"\x00" * 16
    read_only: bool = False

    def write(self, w: Writer) -> None:
        w.write_int(self.protocol_version)
        w.write_long(self.last_zxid_seen)
        w.write_int(self.timeout_ms)
        w.write_long(self.session_id)
        w.write_buffer(self.passwd)
        w.write_bool(self.read_only)

    @classmethod
    def read(cls, r: Reader) -> "ConnectRequest":
        req = cls(
            protocol_version=r.read_int(),
            last_zxid_seen=r.read_long(),
            timeout_ms=r.read_int(),
            session_id=r.read_long(),
            passwd=r.read_buffer() or b"\x00" * 16,
        )
        # The trailing readOnly byte was added in 3.4; tolerate its absence.
        if r.remaining() >= 1:
            req.read_only = r.read_bool()
        return req


@dataclass
class ConnectResponse:
    protocol_version: int = 0
    timeout_ms: int = 30000
    session_id: int = 0
    passwd: bytes = b"\x00" * 16
    read_only: bool = False

    def write(self, w: Writer) -> None:
        w.write_int(self.protocol_version)
        w.write_int(self.timeout_ms)
        w.write_long(self.session_id)
        w.write_buffer(self.passwd)
        w.write_bool(self.read_only)

    @classmethod
    def read(cls, r: Reader) -> "ConnectResponse":
        resp = cls(
            protocol_version=r.read_int(),
            timeout_ms=r.read_int(),
            session_id=r.read_long(),
            passwd=r.read_buffer() or b"\x00" * 16,
        )
        if r.remaining() >= 1:
            resp.read_only = r.read_bool()
        return resp


@dataclass
class RequestHeader:
    xid: int
    type: int

    def write(self, w: Writer) -> None:
        try:
            w.append_packed(_REQ_HDR.pack(self.xid, self.type))
        except struct.error as e:
            raise JuteError(str(e)) from None

    @classmethod
    def read(cls, r: Reader) -> "RequestHeader":
        xid, type_ = r.read_struct(_REQ_HDR)
        return cls(xid=xid, type=type_)


@dataclass
class ReplyHeader:
    xid: int
    zxid: int
    err: int

    def write(self, w: Writer) -> None:
        try:
            w.append_packed(_REPLY_HDR.pack(self.xid, self.zxid, self.err))
        except struct.error as e:
            raise JuteError(str(e)) from None

    @classmethod
    def read(cls, r: Reader) -> "ReplyHeader":
        xid, zxid, err = r.read_struct(_REPLY_HDR)
        return cls(xid=xid, zxid=zxid, err=err)


@dataclass
class Stat:
    czxid: int = 0
    mzxid: int = 0
    ctime: int = 0
    mtime: int = 0
    version: int = 0
    cversion: int = 0
    aversion: int = 0
    ephemeral_owner: int = 0
    data_length: int = 0
    num_children: int = 0
    pzxid: int = 0

    def _packed(self) -> bytes:
        """The wire bytes (delegates to :func:`pack_stat` — the ONE copy
        of the Stat field order, shared by the jute walk, the stat-only
        reply fast path, and the server's dataclass-free stat lane)."""
        return pack_stat(
            self.czxid,
            self.mzxid,
            self.ctime,
            self.mtime,
            self.version,
            self.cversion,
            self.aversion,
            self.ephemeral_owner,
            self.data_length,
            self.num_children,
            self.pzxid,
        )

    def write(self, w: Writer) -> None:
        w.append_packed(self._packed())

    @classmethod
    def read(cls, r: Reader) -> "Stat":
        (
            czxid,
            mzxid,
            ctime,
            mtime,
            version,
            cversion,
            aversion,
            ephemeral_owner,
            data_length,
            num_children,
            pzxid,
        ) = r.read_struct(_STAT)
        return cls(
            czxid=czxid,
            mzxid=mzxid,
            ctime=ctime,
            mtime=mtime,
            version=version,
            cversion=cversion,
            aversion=aversion,
            ephemeral_owner=ephemeral_owner,
            data_length=data_length,
            num_children=num_children,
            pzxid=pzxid,
        )


#: byte offset of ``ephemeralOwner`` inside a wire Stat: czxid, mzxid,
#: ctime, mtime (4 longs = 32) + version, cversion, aversion (3 ints =
#: 12).  Used by the stat-only reply fast path below.
STAT_OWNER_OFFSET = 44


def stat_owner_from_reply(r: Reader) -> int:
    """``ephemeralOwner`` out of a stat-only reply body (EXISTS — the
    heartbeat sweep's op) WITHOUT materializing the 11-field Stat.

    The ownership check (:meth:`registrar_tpu.zk.client.ZKClient.
    heartbeat`) reads exactly one of a Stat's eleven fields, and at
    1k–10k znodes per sweep the per-reply ``ExistsResponse``+``Stat``
    construction dominated the decode profile (docs/PERF.md round 8).
    The reader is NOT consumed (nothing reads a heartbeat reply after
    the owner check).  Raises :class:`~registrar_tpu.zk.jute.JuteError`
    on a truncated body, exactly like ``Stat.read`` would.
    """
    if r.remaining() < _STAT.size:
        r.read_struct(_STAT)  # raises the canonical truncation error
    return r.long_at(STAT_OWNER_OFFSET)


@dataclass
class CreateRequest:
    path: str
    data: Optional[bytes]
    acls: List[ACL] = field(default_factory=lambda: list(OPEN_ACL_UNSAFE))
    flags: int = CreateFlag.PERSISTENT

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_buffer(self.data)
        w.write_vector(self.acls, lambda ww, a: a.write(ww))
        w.write_int(self.flags)

    @classmethod
    def read(cls, r: Reader) -> "CreateRequest":
        return cls(
            path=r.read_ustring(),
            data=r.read_buffer(),
            acls=r.read_vector(ACL.read) or [],
            flags=r.read_int(),
        )


@dataclass
class CreateResponse:
    path: str

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)

    @classmethod
    def read(cls, r: Reader) -> "CreateResponse":
        return cls(path=r.read_ustring())


@dataclass
class DeleteRequest:
    path: str
    version: int = -1

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_int(self.version)

    @classmethod
    def read(cls, r: Reader) -> "DeleteRequest":
        return cls(path=r.read_ustring(), version=r.read_int())


@dataclass
class ExistsRequest:
    path: str
    watch: bool = False

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_bool(self.watch)

    @classmethod
    def read(cls, r: Reader) -> "ExistsRequest":
        return cls(path=r.read_ustring(), watch=r.read_bool())


@dataclass
class ExistsResponse:
    stat: Stat

    def write(self, w: Writer) -> None:
        self.stat.write(w)

    @classmethod
    def read(cls, r: Reader) -> "ExistsResponse":
        return cls(stat=Stat.read(r))


@dataclass
class GetDataRequest:
    path: str
    watch: bool = False

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_bool(self.watch)

    @classmethod
    def read(cls, r: Reader) -> "GetDataRequest":
        return cls(path=r.read_ustring(), watch=r.read_bool())


@dataclass
class GetDataResponse:
    data: Optional[bytes]
    stat: Stat

    def write(self, w: Writer) -> None:
        w.write_buffer(self.data)
        self.stat.write(w)

    @classmethod
    def read(cls, r: Reader) -> "GetDataResponse":
        return cls(data=r.read_buffer(), stat=Stat.read(r))


@dataclass
class SetDataRequest:
    path: str
    data: Optional[bytes]
    version: int = -1

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_buffer(self.data)
        w.write_int(self.version)

    @classmethod
    def read(cls, r: Reader) -> "SetDataRequest":
        return cls(path=r.read_ustring(), data=r.read_buffer(), version=r.read_int())


@dataclass
class SetDataResponse:
    stat: Stat

    def write(self, w: Writer) -> None:
        self.stat.write(w)

    @classmethod
    def read(cls, r: Reader) -> "SetDataResponse":
        return cls(stat=Stat.read(r))


@dataclass
class GetChildrenRequest:
    path: str
    watch: bool = False

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_bool(self.watch)

    @classmethod
    def read(cls, r: Reader) -> "GetChildrenRequest":
        return cls(path=r.read_ustring(), watch=r.read_bool())


@dataclass
class GetChildrenResponse:
    children: List[str]

    def write(self, w: Writer) -> None:
        w.write_vector(self.children, Writer.write_ustring)

    @classmethod
    def read(cls, r: Reader) -> "GetChildrenResponse":
        return cls(children=r.read_vector(Reader.read_ustring) or [])


@dataclass
class GetChildren2Response:
    children: List[str]
    stat: Stat

    def write(self, w: Writer) -> None:
        w.write_vector(self.children, Writer.write_ustring)
        self.stat.write(w)

    @classmethod
    def read(cls, r: Reader) -> "GetChildren2Response":
        return cls(
            children=r.read_vector(Reader.read_ustring) or [], stat=Stat.read(r)
        )


@dataclass
class SetWatches:
    relative_zxid: int
    data_watches: List[str] = field(default_factory=list)
    exist_watches: List[str] = field(default_factory=list)
    child_watches: List[str] = field(default_factory=list)

    def write(self, w: Writer) -> None:
        w.write_long(self.relative_zxid)
        w.write_vector(self.data_watches, Writer.write_ustring)
        w.write_vector(self.exist_watches, Writer.write_ustring)
        w.write_vector(self.child_watches, Writer.write_ustring)

    @classmethod
    def read(cls, r: Reader) -> "SetWatches":
        return cls(
            relative_zxid=r.read_long(),
            data_watches=r.read_vector(Reader.read_ustring) or [],
            exist_watches=r.read_vector(Reader.read_ustring) or [],
            child_watches=r.read_vector(Reader.read_ustring) or [],
        )


@dataclass
class WatcherEvent:
    type: int
    state: int
    path: str

    def write(self, w: Writer) -> None:
        w.write_int(self.type)
        w.write_int(self.state)
        w.write_ustring(self.path)

    @classmethod
    def read(cls, r: Reader) -> "WatcherEvent":
        return cls(type=r.read_int(), state=r.read_int(), path=r.read_ustring())


# --- sync ------------------------------------------------------------------

@dataclass
class SyncRequest:
    path: str

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)

    @classmethod
    def read(cls, r: Reader) -> "SyncRequest":
        return cls(path=r.read_ustring())


@dataclass
class SyncResponse:
    path: str

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)

    @classmethod
    def read(cls, r: Reader) -> "SyncResponse":
        return cls(path=r.read_ustring())


# --- auth / ACL ops ---------------------------------------------------------

@dataclass
class AuthPacket:
    """Body of an OpCode.AUTH request (always sent with xid -4).

    ``type`` is unused by ZooKeeper (always 0); ``scheme`` names the
    authentication provider ("digest", "ip", ...); ``auth`` is the raw
    credential — for digest, ``b"user:password"`` (the *server* hashes it).
    """

    type: int
    scheme: str
    auth: Optional[bytes]

    def write(self, w: Writer) -> None:
        w.write_int(self.type)
        w.write_ustring(self.scheme)
        w.write_buffer(self.auth)

    @classmethod
    def read(cls, r: Reader) -> "AuthPacket":
        return cls(type=r.read_int(), scheme=r.read_ustring(), auth=r.read_buffer())


@dataclass
class GetACLRequest:
    path: str

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)

    @classmethod
    def read(cls, r: Reader) -> "GetACLRequest":
        return cls(path=r.read_ustring())


@dataclass
class GetACLResponse:
    acls: List[ACL]
    stat: Stat

    def write(self, w: Writer) -> None:
        w.write_vector(self.acls, lambda ww, a: a.write(ww))
        self.stat.write(w)

    @classmethod
    def read(cls, r: Reader) -> "GetACLResponse":
        return cls(acls=r.read_vector(ACL.read) or [], stat=Stat.read(r))


@dataclass
class SetACLRequest:
    path: str
    acls: List[ACL]
    version: int = -1  # compared against the node's aversion

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_vector(self.acls, lambda ww, a: a.write(ww))
        w.write_int(self.version)

    @classmethod
    def read(cls, r: Reader) -> "SetACLRequest":
        return cls(
            path=r.read_ustring(),
            acls=r.read_vector(ACL.read) or [],
            version=r.read_int(),
        )


@dataclass
class SetACLResponse:
    stat: Stat

    def write(self, w: Writer) -> None:
        self.stat.write(w)

    @classmethod
    def read(cls, r: Reader) -> "SetACLResponse":
        return cls(stat=Stat.read(r))


# --- multi (transactions) ---------------------------------------------------
#
# A multi is an atomic batch of {create, delete, setData, check} ops.  On the
# wire each op is prefixed by a MultiHeader; a header with done=True (type -1)
# terminates the sequence.  Responses mirror the structure: per-op result
# records, or ErrorResult entries when the transaction aborted (the failing
# op carries its error code, the others RUNTIME_INCONSISTENCY).  The
# reference never batches (zkplus predates multi) — this exists so the
# rebuild's transport exposes the full modern ZooKeeper 3.4 surface, e.g.
# for atomic unregistration.

@dataclass
class CheckVersionRequest:
    path: str
    version: int

    def write(self, w: Writer) -> None:
        w.write_ustring(self.path)
        w.write_int(self.version)

    @classmethod
    def read(cls, r: Reader) -> "CheckVersionRequest":
        return cls(path=r.read_ustring(), version=r.read_int())


@dataclass
class MultiHeader:
    type: int
    done: bool
    err: int

    def write(self, w: Writer) -> None:
        w.write_int(self.type)
        w.write_bool(self.done)
        w.write_int(self.err)

    @classmethod
    def read(cls, r: Reader) -> "MultiHeader":
        return cls(type=r.read_int(), done=r.read_bool(), err=r.read_int())


#: op type -> request record class admissible inside a multi
MULTI_REQUESTS = {
    OpCode.CREATE: CreateRequest,
    OpCode.DELETE: DeleteRequest,
    OpCode.SET_DATA: SetDataRequest,
    OpCode.CHECK: CheckVersionRequest,
}

_MULTI_DONE = MultiHeader(type=-1, done=True, err=-1)


@dataclass
class ErrorResult:
    """Per-op failure marker inside an aborted multi response."""

    err: int

    def write(self, w: Writer) -> None:
        w.write_int(self.err)

    @classmethod
    def read(cls, r: Reader) -> "ErrorResult":
        return cls(err=r.read_int())


@dataclass
class MultiRequest:
    """Ordered (op_type, request_record) pairs forming one transaction."""

    ops: List[tuple]

    def write(self, w: Writer) -> None:
        for op_type, record in self.ops:
            MultiHeader(type=op_type, done=False, err=-1).write(w)
            record.write(w)
        _MULTI_DONE.write(w)

    @classmethod
    def read(cls, r: Reader) -> "MultiRequest":
        ops: List[tuple] = []
        while True:
            hdr = MultiHeader.read(r)
            if hdr.done:
                return cls(ops=ops)
            req_cls = MULTI_REQUESTS.get(hdr.type)
            if req_cls is None:
                raise ValueError(f"op type {hdr.type} not allowed in multi")
            ops.append((hdr.type, req_cls.read(r)))


@dataclass
class MultiResponse:
    """Per-op results: CreateResponse | SetDataResponse | None (delete/check
    ok) | ErrorResult."""

    results: List[object]

    def write(self, w: Writer) -> None:
        for result in self.results:
            if isinstance(result, ErrorResult):
                MultiHeader(type=OpCode.ERROR, done=False, err=result.err).write(w)
                result.write(w)
                continue
            if isinstance(result, CreateResponse):
                op_type = OpCode.CREATE
            elif isinstance(result, SetDataResponse):
                op_type = OpCode.SET_DATA
            elif isinstance(result, DeleteResult):
                op_type = OpCode.DELETE
            elif isinstance(result, CheckResult):
                op_type = OpCode.CHECK
            else:
                raise ValueError(f"bad multi result {result!r}")
            MultiHeader(type=op_type, done=False, err=0).write(w)
            if not isinstance(result, (DeleteResult, CheckResult)):
                result.write(w)
        _MULTI_DONE.write(w)

    @classmethod
    def read(cls, r: Reader) -> "MultiResponse":
        results: List[object] = []
        while True:
            hdr = MultiHeader.read(r)
            if hdr.done:
                return cls(results=results)
            if hdr.type == OpCode.ERROR:
                results.append(ErrorResult.read(r))
            elif hdr.type == OpCode.CREATE:
                results.append(CreateResponse.read(r))
            elif hdr.type == OpCode.SET_DATA:
                results.append(SetDataResponse.read(r))
            elif hdr.type == OpCode.DELETE:
                results.append(DeleteResult())
            elif hdr.type == OpCode.CHECK:
                results.append(CheckResult())
            else:
                raise ValueError(f"bad multi result type {hdr.type}")


@dataclass
class DeleteResult:
    """Successful delete inside a multi (no payload on the wire)."""


@dataclass
class CheckResult:
    """Successful version check inside a multi (no payload on the wire)."""


# --- framing helpers -------------------------------------------------------

def frame(payload: bytes) -> bytes:
    """Prefix a payload with its 4-byte big-endian length."""
    return _LEN.pack(len(payload)) + payload


# --- single-pack primitives (the dataclass-free reply lane, ISSUE 11) -------
#
# The server answers a 10k-znode heartbeat sweep with 10k stat-only
# replies; building Stat + ExistsResponse dataclasses per reply just to
# struct-pack them again dominated its encode profile.  These helpers
# expose the precompiled packs directly so the hot server lanes (and any
# other caller that already holds the raw fields) can emit wire bytes
# with zero intermediates — byte-identity with the record encoders is
# pinned by tests/test_wire_golden.py.

def pack_reply_header(xid: int, zxid: int, err: int) -> bytes:
    """One-struct ReplyHeader bytes (encode twin of ``read_struct``)."""
    try:
        return _REPLY_HDR.pack(xid, zxid, err)
    except struct.error as e:
        raise JuteError(str(e)) from None


#: ReplyHeader wire size — a reply body starts at this offset
REPLY_HDR_SIZE = _REPLY_HDR.size


def unpack_reply_header(payload) -> "tuple":
    """``(xid, zxid, err)`` straight off a reply frame (bytes or view),
    no ReplyHeader dataclass — the client dispatches every received
    frame through this."""
    if len(payload) < _REPLY_HDR.size:
        raise JuteError(
            f"truncated reply header: {len(payload)} bytes"
        )
    return _REPLY_HDR.unpack_from(payload, 0)


def pack_buffer(value: Optional[bytes]) -> bytes:
    """A jute buffer (int length + raw bytes; -1 encodes null)."""
    if value is None:
        return _LEN.pack(-1)
    try:
        return _LEN.pack(len(value)) + value
    except struct.error as e:  # pragma: no cover - >2GiB payload
        raise JuteError(str(e)) from None


def pack_stat(
    czxid: int,
    mzxid: int,
    ctime: int,
    mtime: int,
    version: int,
    cversion: int,
    aversion: int,
    ephemeral_owner: int,
    data_length: int,
    num_children: int,
    pzxid: int,
) -> bytes:
    """The 68-byte wire Stat in one struct pack — the ONE copy of the
    field order (``Stat._packed`` delegates here)."""
    try:
        return _STAT.pack(
            czxid, mzxid, ctime, mtime, version, cversion, aversion,
            ephemeral_owner, data_length, num_children, pzxid,
        )
    except struct.error as e:
        raise JuteError(str(e)) from None


def encode_request(xid: int, op: int, body=None) -> bytes:
    """Encode a framed request: RequestHeader + optional body record.

    The (path, watch) request shapes — EXISTS is hot loop #1's op (the
    heartbeat sweep, SURVEY §3.2), GET_DATA the resolver's — encode in a
    single struct pack; byte-equality with the general path is pinned by
    tests/test_wire_golden.py.
    """
    t = type(body)
    if t is ExistsRequest or t is GetDataRequest:
        b = body.path.encode("utf-8")
        n = len(b)
        try:
            head = _PW_HDR.pack(n + 13, xid, op, n)
        except struct.error as e:
            raise JuteError(str(e)) from None
        return head + b + (b"\x01" if body.watch else b"\x00")
    if t is CreateRequest and body.acls == _OPEN_ACLS_SNAPSHOT:
        # The registration pipeline's op (mkdirp components + ephemeral
        # host records) always carries the default world:anyone ACL,
        # whose encoded vector is the precomputed _OPEN_ACL_BLOB.
        b = body.path.encode("utf-8")
        d = body.data
        n = len(b)
        m = -1 if d is None else len(d)
        # body = xid 4 + type 4 + pathlen 4 + path n + datalen 4 +
        #        data max(m,0) + acl blob + flags 4
        try:
            head = _PW_HDR.pack(
                20 + n + (0 if m < 0 else m) + len(_OPEN_ACL_BLOB),
                xid, op, n,
            )
            datalen = _LEN.pack(m)
            flags = _LEN.pack(body.flags)
        except struct.error as e:
            raise JuteError(str(e)) from None
        return head + b + datalen + (d or b"") + _OPEN_ACL_BLOB + flags
    w = Writer()
    RequestHeader(xid=xid, type=op).write(w)
    if body is not None:
        body.write(w)
    return frame(w.to_bytes())


def encode_reply_payload(xid: int, zxid: int, err: int, body=None) -> bytes:
    """Encode an unframed reply: ReplyHeader + body (body suppressed on error).

    Stat-only reply bodies (exists — the heartbeat answer — and setData)
    encode in two struct packs; byte-equality with the general path is
    pinned by tests/test_wire_golden.py.
    """
    if err == Err.OK:
        t = type(body)
        if t is ExistsResponse or t is SetDataResponse:
            try:
                head = _REPLY_HDR.pack(xid, zxid, err)
            except struct.error as e:
                raise JuteError(str(e)) from None
            return head + body.stat._packed()
    w = Writer()
    ReplyHeader(xid=xid, zxid=zxid, err=err).write(w)
    if body is not None and err == Err.OK:
        body.write(w)
    return w.to_bytes()


def encode_reply(xid: int, zxid: int, err: int, body=None) -> bytes:
    """Encode a framed reply: ReplyHeader + optional body record."""
    return frame(encode_reply_payload(xid, zxid, err, body))


class ZKError(Exception):
    """A ZooKeeper server-reported error, carrying the protocol code.

    ``name`` holds the symbolic code name (e.g. ``"NO_NODE"``); upper layers
    match on it exactly like the reference matches zkplus error names
    (reference lib/register.js:88).
    """

    def __init__(self, code: int, path: Optional[str] = None):
        self.code = code
        self.name = ERR_NAMES.get(code, f"ZK_ERROR_{code}")
        self.path = path
        super().__init__(f"{self.name} ({code})" + (f": {path}" if path else ""))


#: PathCache bounds (module-level so the class-body defaults resolve in
#: module scope — class attributes are invisible to the checker's
#: default-argument approximation, and to nested scopes generally).
PATH_CACHE_MAX_ENTRIES = 4096
PATH_CACHE_MAX_PATH_LEN = 256


class PathCache:
    """Paths already validated by :func:`check_path`.

    The daemon's hot loops (heartbeat sweeps, the registration pipeline)
    re-validate the same handful of paths every pass; membership here
    short-circuits the per-component walk.  Bounded in count AND entry
    size (a wire frame can carry a multi-MiB path — an unbounded-bytes
    cache would let a hostile stream pin gigabytes); validation is pure,
    so caching is safe.  FIFO eviction when full (insertion-ordered
    dict), so a long-lived daemon whose instance paths churn keeps
    caching NEW hot paths instead of freezing on the first 4096 it ever
    saw.

    Each :class:`~registrar_tpu.zk.client.ZKClient` owns one; the test
    server validates client-supplied paths with NO cache at all, so a
    noisy or hostile peer streaming unique valid paths can never thrash
    the daemon's own hot entries (it only pays the per-component walk it
    asked for).
    """

    __slots__ = ("_entries", "max_entries", "max_path_len")

    def __init__(
        self,
        max_entries: int = PATH_CACHE_MAX_ENTRIES,
        max_path_len: int = PATH_CACHE_MAX_PATH_LEN,
    ):
        self._entries: dict = {}
        self.max_entries = max_entries
        self.max_path_len = max_path_len

    def __contains__(self, path) -> bool:
        return type(path) is str and path in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def add(self, path: str) -> None:
        if self.max_entries <= 0:
            return  # a zero-capacity cache is disabled, not a crash
        if len(path) > self.max_path_len:
            return
        if len(self._entries) >= self.max_entries:
            self._entries.pop(next(iter(self._entries)))  # FIFO eviction
        self._entries[path] = True


def check_path(path: str, cache: Optional[PathCache] = None) -> str:
    """Validate a znode path the way ZooKeeper's PathUtils does.

    ``cache`` (a caller-owned :class:`PathCache`) short-circuits
    re-validation of known-good paths; pass None for untrusted input
    (server-side validation of peer-supplied paths) or one-off calls.
    """
    if cache is not None and path in cache:
        return path
    if not isinstance(path, str) or not path:
        raise ValueError("path must be a non-empty string")
    if not path.startswith("/"):
        raise ValueError(f"path must start with /: {path!r}")
    if len(path) > 1 and path.endswith("/"):
        raise ValueError(f"path must not end with /: {path!r}")
    if "//" in path:
        raise ValueError(f"empty path component: {path!r}")
    for comp in path.split("/")[1:]:
        if comp in (".", ".."):
            raise ValueError(f"relative path component: {path!r}")
        if "\x00" in comp:
            raise ValueError(f"null byte in path component: {path!r}")
    if cache is not None:
        cache.add(path)
    return path
