"""Jute (ZooKeeper's wire serialization) primitives.

ZooKeeper serializes every protocol record with "jute", a tiny big-endian
binary format.  The reference delegates this to the external zkplus/node
ZooKeeper stack (reference package.json:21); this rebuild implements the
format directly so the framework is standalone.

Primitive encodings (Apache ZooKeeper jute/binary format, stable since 3.x):

    int      4-byte signed big-endian
    long     8-byte signed big-endian
    boolean  1 byte (0 or 1)
    buffer   int length followed by raw bytes; length -1 encodes null
    ustring  buffer holding UTF-8 text
    vector   int count followed by elements; count -1 encodes null
"""

from __future__ import annotations

import struct
from typing import Callable, List, Optional, TypeVar

from registrar_tpu import malformed

T = TypeVar("T")

_INT = struct.Struct(">i")
_LONG = struct.Struct(">q")

INT_MIN, INT_MAX = -(2**31), 2**31 - 1
LONG_MIN, LONG_MAX = -(2**63), 2**63 - 1


class JuteError(ValueError):
    """Raised on malformed jute data."""


class Writer:
    """Accumulates jute-encoded primitives into a byte buffer."""

    __slots__ = ("_chunks",)

    def __init__(self) -> None:
        self._chunks: List[bytes] = []

    def to_bytes(self) -> bytes:
        return b"".join(self._chunks)

    def append_packed(self, encoded: bytes) -> "Writer":
        """Append pre-packed big-endian bytes (a `struct.Struct.pack` of
        several primitives at once).  The fixed-layout records (Stat, the
        request/reply headers) pack their whole field list in one call —
        the per-field write_int/write_long walk was the hottest encode
        path in the wire stack."""
        self._chunks.append(encoded)
        return self

    def write_int(self, value: int) -> "Writer":
        if not INT_MIN <= value <= INT_MAX:
            raise JuteError(f"int out of range: {value}")
        self._chunks.append(_INT.pack(value))
        return self

    def write_long(self, value: int) -> "Writer":
        if not LONG_MIN <= value <= LONG_MAX:
            raise JuteError(f"long out of range: {value}")
        self._chunks.append(_LONG.pack(value))
        return self

    def write_bool(self, value: bool) -> "Writer":
        self._chunks.append(b"\x01" if value else b"\x00")
        return self

    def write_buffer(self, value: Optional[bytes]) -> "Writer":
        if value is None:
            return self.write_int(-1)
        self.write_int(len(value))
        # bytes payloads (the common case) are immutable — append as-is;
        # only mutable buffer types (bytearray/memoryview) need a copy to
        # pin the encoded frame against later mutation.
        self._chunks.append(value if type(value) is bytes else bytes(value))
        return self

    def write_ustring(self, value: Optional[str]) -> "Writer":
        return self.write_buffer(None if value is None else value.encode("utf-8"))

    def write_vector(
        self, items: Optional[List[T]], write_item: Callable[["Writer", T], object]
    ) -> "Writer":
        if items is None:
            return self.write_int(-1)
        self.write_int(len(items))
        for item in items:
            write_item(self, item)
        return self


class Reader:
    """Reads jute-encoded primitives from any bytes-like buffer.

    ``data`` may be ``bytes`` or a ``memoryview`` (ISSUE 11): the frame
    layer hands replies over as zero-copy views into the transport's
    receive chunks, and every fixed-width primitive decodes in place via
    ``unpack_from`` — no per-field slice is ever materialized.  Variable
    payloads materialize lazily, exactly once, at their read call:
    :meth:`read_buffer` returns real ``bytes`` (payloads escape into
    caches and comparisons, where a view pinning a 64 KB receive chunk
    would be a leak) and :meth:`read_ustring` decodes straight from the
    view without an intermediate ``bytes`` copy.
    """

    __slots__ = ("_data", "_pos")

    def __init__(self, data, pos: int = 0) -> None:
        self._data = data
        self._pos = pos

    @property
    def pos(self) -> int:
        return self._pos

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def _take(self, n: int):
        """Consume ``n`` bytes as a slice of the underlying buffer — a
        copy for ``bytes`` input, a zero-copy subview for ``memoryview``
        input.  Internal: callers materialize or decode as needed."""
        if self.remaining() < n:
            malformed.note("jute")
            raise JuteError(
                f"truncated jute data: need {n} bytes at offset {self._pos}, "
                f"have {self.remaining()}"
            )
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def read_struct(self, st: struct.Struct) -> tuple:
        """Unpack a fixed-layout run of primitives in one call (the decode
        twin of :meth:`Writer.append_packed`)."""
        pos = self._pos
        if len(self._data) - pos < st.size:
            self._take(st.size)  # raises the canonical truncation error
        self._pos = pos + st.size
        return st.unpack_from(self._data, pos)

    def read_int(self) -> int:
        # unpack_from avoids the intermediate slice _take would allocate;
        # ints dominate every frame (lengths, xids, versions), so this is
        # the hottest decode path in the wire stack.
        pos = self._pos
        if len(self._data) - pos < 4:
            self._take(4)  # raises the canonical truncation error
        self._pos = pos + 4
        return _INT.unpack_from(self._data, pos)[0]

    def read_long(self) -> int:
        pos = self._pos
        if len(self._data) - pos < 8:
            self._take(8)
        self._pos = pos + 8
        return _LONG.unpack_from(self._data, pos)[0]

    def read_bool(self) -> bool:
        return self._take(1) != b"\x00"

    def long_at(self, offset: int) -> int:
        """Peek one long at ``pos + offset`` WITHOUT consuming anything.

        The scratch-free fast path for fixed-layout reply bodies that
        only need one field (the heartbeat sweep reads a Stat's
        ``ephemeralOwner`` and nothing else — see
        :func:`registrar_tpu.zk.protocol.stat_owner_from_reply`)."""
        pos = self._pos + offset
        if offset < 0 or len(self._data) - pos < 8:
            malformed.note("jute")
            raise JuteError(
                f"truncated jute data: need 8 bytes at offset {pos}, "
                f"have {max(len(self._data) - pos, 0)}"
            )
        return _LONG.unpack_from(self._data, pos)[0]

    def read_buffer(self) -> Optional[bytes]:
        n = self.read_int()
        if n == -1:
            return None
        if n < -1:
            malformed.note("jute")
            raise JuteError(f"negative buffer length: {n}")
        out = self._take(n)
        # Materialize exactly once: a view escaping here would pin the
        # whole receive chunk for as long as a cached payload lives.
        return out if type(out) is bytes else bytes(out)

    def read_ustring(self) -> Optional[str]:
        n = self.read_int()
        if n == -1:
            return None
        if n < -1:
            malformed.note("jute")
            raise JuteError(f"negative buffer length: {n}")
        # Decode straight off the buffer slice (bytes or view): one
        # string allocation, no intermediate bytes copy for views.
        try:
            return str(self._take(n), "utf-8")
        except UnicodeDecodeError as err:
            malformed.note("jute")
            raise JuteError(f"string not UTF-8: {err}") from err

    def read_vector(self, read_item: Callable[["Reader"], T]) -> Optional[List[T]]:
        n = self.read_int()
        if n == -1:
            return None
        if n < -1:
            malformed.note("jute")
            raise JuteError(f"negative vector length: {n}")
        if n > self.remaining():
            # Every element costs >= 1 byte, so a count beyond the buffer
            # is malformed; reject before allocating the list (a hostile
            # frame could otherwise declare a 2^31 count).
            malformed.note("jute")
            raise JuteError(f"vector length {n} exceeds remaining data")
        return [read_item(self) for _ in range(n)]
