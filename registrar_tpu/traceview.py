"""Cross-process trace assembly: fragments in, ONE causal tree out.

PR 8 gave every process a span layer and a flight recorder; PR 12 made
the serve path multi-process — and a resolve crossing ShardRouter →
ShardWorker → ZooKeeper now leaves its spans scattered across three
recorders.  The shard protocol's trace-context extension (ISSUE 13,
:mod:`registrar_tpu.shard`) makes every fragment share ONE trace id and
honest parent ids; this module is the other half — it merges dumped
flight-recorder entries from any number of processes and reconstructs
the parent tree:

  * **spans** are joined by ``span_id``/``parent_id`` across process
    boundaries (the ids are process-independent 64-bit tokens);
  * **duplicates** are dropped by span id, first occurrence wins — the
    collector may legitimately hand the same recorder in twice (the
    router's own tracer is also the SLO harness's tracer);
  * **orphans** — spans whose parent id was never collected (the parent
    process crashed, its ring evicted the span, or the parent was
    unsampled) — attach under a synthetic :data:`MISSING_PARENT` node
    instead of silently vanishing.  A crashed worker must not erase the
    subtree that survived it; an incomplete tree that SAYS it is
    incomplete is evidence, a quietly-pruned one is a lie;
  * **events** carrying the trace id ride along in timestamp order
    (they have no parent ids; they annotate the trace, not the tree).

Consumed by :meth:`registrar_tpu.shard.ShardRouter.collect_trace` (the
``OP_TRACE`` fan-out behind ``GET /debug/trace?id=`` and ``zkcli trace
--id``), by the daemon's own single-process ``?id=`` view (main.py),
and by the SLO report's worst-outage dump (testing/slo.py).  The future
DNS frontend inherits this unchanged: a DNS query id maps onto the same
trace id and lands in the same tree.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

#: the synthetic node orphaned subtrees hang under — a NAME, not a span
#: id, so renderers and tests can key on it (docs/OBSERVABILITY.md
#: documents the convention)
MISSING_PARENT = "<missing parent>"


def _node(entry: Dict[str, Any]) -> Dict[str, Any]:
    node = dict(entry)
    node["children"] = []
    return node


def _sort_key(node: Dict[str, Any]):
    return (node.get("time") or 0.0, node.get("span_id") or "")


def assemble(
    entries: Iterable[Dict[str, Any]], trace_id: str
) -> Dict[str, Any]:
    """Merge flight-recorder ``entries`` (possibly from many processes,
    possibly overlapping) into one trace tree for ``trace_id``.

    Returns ``{"trace_id", "spans", "events", "orphans", "roots",
    "events_list"}`` where ``roots`` is a list of span nodes (each with
    recursive ``children``, time-ordered) — the last root is the
    synthetic :data:`MISSING_PARENT` node when any span's parent was
    not collected.
    """
    spans: Dict[str, Dict[str, Any]] = {}
    events: List[Dict[str, Any]] = []
    seen_events: set = set()
    for entry in entries:
        if entry.get("trace_id") != trace_id:
            continue
        if entry.get("kind") == "event":
            # Events carry no ids; dedupe overlapping dumps by their
            # FULL observable identity (name, timestamp, origin, attrs)
            # so a recorder handed in twice cannot double-count them —
            # while two distinct same-named events that merely share a
            # coarse-clock timestamp keep their separate attrs.
            key = (
                entry.get("name"),
                entry.get("time"),
                entry.get("proc"),
                repr(sorted((entry.get("attrs") or {}).items())),
            )
            if key in seen_events:
                continue
            seen_events.add(key)
            events.append(dict(entry))
            continue
        span_id = entry.get("span_id")
        if span_id is None or span_id in spans:
            continue  # duplicate fragment: first occurrence wins
        spans[span_id] = _node(entry)

    roots: List[Dict[str, Any]] = []
    orphaned: List[Dict[str, Any]] = []
    for node in spans.values():
        parent_id = node.get("parent_id")
        if parent_id is None:
            roots.append(node)
        elif parent_id in spans:
            spans[parent_id]["children"].append(node)
        else:
            orphaned.append(node)

    for node in spans.values():
        node["children"].sort(key=_sort_key)
    roots.sort(key=_sort_key)
    events.sort(key=lambda e: e.get("time") or 0.0)

    if orphaned:
        orphaned.sort(key=_sort_key)
        roots.append(
            {
                "kind": "span",
                "name": MISSING_PARENT,
                "trace_id": trace_id,
                "span_id": None,
                "parent_id": None,
                "synthetic": True,
                "children": orphaned,
            }
        )
    return {
        "trace_id": trace_id,
        "spans": len(spans),
        "events": len(events),
        "orphans": len(orphaned),
        "roots": roots,
        "events_list": events,
    }


def _fmt_span(node: Dict[str, Any]) -> str:
    if node.get("synthetic"):
        return f"{node['name']}  (parent span never collected)"
    dur = node.get("duration_ms")
    dur_s = f"{dur:.3f}ms" if isinstance(dur, (int, float)) else "?"
    bits = [f"{node.get('name')}  {dur_s}  [{node.get('status', '?')}]"]
    proc = node.get("proc")
    if proc:
        bits.append(f"@{proc}")
    attrs = node.get("attrs") or {}
    if attrs:
        bits.append(
            " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        )
    marks = node.get("marks") or {}
    if marks:
        bits.append(
            "marks: "
            + " ".join(f"{k}={v}ms" for k, v in sorted(marks.items()))
        )
    return "  ".join(bits)


def render_text(tree: Dict[str, Any]) -> str:
    """The operator view: one indented line per span, durations and
    marks inline, orphan subtrees visibly flagged — what ``zkcli trace
    --id`` prints and the SLO worst-outage dump ships next to
    slo-report.json."""
    lines = [
        f"trace {tree['trace_id']}: {tree['spans']} spans, "
        f"{tree['events']} events"
        + (f", {tree['orphans']} orphaned" if tree.get("orphans") else "")
    ]

    def walk(node: Dict[str, Any], depth: int) -> None:
        lines.append("  " * depth + _fmt_span(node))
        for child in node.get("children", ()):
            walk(child, depth + 1)

    for root in tree.get("roots", ()):
        walk(root, 1)
    for event in tree.get("events_list", ()):
        attrs = event.get("attrs") or {}
        suffix = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        lines.append(f"  event {event.get('name')}  {suffix}".rstrip())
    return "\n".join(lines)


def worst_span_ms(tree: Dict[str, Any]) -> Optional[float]:
    """The longest span duration in the tree (report rollups)."""
    worst: Optional[float] = None

    def walk(node: Dict[str, Any]) -> None:
        nonlocal worst
        dur = node.get("duration_ms")
        if isinstance(dur, (int, float)) and (worst is None or dur > worst):
            worst = dur
        for child in node.get("children", ()):
            walk(child)

    for root in tree.get("roots", ()):
        walk(root)
    return worst
