"""Registration engine: writes this host's service-discovery records.

The rebuild of reference lib/register.js:174-304.  ``register`` runs the
same observable five-stage pipeline against ZooKeeper:

  1. cleanup previous entries — parallel unlink of every target znode,
     ignoring NO_NODE (reference lib/register.js:78-105);
  2. settle delay — fixed 1 s pause "to be nice to watchers"
     (reference lib/register.js:232-235; configurable here, same default);
  3. setup directories — parallel mkdirp of each znode's parent
     (reference lib/register.js:108-129);
  4. register entries — parallel ephemeral-plus create of the host record
     at each znode (reference lib/register.js:132-171);
  5. register service — when a service is configured, a *persistent* put of
     the service record at the domain node itself, which is then appended
     to the owned-node list (reference lib/register.js:45-75).

``unregister`` deletes the znodes sequentially (reference
lib/register.js:254-295).  Two reference bugs are fixed here without
changing znode state (SURVEY.md §7 "faithful-vs-fixed"):

  * reference unregister invokes the *outer* callback after the first
    successful unlink (`cb()` instead of `_cb()`, lib/register.js:281), so
    callers observed completion while later deletes were still in flight —
    here completion means every node was processed;
  * the reference re-validates + mutates the caller's service config in
    place; here record construction is side-effect-free.
"""

from __future__ import annotations

import asyncio
import logging
import socket
from typing import Any, List, Mapping, Optional, Sequence

from registrar_tpu import trace
from registrar_tpu.records import (
    default_address,
    domain_to_path,
    host_record,
    payload_bytes,
    service_record,
)
from registrar_tpu.retry import RetryPolicy, call_with_backoff, is_transient
from registrar_tpu.zk.client import MultiError, Op, ZKClient
from registrar_tpu.zk.protocol import Err, ZKError

log = logging.getLogger("registrar_tpu.registration")

#: Stage-2 pause before re-creating nodes, reference lib/register.js:232-235.
SETTLE_DELAY_S = 1.0

#: Default transient-fault retry for the registration pipeline when a
#: caller opts in (``retry_policy=REGISTER_RETRY``): a blip of connection
#: loss / per-op timeout mid-pipeline re-runs the whole idempotent
#: pipeline (stage 1's cleanup reconciles any half-registration) after a
#: short decorrelated-jitter backoff, instead of surfacing to the
#: orchestrator as a registration failure.  SESSION_EXPIRED and semantic
#: errors stay fatal (retry.is_transient).
REGISTER_RETRY = RetryPolicy(
    max_attempts=4, initial_delay=0.25, max_delay=2.0, jitter="decorrelated"
)


def _validate_registration(registration: Mapping[str, Any]) -> None:
    """Schema check mirroring the reference's assert-plus block
    (lib/register.js:174-201)."""
    if not isinstance(registration, Mapping):
        raise ValueError("registration must be an object")
    if not isinstance(registration.get("domain"), str) or not registration["domain"]:
        raise ValueError("registration.domain must be a non-empty string")
    if not isinstance(registration.get("type"), str) or not registration["type"]:
        raise ValueError("registration.type must be a non-empty string")
    ttl = registration.get("ttl")
    if ttl is not None and (not isinstance(ttl, int) or isinstance(ttl, bool)):
        raise ValueError("registration.ttl must be an integer")
    ports = registration.get("ports")
    if ports is not None:
        if not isinstance(ports, Sequence) or isinstance(ports, (str, bytes)):
            raise ValueError("registration.ports must be an array of integers")
        for p in ports:
            if not isinstance(p, int) or isinstance(p, bool):
                raise ValueError("registration.ports must be an array of integers")
    aliases = registration.get("aliases")
    if aliases is not None:
        if not isinstance(aliases, Sequence) or isinstance(aliases, (str, bytes)):
            raise ValueError("registration.aliases must be an array of strings")
        for a in aliases:
            if not isinstance(a, str):
                raise ValueError("registration.aliases must be an array of strings")


def znode_paths(
    registration: Mapping[str, Any], hostname: Optional[str] = None
) -> List[str]:
    """The znodes a registration owns: ``$path/$(hostname)`` plus one per
    alias (aliases are full DNS names, each mapped through domain_to_path;
    reference lib/register.js:217-227)."""
    path = domain_to_path(registration["domain"])
    hostname = hostname or socket.gethostname()
    nodes = [f"{path}/{hostname}" if path != "/" else f"/{hostname}"]
    nodes.extend(domain_to_path(a) for a in registration.get("aliases") or [])
    return nodes


def registration_payloads(
    registration: Mapping[str, Any],
    admin_ip: Optional[str] = None,
    hostname: Optional[str] = None,
):
    """The registration's desired znode set and payload bytes:
    ``(host_paths, host_payload, service_path, service_payload)`` —
    service fields are None when no service is configured.

    The ONE place this is computed: the write pipeline
    (:func:`_register_once`) and the reconciler's desired-state diff
    (:func:`registrar_tpu.reconcile.desired_records`) both call it, so
    the bytes the pipeline writes and the bytes the sweep expects can
    never drift apart (a formula divergence would otherwise surface as
    permanent false ``payload`` drift — and, with repair on, a rewrite
    of the live registration every interval).
    """
    service = registration.get("service")
    service_payload = (
        payload_bytes(service_record(service)) if service is not None else None
    )
    nodes = znode_paths(registration, hostname)
    address = admin_ip if admin_ip else default_address()
    ports = registration.get("ports")
    if ports is None and service is not None:
        ports = [service["service"]["port"]]
    record_payload = payload_bytes(
        host_record(
            registration["type"], address,
            ttl=registration.get("ttl"), ports=ports,
        )
    )
    service_path = (
        domain_to_path(registration["domain"]) if service is not None else None
    )
    return nodes, record_payload, service_path, service_payload


async def _fanout(coros) -> None:
    """Await a stage's parallel ops; a single op (the common host-type
    registration: one znode, one parent) runs inline without the Task +
    gather machinery."""
    coros = list(coros)
    if len(coros) == 1:
        await coros[0]
    elif coros:
        await asyncio.gather(*coros)


async def register(
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str] = None,
    hostname: Optional[str] = None,
    settle_delay: float = SETTLE_DELAY_S,
    retry_policy: Optional[RetryPolicy] = None,
) -> List[str]:
    """Run the five-stage registration pipeline; returns the owned znodes.

    ``registration`` is the config's ``registration`` object (domain, type,
    aliases?, ttl?, ports?, service?).  ``admin_ip`` overrides the
    interface-probe address (reference lib/register.js:141,148 uses
    opts.adminIp the same way).

    ``retry_policy`` opts into the transient-fault retry layer (ISSUE 2):
    a connection blip or per-operation timeout mid-pipeline re-runs the
    whole pipeline from stage 1 (whose cleanup makes re-entry idempotent)
    with the policy's backoff, while session expiry and semantic errors
    (bad config, ACLs) propagate immediately.  Default None = single
    attempt, the reference's behavior.
    """
    _validate_registration(registration)
    if retry_policy is not None:
        return await call_with_backoff(
            lambda: _register_once(
                zk, registration, admin_ip, hostname, settle_delay
            ),
            retry_policy,
            on_backoff=lambda n, delay, err: log.warning(
                "register: transient fault (%r); retrying pipeline in %.2fs "
                "(attempt %d)", err, delay, n + 1,
            ),
            # A closed client surfaces CONNECTION_LOSS too, but nothing
            # will ever reconnect it — an expired session must propagate
            # on the first failure, not after the whole backoff budget.
            retryable=lambda err: not zk.closed and is_transient(err),
        )
    return await _register_once(zk, registration, admin_ip, hostname, settle_delay)


async def _register_once(
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str],
    hostname: Optional[str],
    settle_delay: float,
) -> List[str]:
    """One pass of the five-stage pipeline (validated input)."""
    with trace.tracer_for(zk).span(
        "register.pipeline", domain=registration["domain"]
    ):
        return await _register_stages(
            zk, registration, admin_ip, hostname, settle_delay
        )


async def _register_stages(
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str],
    hostname: Optional[str],
    settle_delay: float,
) -> List[str]:
    nodes, record_payload, path, service_payload = registration_payloads(
        registration, admin_ip, hostname
    )

    log.debug("register: entered (domain=%s nodes=%s)", registration["domain"], nodes)

    # Stage 1: cleanup previous entries (parallel, NO_NODE ignored).
    async def _cleanup(node: str) -> None:
        try:
            await zk.unlink(node)
        except ZKError as err:
            if err.code != Err.NO_NODE:
                raise

    await _fanout(_cleanup(n) for n in nodes)

    # Stage 2: be nice to watchers and wait for them to catch up.
    if settle_delay > 0:
        await asyncio.sleep(settle_delay)

    # Stage 3: parent directories (parallel mkdirp).
    parents = {n.rsplit("/", 1)[0] or "/" for n in nodes}
    await _fanout(zk.mkdirp(p) for p in parents if p != "/")

    # Stage 4: ephemeral host records (parallel).
    await _fanout(zk.create_ephemeral_plus(n, record_payload) for n in nodes)

    # Stage 5: persistent service record at the domain node.
    if service_payload is not None:
        await zk.put(path, service_payload)
        if path not in nodes:
            nodes.append(path)

    log.debug("register: done (znodes=%s)", nodes)
    return nodes


async def unlink_tolerant(zk: ZKClient, path: str) -> str:
    """Delete one znode, absorbing the two benign outcomes every
    deregistration walk shares.  Returns ``"deleted"``, ``"absent"``
    (NO_NODE — already gone, e.g. deleted out-of-band or a replay of a
    half-applied delta), or ``"shared"`` (NOT_EMPTY — a service node
    with sibling hosts' ephemerals still under it, which must survive
    this host's departure).  Any other error propagates.

    The ONE copy of this tolerance (ISSUE 5): the reload delta
    (:mod:`registrar_tpu.agent`), the drain shutdown
    (:mod:`registrar_tpu.main`), and ``zkcli drain`` all walk with it,
    so their deregistration semantics can never drift apart.
    """
    try:
        await zk.unlink(path)
    except ZKError as err:
        if err.code == Err.NO_NODE:
            return "absent"
        if err.code == Err.NOT_EMPTY:
            return "shared"
        raise
    return "deleted"


async def unregister(
    zk: ZKClient, znodes: Sequence[str], atomic: bool = False
) -> List[str]:
    """Delete the owned znodes, sequentially (reference lib/register.js:254-295).

    Returns the nodes actually deleted — callers reporting the outcome
    (e.g. the agent's ``unregister`` event) must not claim a shared
    service node that was left in place.

    Every node is processed before this returns (the reference fires its
    callback after the first delete — fixed, see module docstring).  The
    first error aborts the walk and propagates, matching the reference's
    forEachPipeline semantics — with one deliberate exception: a node that
    fails with NOT_EMPTY is left in place and the walk continues.  The
    owned-node list includes the *persistent* service record at the domain
    node (``register`` appends it, like the reference's registerService);
    in a multi-instance domain — the normal production shape — sibling
    hosts' ephemerals still live under it, so it must survive this host's
    deregistration.  The znode outcome is identical to the reference's
    (ZooKeeper refuses the delete either way; the reference's early-callback
    bug merely hid the error), but here "shared node still in use" is
    success, not failure, so health-driven deregistration in a fleet emits
    ``unregister`` instead of ``error``.

    ``atomic=True`` (beyond the reference's surface) instead deletes all
    nodes in one ZooKeeper multi transaction: observers never see a
    half-deregistered host.  NOT_EMPTY gets the same treatment — the
    transaction is retried without the still-shared nodes (each retry drops
    at least one, so the loop terminates).  Default stays off — the
    sequential walk is the reference's observable behavior.
    """
    if not isinstance(znodes, Sequence) or isinstance(znodes, (str, bytes)):
        raise ValueError("znodes must be a sequence of paths")
    if atomic and znodes:
        log.debug("unregister: atomic delete of %s", list(znodes))
        remaining = list(znodes)
        while remaining:
            try:
                await zk.multi([Op.delete(n) for n in remaining])
                break
            except MultiError as err:
                shared = [
                    n
                    for n, code in zip(remaining, err.results)
                    if code == Err.NOT_EMPTY
                ]
                if not shared:
                    raise
                log.debug(
                    "unregister: %s still shared (children remain); retrying "
                    "without them", shared,
                )
                remaining = [n for n in remaining if n not in shared]
        log.debug("unregister: done")
        return remaining
    deleted: List[str] = []
    for node in znodes:
        log.debug("unregister: deleting %s", node)
        try:
            await zk.unlink(node)
        except ZKError as err:
            if err.code != Err.NOT_EMPTY:
                raise
            log.debug(
                "unregister: %s still has children (shared service node); "
                "left in place", node,
            )
        else:
            deleted.append(node)
    log.debug("unregister: done")
    return deleted
