"""The registrar orchestrator: registration + heartbeat + health checking.

Rebuild of the reference's default export ``register_plus``
(lib/index.js:33-182).  Ties the three subsystems together and exposes a
lifecycle event surface:

    register(znodes)           registration (or re-registration) completed
    unregister(err, znodes)    health check declared down; znodes holds what
                               was actually deleted (a shared service node
                               with sibling hosts under it stays, and is
                               not listed)
    heartbeat(znodes)          periodic znode liveness probe succeeded
    heartbeatFailure(err)      probe failed after bounded retries
    ok()                       health check recovered (was down)
    fail(err)                  health check crossed the failure threshold
    error(err)                 unexpected error from any subsystem

Loop behavior matches the reference exactly (BASELINE.md):

  * heartbeat every ``heartbeat_interval`` (default 3 s,
    lib/index.js:132), re-armed *after* each probe completes (the
    reference's self-rescheduling setTimeout chain, not a fixed-rate timer);
  * after a heartbeat failure the loop backs off to
    ``max(heartbeat_interval, 60 s)`` (lib/index.js:146);
  * a heartbeat failure does NOT deregister or exit — recovery rides on ZK
    session expiry + supervisor restart, or a health-check ``ok``
    re-registration (SURVEY.md §3.2 note).  SURVEY.md §3.2 flags re-creating
    missing ephemerals on heartbeat NO_NODE as a worthwhile but
    behavior-changing improvement: it is available here as the **opt-in**
    ``repair_heartbeat_miss`` flag (config key ``repairHeartbeatMiss``),
    default off for reference parity.  When enabled, a heartbeat that fails
    with NO_NODE re-runs the registration pipeline — unless the health
    checker has deliberately deregistered the host (``ee.down``);
  * on health ``fail`` with ``isDown`` the znodes are unregistered; on the
    next health ``ok`` the full registration pipeline runs again
    (lib/index.js:59-116).

Fixed here (reference warts that are unobservable in znode state):
``register_plus`` references an undefined ``cfg`` on initial-registration
failure (lib/index.js:48) — the error path here just emits ``error``; and
re-registration is guarded against overlapping ``ok`` events.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Mapping, Optional

from registrar_tpu import registration as register_mod
from registrar_tpu.events import EventEmitter, spawn_owned
from registrar_tpu.health import HealthCheck, create_health_check
from registrar_tpu.registration import SETTLE_DELAY_S
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import Err, ZKError

log = logging.getLogger("registrar_tpu.agent")

#: reference lib/index.js:132
DEFAULT_HEARTBEAT_INTERVAL_S = 3.0
#: reference lib/index.js:146 — floor of the post-failure re-arm delay
HEARTBEAT_FAILURE_BACKOFF_S = 60.0


class RegistrarEvents(EventEmitter):
    """Event surface returned by :func:`register_plus` (the reference's
    EventEmitter + ``.stop()``, lib/index.js:164-171)."""

    def __init__(self) -> None:
        super().__init__()
        self.znodes: list = []
        #: True while the health checker holds the host deregistered —
        #: gates heartbeat repair so it never races a deliberate
        #: deregistration.
        self.down = False
        self._tasks: set = set()
        self._health: Optional[HealthCheck] = None
        self._stopped = False

    def stop(self) -> None:
        """Stop the heartbeat loop and health checker.

        Does NOT delete the znodes — like the reference, a graceful library
        stop leaves cleanup to ZK session expiry (SURVEY.md §3.4)."""
        self._stopped = True
        if self._health is not None:
            self._health.stop()
        for task in list(self._tasks):
            task.cancel()
        self._tasks.clear()

    def _track(self, coro) -> "asyncio.Task":
        """Spawn ``coro`` as a task owned until done (finished tasks drop
        out, so a daemon with a flapping health check doesn't accumulate
        them forever) and cancelled by stop()."""
        return spawn_owned(coro, self._tasks)

    @property
    def stopped(self) -> bool:
        return self._stopped


def register_plus(
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str] = None,
    health_check: Optional[Mapping[str, Any]] = None,
    heartbeat_interval: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    hostname: Optional[str] = None,
    settle_delay: float = SETTLE_DELAY_S,
    heartbeat_retry: Optional[RetryPolicy] = None,
    repair_heartbeat_miss: bool = False,
    register_retry: Optional[RetryPolicy] = None,
) -> RegistrarEvents:
    """Register, then keep the registration alive; returns the event surface.

    Must be called with a running event loop (the daemon's mainline or a
    test harness).  ``health_check`` is the config's ``healthCheck`` object
    (seconds-based keys, see :mod:`registrar_tpu.config` for translation).
    ``heartbeat_retry`` overrides the per-probe retry policy (configured
    from the sample config's ``maxAttempts``, see config.py).
    ``repair_heartbeat_miss`` opts into re-registering when a heartbeat
    finds the znodes gone (module docstring; default off = reference
    behavior).  ``register_retry`` opts the registration pipeline (initial
    and every re-registration) into the transient-fault retry layer
    (:data:`registrar_tpu.registration.REGISTER_RETRY` is the shipped
    policy); default None = single attempt, reference behavior.
    """
    ee = RegistrarEvents()
    ee._track(_run(ee, zk, registration, admin_ip,
                   health_check, heartbeat_interval,
                   hostname, settle_delay,
                   heartbeat_retry,
                   repair_heartbeat_miss,
                   register_retry))
    return ee


async def _run(
    ee: RegistrarEvents,
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str],
    health_check: Optional[Mapping[str, Any]],
    heartbeat_interval: float,
    hostname: Optional[str],
    settle_delay: float,
    heartbeat_retry: Optional[RetryPolicy] = None,
    repair_heartbeat_miss: bool = False,
    register_retry: Optional[RetryPolicy] = None,
) -> None:
    async def do_register() -> list:
        """The one registration pipeline call every path shares."""
        return await register_mod.register(
            zk, registration, admin_ip=admin_ip, hostname=hostname,
            settle_delay=settle_delay, retry_policy=register_retry,
        )

    try:
        znodes = await do_register()
    except asyncio.CancelledError:
        raise
    except Exception as err:  # noqa: BLE001
        log.debug("registration failed: %r", err)
        ee.emit("error", err)
        return

    ee.znodes = znodes
    if ee.stopped:
        return

    ee._track(_heartbeat_loop(
        ee, zk, heartbeat_interval, heartbeat_retry,
        do_register if repair_heartbeat_miss else None,
    ))
    if health_check:
        _start_health_consumer(ee, zk, do_register, health_check)
    ee.emit("register", znodes)


async def _heartbeat_loop(
    ee: RegistrarEvents,
    zk: ZKClient,
    interval: float,
    retry: Optional[RetryPolicy] = None,
    repair=None,
) -> None:
    """Hot loop #1 (SURVEY.md §3.2): self-rescheduling znode liveness probe.

    ``repair``: optional coroutine factory re-running the registration
    pipeline; invoked when a probe fails with NO_NODE (znodes vanished
    without our session expiring — e.g. an operator deleted them, or a
    reattach raced a cleanup) unless the health checker holds the host
    down.  None = reference behavior: failures only back off.
    """
    while not ee.stopped:
        try:
            await zk.heartbeat(ee.znodes, retry=retry)
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001
            log.debug("zk.heartbeat(%s) failed: %r", ee.znodes, err)
            ee.emit("heartbeatFailure", err)
            if (
                repair is not None
                and not ee.down
                and not ee.stopped
                and isinstance(err, ZKError)
                and err.code == Err.NO_NODE
                and await _confirm_nodes_missing(zk, ee)
            ):
                try:
                    new_znodes = await repair()
                except asyncio.CancelledError:
                    raise
                except Exception as r_err:  # noqa: BLE001
                    log.debug("heartbeat repair failed: %r", r_err)
                    ee.emit("error", r_err)
                else:
                    if ee.down or ee.stopped:
                        # The health checker crossed its threshold while the
                        # repair's pipeline (1 s settle + RPCs) was in
                        # flight: honor the deregistration — roll the fresh
                        # znodes back out rather than resurrecting a host
                        # health just declared down.
                        log.debug(
                            "heartbeat repair rolled back (health down)"
                        )
                        try:
                            await register_mod.unregister(zk, new_znodes)
                        except Exception as u_err:  # noqa: BLE001
                            ee.emit("error", u_err)
                    else:
                        ee.znodes = new_znodes
                        log.debug(
                            "heartbeat repair re-registered %s", ee.znodes
                        )
                        ee.emit("register", ee.znodes)
                        await asyncio.sleep(interval)
                        continue
            await asyncio.sleep(max(interval, HEARTBEAT_FAILURE_BACKOFF_S))
            continue
        log.debug("zk.heartbeat(%s): ok", ee.znodes)
        ee.emit("heartbeat", ee.znodes)
        await asyncio.sleep(interval)


async def _confirm_nodes_missing(zk: ZKClient, ee: RegistrarEvents) -> bool:
    """One fresh single-attempt probe before the repair pipeline runs.

    A NO_NODE from the probe retry chain can be a *transient* artifact —
    a stale read served by a lagging follower just before catch-up, or a
    probe raced with a session reattach — and the repair pipeline is not
    free: its cleanup stage deletes and re-creates the live znodes, a
    real (if brief) deregistration observable by Binder.  Repair only
    proceeds when a second, immediate probe confirms the znodes are
    really gone; any other outcome (probe passes, or fails for transient
    reasons like CONNECTION_LOSS) falls back to the reference's plain
    failure backoff.
    """
    try:
        await zk.heartbeat(ee.znodes, retry=RetryPolicy(max_attempts=1))
    except asyncio.CancelledError:
        raise
    except ZKError as err:
        return err.code == Err.NO_NODE
    except Exception:  # noqa: BLE001 - transient/unknown: do not repair
        return False
    return False


def _start_health_consumer(
    ee: RegistrarEvents,
    zk: ZKClient,
    do_register,
    health_check: Mapping[str, Any],
) -> None:
    """Hot loop #2 (SURVEY.md §3.3): health stream -> deregister/re-register."""
    check = create_health_check(**health_check)
    ee._health = check
    transitioning = False

    async def on_fail(err: Exception) -> None:
        nonlocal transitioning
        ee.down = True
        transitioning = True
        try:
            log.debug("healthcheck failed, deregistering (znodes=%s)", ee.znodes)
            ee.emit("fail", err)
            try:
                deleted = await register_mod.unregister(zk, ee.znodes)
            except Exception as u_err:  # noqa: BLE001
                log.debug("healthcheck: unregister failed: %r", u_err)
                ee.emit("error", u_err)
            else:
                ee.emit("unregister", err, deleted)
        finally:
            transitioning = False

    async def on_recover() -> None:
        nonlocal transitioning
        transitioning = True
        try:
            ee.emit("ok")
            try:
                znodes = await do_register()
            except Exception as r_err:  # noqa: BLE001
                log.debug("register: reregister failed: %r", r_err)
                ee.emit("error", r_err)
            else:
                ee.znodes = znodes
                ee.down = False
                ee.emit("register", znodes)
        finally:
            transitioning = False

    def on_data(record: Mapping[str, Any]) -> None:
        if ee.stopped or transitioning:
            # Mirrors the reference's implicit single-flight behavior: its
            # `down` flag only flips after the async transition completes.
            return
        rtype = record.get("type")
        if rtype == "ok":
            if ee.down:
                ee._track(on_recover())
        elif rtype == "fail":
            if (
                record.get("err") is not None
                and record.get("isDown")
                and not ee.down
            ):
                ee._track(on_fail(record["err"]))
        else:
            ee.emit("error", ValueError(f"unknown check type: {rtype!r}"))

    check.on("data", on_data)
    check.on("error", lambda err: ee.emit("error", err))
    check.start()
